"""SLO-driven per-role-group autoscaling + crash-safe desired state.

The control loop (ISSUE 17) closes the signal -> decision -> actuation
pipeline over signals the stack already exports:

- **signal**: the controller's health pass piggybacks each replica's
  ``get_metrics()`` (engine ``queue_depth`` / ``active_slots`` /
  ``slots``, replica ``ongoing``) into this module's signal book, and
  every router reports its blocked-admission ``pending`` count when it
  refreshes membership — the scale-from-zero demand signal, since a
  zero-replica group has no replica to report anything.
- **decision**: :func:`decide` turns one group's aggregated signals
  into a bounded target — EMA-smoothed load (see
  ``_private.metrics.EMA``), hysteresis dead-band, stability delays,
  per-direction cooldowns, capped step sizes. Stale or missing signals
  (a replica that missed its health pass) degrade to a conservative
  hold; a scale-from-zero stamps a cold-start grace window so the
  burst that queued behind the compiling replica doesn't panic-scale.
- **actuation**: the controller applies the returned targets through
  its existing reconcile machinery, so scale-down always routes
  through the graceful drain path (never kills an in-flight stream).

Role groups decide independently: prefill replicas track admission
backlog (burst arrival), decode replicas track slot occupancy and the
TPOT p95 SLO, each under its own :meth:`AutoscalingConfig.for_role`
view.

Crash safety lives in :class:`DesiredStateJournal`: desired targets and
replica intents are written ahead to the cluster KV store (head-side,
WAL-persisted — it survives a SIGKILLed controller), replicas are
named/detached actors, and a restarted controller adopts the journaled
fleet instead of double-scaling or orphaning it.
"""
from __future__ import annotations

import collections
import json
import math
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

from .._private import events as _events
from .._private.metrics import EMA, serve_metrics
from .config import AutoscalingConfig

#: How many past ``decide()`` outcomes each role group remembers, with
#: their full signal snapshots — surfaced through ``serve.status()``
#: (ISSUE 19 satellite: the counters-only view from PR 17 could say a
#: hold happened but never WHY).
DECISION_RING_N = 32

#: Cluster-KV namespace shared with the declarative config plane.
KV_NS = "serve"
_APP_PREFIX = "journal/app/"
_DESIRED_PREFIX = "journal/desired/"
_REPLICA_PREFIX = "journal/replicas/"

#: Plain (non-disaggregated) deployments decide as one group under
#: this key; role-split deployments use their role names.
PLAIN_GROUP = "all"


def replica_actor_name(app_name: str, rid: str) -> str:
    """Cluster-wide name of a replica actor. Named actors are DETACHED
    in this runtime (they survive their creator), which is exactly what
    lets a restarted controller adopt the fleet instead of the old
    unnamed replicas being garbage-collected mid-stream."""
    return f"SERVE_REPLICA:{app_name}:{rid}"


# ---------------------------------------------------------------- journal
class DesiredStateJournal:
    """Write-ahead desired-state journal in the cluster KV store.

    Three keys per application, all full-document overwrites (one
    head-side op each, so a crash can only ever lose the newest write,
    never corrupt the document):

    - ``journal/app/{app}``: cloudpickled app spec (payloads +
      configs) — enough to rebuild controller state from nothing;
    - ``journal/desired/{app}``: JSON ``{dname: {"target", "role_targets"}}``;
    - ``journal/replicas/{app}``: JSON ``{dname: {rid: {"role",
      "state": starting|live|condemned, "t"}}}`` — intents are written
      BEFORE the actor create / drain they describe, so every replica
      that can possibly exist has a journal entry to reconcile against.
    """

    @staticmethod
    def _kv():
        from ..core.worker import CoreWorker

        return CoreWorker.current()

    # -- app spec ------------------------------------------------------
    def put_app(self, app: str, spec_blob: dict):
        import cloudpickle

        self._kv().kv_put(_APP_PREFIX + app, cloudpickle.dumps(spec_blob),
                          ns=KV_NS)

    def get_app(self, app: str) -> Optional[dict]:
        import cloudpickle

        raw = self._kv().kv_get(_APP_PREFIX + app, ns=KV_NS)
        return cloudpickle.loads(raw) if raw else None

    def list_apps(self) -> List[str]:
        keys = self._kv().kv_keys(_APP_PREFIX, ns=KV_NS)
        return sorted(k[len(_APP_PREFIX):] for k in keys)

    def del_app(self, app: str):
        kv = self._kv()
        for prefix in (_APP_PREFIX, _DESIRED_PREFIX, _REPLICA_PREFIX):
            try:
                kv.kv_del(prefix + app, ns=KV_NS)
            except Exception:  # noqa: BLE001 - absent key; nothing to clear
                pass

    # -- desired targets ----------------------------------------------
    def put_desired(self, app: str, desired: Dict[str, dict]):
        self._kv().kv_put(_DESIRED_PREFIX + app,
                          json.dumps(desired).encode(), ns=KV_NS)

    def get_desired(self, app: str) -> Dict[str, dict]:
        raw = self._kv().kv_get(_DESIRED_PREFIX + app, ns=KV_NS)
        return json.loads(raw) if raw else {}

    # -- replica intents ----------------------------------------------
    def put_replicas(self, app: str, intents: Dict[str, dict]):
        self._kv().kv_put(_REPLICA_PREFIX + app,
                          json.dumps(intents).encode(), ns=KV_NS)

    def get_replicas(self, app: str) -> Dict[str, dict]:
        raw = self._kv().kv_get(_REPLICA_PREFIX + app, ns=KV_NS)
        return json.loads(raw) if raw else {}


# ----------------------------------------------------------------- signals
@dataclass
class GroupSignals:
    """One role group's aggregated signal snapshot, as :func:`decide`
    consumes it. ``fresh`` counts members whose newest signal is within
    the config's staleness window; ``newest_age`` is the age of the
    freshest signal in the group (``inf`` when none exists)."""

    n: int = 0
    fresh: int = 0
    ongoing: float = 0.0
    queue_depth: float = 0.0
    active_slots: float = 0.0
    slots: float = 0.0
    newest_age: float = math.inf
    pending: float = 0.0
    tpot_p95: Optional[float] = None


@dataclass
class Decision:
    target: int
    direction: str  # "up" | "down" | "hold"
    reason: str


class GroupState:
    """Per-group decision memory: the EMA of the load ratio, the
    stability window, cooldown stamps, the idle clock for
    scale-to-zero, and the cold-start grace deadline."""

    def __init__(self, tau_s: float):
        self.ema = EMA(tau_s)
        self.desired: Optional[int] = None
        self.since = 0.0
        self.last_up = -math.inf
        self.last_down = -math.inf
        self.idle_since: Optional[float] = None
        self.cold_until = 0.0
        self.last_decision: Optional[dict] = None
        #: Last-N decisions WITH their signal snapshots (newest last).
        self.decisions: "collections.deque[dict]" = \
            collections.deque(maxlen=DECISION_RING_N)


def _load_mode(cfg: AutoscalingConfig,
               sig: GroupSignals) -> tuple:
    """(load, per_replica_capacity, mode): the group's demand in the
    unit its config targets, and how much of it one replica absorbs."""
    if cfg.target_occupancy is not None and sig.slots > 0:
        per = cfg.target_occupancy * (sig.slots / max(sig.n, 1))
        # Waiting work needs slots just as much as admitted work.
        return sig.active_slots + sig.queue_depth, per, "occupancy"
    if cfg.target_queue_depth is not None:
        return (sig.queue_depth + sig.pending,
                max(cfg.target_queue_depth, 1e-9), "queue_depth")
    return (sig.ongoing + sig.pending,
            max(cfg.target_ongoing_requests, 1e-9), "ongoing")


def decide(cfg: AutoscalingConfig, cur: int, sig: GroupSignals,
           st: GroupState, now: float) -> Decision:
    """One bounded scaling decision for one role group.

    Pure up to ``st`` (its decision memory); no I/O, no clock reads —
    unit-testable tick by tick. The ordering below IS the degradation
    contract: freshness gates everything (a missed health pass can
    only ever hold), the cold-start grace gates upscale, stability and
    cooldown gate both directions, and the step cap bounds whatever
    survives.
    """
    # Scale-from-zero: no replica exists to report a signal, so router
    # pending demand is the only input. Bypasses the stability delay
    # (the burst is already queued) and stamps the cold-start grace.
    if cur == 0:
        if cfg.min_replicas > 0:
            return Decision(cfg.min_replicas, "up", "min_replicas")
        if sig.pending > 0:
            st.cold_until = now + cfg.cold_start_grace_s
            st.ema.reset()
            st.desired = None
            st.idle_since = None
            _, per, _ = _load_mode(cfg, sig)
            want = math.ceil(sig.pending / max(per, 1e-9))
            target = max(1, min(cfg.max_replicas, cfg.upscale_step, want))
            st.last_up = now
            return Decision(target, "up", "scale_from_zero")
        return Decision(0, "hold", "idle")

    # Freshness gate: a group whose signals all rotted holds outright;
    # one member missing its health pass also holds (conservative — we
    # cannot tell an idle replica from a wedged probe).
    if sig.n > 0 and sig.fresh == 0:
        return Decision(cur, "hold", "stale_signal")
    if sig.fresh < sig.n:
        return Decision(cur, "hold", "missing_signal")

    load, per, mode = _load_mode(cfg, sig)
    smoothed = st.ema.update(load / per, now)

    # Latency SLO overlay: a breached TPOT p95 forces at least one
    # replica of upscale pressure no matter what occupancy says.
    if cfg.tpot_slo_s is not None and sig.tpot_p95 is not None \
            and sig.tpot_p95 > cfg.tpot_slo_s:
        smoothed = max(smoothed, cur + 1)
        mode = "slo"

    # Hysteresis dead-band around the current size, then clamp.
    if abs(smoothed - cur) <= cfg.hysteresis * max(cur, 1):
        desired = cur
    else:
        desired = math.ceil(smoothed)
    desired = max(cfg.min_replicas, min(cfg.max_replicas, desired))

    # Idle clock for scale-to-zero (explicit opt-in; without it a
    # zero-min group still floors at one live replica).
    if load <= 0 and sig.pending <= 0:
        if st.idle_since is None:
            st.idle_since = now
    else:
        st.idle_since = None
    if desired == 0:
        idle_ok = (cfg.scale_to_zero_idle_s is not None
                   and st.idle_since is not None
                   and now - st.idle_since >= cfg.scale_to_zero_idle_s)
        if not idle_ok:
            desired = 1
            if cur == 1:
                return Decision(cur, "hold", "idle_wait")
        else:
            mode = "scale_to_zero"

    if desired == cur:
        st.desired = None
        return Decision(cur, "hold", "steady")

    if desired > cur and now < st.cold_until:
        return Decision(cur, "hold", "cold_start")

    # Stability window: the desired size must survive unchanged for
    # the direction's delay before it actuates (flap damping).
    if st.desired != desired:
        st.desired = desired
        st.since = now
        return Decision(cur, "hold", "stabilizing")
    delay = cfg.upscale_delay_s if desired > cur else cfg.downscale_delay_s
    if now - st.since < delay:
        return Decision(cur, "hold", "stabilizing")

    if desired > cur and now - st.last_up < cfg.upscale_cooldown_s:
        return Decision(cur, "hold", "cooldown")
    if desired < cur and now - st.last_down < cfg.downscale_cooldown_s:
        return Decision(cur, "hold", "cooldown")

    if desired > cur:
        target = min(desired, cur + cfg.upscale_step)
        st.last_up = now
        direction = "up"
    else:
        target = max(desired, cur - cfg.downscale_step)
        st.last_down = now
        direction = "down"
    st.desired = None
    return Decision(target, direction, mode)


# -------------------------------------------------------------- autoscaler
class Autoscaler:
    """Signal book + per-group decision state for one controller.

    ``record``/``prune`` run on the controller's reconcile thread (the
    health pass feeds them); ``note_pending`` runs on RPC threads (the
    routers' membership refresh carries it) — the book lock covers
    both. ``tick`` is reconcile-thread only: it snapshots the book,
    decides every group, and returns the targets to actuate.
    """

    def __init__(self):
        self._lock = threading.Lock()
        # (app, dname) -> rid -> {"t", "role", "ongoing", "queue_depth",
        #                          "active_slots", "slots", "draining"}
        self._signals: Dict[tuple, Dict[str, dict]] = {}
        # (app, dname) -> router_id -> (pending, t)
        self._pending: Dict[tuple, Dict[str, tuple]] = {}
        # (app, dname, group) -> GroupState
        self._states: Dict[tuple, GroupState] = {}

    # ------------------------------------------------------------ intake
    def record(self, app: str, dname: str, rid: str, metrics: dict,
               now: float):
        """Fold one replica's health-pass ``get_metrics()`` payload
        into the signal book."""
        sig = {"t": now,
               "role": None,
               "ongoing": float(metrics.get("ongoing", 0) or 0),
               "queue_depth": 0.0, "active_slots": 0.0, "slots": 0.0,
               "draining": bool(metrics.get("draining"))}
        for est in metrics.get("engines") or []:
            sig["queue_depth"] += float(est.get("queue_depth", 0) or 0)
            sig["active_slots"] += float(est.get("active_slots", 0) or 0)
            sig["slots"] += float(est.get("slots", 0) or 0)
            if est.get("role"):
                sig["role"] = est["role"]
        with self._lock:
            self._signals.setdefault((app, dname), {})[rid] = sig

    def note_pending(self, app: str, dname: str, router_id: str,
                     pending: int, now: float):
        """A router reported its blocked-admission queue depth on a
        membership refresh — the demand signal that exists even when
        the group has zero replicas."""
        with self._lock:
            book = self._pending.setdefault((app, dname), {})
            book[router_id] = (int(pending), now)

    def prune(self, app: str, dname: str, live_rids, now: float,
              staleness_s: float = 30.0):
        """Drop signal entries for replicas the controller no longer
        lists (satellite: the book must not accrete ghosts) and
        pending reports from routers that went quiet."""
        with self._lock:
            sigs = self._signals.get((app, dname))
            if sigs is not None:
                for rid in list(sigs):
                    if rid not in live_rids:
                        sigs.pop(rid, None)
            pend = self._pending.get((app, dname))
            if pend is not None:
                for router_id, (_, t) in list(pend.items()):
                    if now - t > staleness_s:
                        pend.pop(router_id, None)

    def forget(self, app: str, dname: Optional[str] = None):
        """Deployment (or whole app) torn down: drop its book and
        decision state so a later same-name deploy starts cold."""
        with self._lock:
            for key in list(self._signals):
                if key[0] == app and (dname is None or key[1] == dname):
                    self._signals.pop(key, None)
                    self._pending.pop(key, None)
            for key in list(self._states):
                if key[0] == app and (dname is None or key[1] == dname):
                    self._states.pop(key, None)

    # ----------------------------------------------------------- querying
    def signal_ages(self, app: str, dname: str, groups: Dict[str, list],
                    now: float) -> Dict[str, Optional[float]]:
        """Freshest signal age per role group (``None`` when the group
        has no signal at all) — surfaced as ``signal_age_s`` in
        ``serve.status()`` so a held decision is diagnosable."""
        with self._lock:
            sigs = dict(self._signals.get((app, dname)) or {})
        out: Dict[str, Optional[float]] = {}
        for group, rids in groups.items():
            ages = [now - sigs[rid]["t"] for rid in rids if rid in sigs]
            out[group] = round(min(ages), 3) if ages else None
        return out

    def pending_total(self, app: str, dname: str, now: float,
                      window_s: float = 5.0) -> int:
        with self._lock:
            pend = self._pending.get((app, dname)) or {}
            return sum(p for p, t in pend.values() if now - t <= window_s)

    def last_decisions(self, app: str, dname: str) -> Dict[str, dict]:
        """Per-group decision view for ``serve.status()``: the latest
        decision's fields at the top level (back-compat with the
        counters-era shape) plus ``ring`` — the last-N ``decide()``
        outcomes with their full signal snapshots, newest last."""
        with self._lock:
            out = {}
            for (a, d, group), st in self._states.items():
                if a == app and d == dname and st.last_decision:
                    entry = dict(st.last_decision)
                    entry["ring"] = [dict(e) for e in st.decisions]
                    out[group] = entry
            return out

    # ------------------------------------------------------------- decide
    # rtlint: entry=driver
    def tick(self, app: str, dname: str, ac: AutoscalingConfig,
             groups: Dict[str, dict], now: float,
             tpot_p95: Optional[float] = None) -> Dict[str, Decision]:
        """Decide every role group of one deployment.

        ``groups`` maps group name (:data:`PLAIN_GROUP` or a role) to
        ``{"cur": int, "rids": [...]}`` — the controller's view of the
        group's current target and membership. Returns the full
        decision map; the caller actuates ``direction != "hold"``
        entries through its drain-aware reconcile machinery.
        """
        with self._lock:
            sigs = dict(self._signals.get((app, dname)) or {})
        pending = self.pending_total(app, dname, now)
        decisions: Dict[str, Decision] = {}
        for group, info in groups.items():
            cfg = ac.for_role(None if group == PLAIN_GROUP else group)
            key = (app, dname, group)
            with self._lock:
                st = self._states.get(key)
                if st is None:
                    st = self._states[key] = GroupState(cfg.ema_tau_s)
            sig = self._aggregate(cfg, info["rids"], sigs, pending, now)
            sig.tpot_p95 = tpot_p95
            d = self._decide_group(cfg, int(info["cur"]), sig, st, now)
            st.last_decision = {"target": d.target,
                                "direction": d.direction,
                                "reason": d.reason, "t": now}
            # Decision-ring entry: the decision PLUS everything it was
            # decided from, so a held/odd scaling call is explainable
            # after the fact without replaying the controller.
            _, _, mode = _load_mode(cfg, sig)
            snapshot = {
                "queue_depth": sig.queue_depth,
                "ongoing": sig.ongoing,
                "active_slots": sig.active_slots,
                "slots": sig.slots,
                "occupancy": (sig.active_slots / sig.slots
                              if sig.slots else 0.0),
                "pending": sig.pending, "n": sig.n,
                "fresh": sig.fresh,
                "newest_age": (round(sig.newest_age, 3)
                               if math.isfinite(sig.newest_age)
                               else None),
                "tpot_p95": sig.tpot_p95,
            }
            with self._lock:
                st.decisions.append({
                    **st.last_decision, "cur": int(info["cur"]),
                    "mode": mode, "ema": st.ema.value,
                    "signals": snapshot})
            _events.emit("autoscale.decide", deployment=dname,
                         group=group, target=d.target,
                         direction=d.direction, reason=d.reason,
                         cur=int(info["cur"]), mode=mode,
                         ema=st.ema.value, **snapshot)
            self._observe(dname, group, d)
            decisions[group] = d
        return decisions

    # rtlint: owner=driver
    def _decide_group(self, cfg: AutoscalingConfig, cur: int,
                      sig: GroupSignals, st: GroupState,
                      now: float) -> Decision:
        return decide(cfg, cur, sig, st, now)

    @staticmethod
    def _aggregate(cfg: AutoscalingConfig, rids, sigs: dict,
                   pending: int, now: float) -> GroupSignals:
        out = GroupSignals(pending=float(pending))
        for rid in rids:
            s = sigs.get(rid)
            if s is not None and s.get("draining"):
                continue
            out.n += 1
            if s is None:
                continue
            age = now - s["t"]
            out.newest_age = min(out.newest_age, age)
            if age <= cfg.signal_staleness_s:
                out.fresh += 1
                out.ongoing += s["ongoing"]
                out.queue_depth += s["queue_depth"]
                out.active_slots += s["active_slots"]
                out.slots += s["slots"]
        return out

    @staticmethod
    def _observe(dname: str, group: str, d: Decision):
        sm = serve_metrics()
        if d.direction in ("up", "down"):
            sm["autoscale_decisions"].inc(labels={
                "deployment": dname, "group": group,
                "direction": d.direction})
        elif d.reason not in ("steady", "idle"):
            sm["autoscale_held"].inc(labels={
                "deployment": dname, "group": group,
                "reason": d.reason})
