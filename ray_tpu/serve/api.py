"""Serve public API: ``@deployment``, ``bind``, ``run``, handles, lifecycle.

Capability parity with the reference's ``ray.serve.api``
(reference: ``python/ray/serve/api.py:248`` ``deployment``, ``:545`` ``run``,
``:66`` ``start``, ``:120`` ``shutdown``, ``:780`` ``status``, ``:808`` /
``:844`` handle getters; ``serve/deployment.py`` ``Deployment`` /
``Application``). The deployment graph is serialized per-deployment with
bound sub-applications replaced by handle markers, resolved back into live
``DeploymentHandle``s at replica init.
"""
from __future__ import annotations

import threading
import time
from dataclasses import replace
from typing import Any, Callable, Dict, List, Optional, Union

import cloudpickle

from .. import api as rt
from ..exceptions import RayTpuError
from .config import (DEFAULT_APP_NAME, SERVE_CONTROLLER_NAME,
                     AutoscalingConfig, DeploymentConfig, HTTPOptions,
                     gRPCOptions)
from .handle import DeploymentHandle, _HandleMarker, reset_routers

_client_lock = threading.Lock()
_client: Dict[str, Any] = {"controller": None, "proxy": None, "http": None}
#: Single-flight bootstrap gate (rtsan RS104 real finding, ISSUE 13):
#: start() used to hold _client_lock across the WHOLE control-plane
#: bootstrap — controller creation, 60 s proxy RPCs, and get_actor's
#: retry-sleep loop — so a concurrent status()/_controller()/shutdown()
#: stalled behind a full bootstrap instead of its own short timeout.
#: Now _client_lock only ever guards the state dict; the slow work runs
#: outside it, serialized by this leader Event (followers wait, then
#: re-run the now-fast idempotent body).
_boot: Dict[str, Any] = {"ev": None}


def _boot_enter() -> "threading.Event":
    """Become the bootstrap leader, waiting out any in-flight one.
    Callers MUST pair with :func:`_boot_exit` (try/finally)."""
    while True:
        with _client_lock:
            ev = _boot["ev"]
            if ev is None:
                ev = _boot["ev"] = threading.Event()
                return ev
        # Bounded: the leader's finally publishes and clears; on the
        # pathological timeout we loop and re-contend.
        ev.wait(timeout=120)


def _boot_exit(ev: "threading.Event"):
    with _client_lock:
        if _boot["ev"] is ev:
            _boot["ev"] = None
    ev.set()


class Deployment:
    """A configured-but-unbound deployment (user class/function + config)."""

    def __init__(self, func_or_class: Callable, name: str,
                 config: DeploymentConfig):
        self.func_or_class = func_or_class
        self.name = name
        self.config = config

    def options(self, *, name: Optional[str] = None,
                num_replicas: Optional[int] = None,
                max_ongoing_requests: Optional[int] = None,
                max_queued_requests: Optional[int] = None,
                autoscaling_config: Union[None, dict,
                                          AutoscalingConfig] = None,
                user_config: Any = None,
                health_check_period_s: Optional[float] = None,
                graceful_shutdown_timeout_s: Optional[float] = None,
                ray_actor_options: Optional[dict] = None,
                engine_config: Optional[dict] = None) -> "Deployment":
        cfg = self.config
        updates: Dict[str, Any] = {}
        if num_replicas is not None:
            updates["num_replicas"] = num_replicas
        if max_ongoing_requests is not None:
            updates["max_ongoing_requests"] = max_ongoing_requests
        if max_queued_requests is not None:
            updates["max_queued_requests"] = max_queued_requests
        if autoscaling_config is not None:
            if isinstance(autoscaling_config, dict):
                autoscaling_config = AutoscalingConfig(**autoscaling_config)
            updates["autoscaling_config"] = autoscaling_config
        if user_config is not None:
            updates["user_config"] = user_config
        if health_check_period_s is not None:
            updates["health_check_period_s"] = health_check_period_s
        if graceful_shutdown_timeout_s is not None:
            updates["graceful_shutdown_timeout_s"] = graceful_shutdown_timeout_s
        if ray_actor_options is not None:
            updates["ray_actor_options"] = ray_actor_options
        if engine_config is not None:
            updates["engine_config"] = dict(engine_config)
        return Deployment(self.func_or_class, name or self.name,
                          replace(cfg, **updates))

    def bind(self, *args, **kwargs) -> "Application":
        return Application(self, args, kwargs)

    def __repr__(self):
        return f"Deployment({self.name!r})"


class Application:
    """A bound deployment graph; the root is the app's ingress."""

    def __init__(self, deployment: Deployment, args: tuple, kwargs: dict):
        self.deployment = deployment
        self.args = args
        self.kwargs = kwargs


def deployment(_func_or_class: Optional[Callable] = None, *,
               name: Optional[str] = None,
               num_replicas: Union[int, str, None] = None,
               max_ongoing_requests: Optional[int] = None,
               max_queued_requests: Optional[int] = None,
               autoscaling_config: Union[None, dict,
                                         AutoscalingConfig] = None,
               user_config: Any = None,
               health_check_period_s: Optional[float] = None,
               graceful_shutdown_timeout_s: Optional[float] = None,
               ray_actor_options: Optional[dict] = None,
               engine_config: Optional[dict] = None):
    """``@serve.deployment`` decorator (reference: ``serve/api.py:248``).

    ``num_replicas="auto"`` enables autoscaling with default bounds, like the
    reference's ``handle_num_replicas_auto``.

    **Request lifecycle** (deadline → budgeted retry → shed):

    - Every request is stamped with an absolute deadline at the edge
      (HTTP proxy: ``request_timeout_s``; handles:
      ``handle.options(timeout_s=...)``, default 60 s) and carries it
      proxy → router → replica → batcher. A replica drops an
      already-expired request before invoking user code and the batcher
      drops expired entries at flush time, so no device cycles are spent
      on answers nobody is waiting for; callers see
      ``RequestDeadlineExceeded`` (HTTP ``504``). User code can read its
      remaining budget via ``serve.get_request_deadline()``.
    - ``DeploymentResponse.result()`` retries replica death with
      exponential backoff + jitter, deducting elapsed time (a retry
      never restarts the window), and spends a per-router **retry
      budget** (token bucket fed ~10% of successes plus a small
      reserve) so a dying deployment can't amplify its own load with a
      retry storm. Streaming calls transparently re-route as long as no
      item has been delivered. When the budget or attempts are
      exhausted, the ORIGINAL error raises.
    - ``max_ongoing_requests`` is enforced on the replica itself: a
      saturated replica answers with a typed overload pushback and the
      router re-picks another replica without marking it dead. Once
      every replica is saturated and ``max_queued_requests`` callers
      are already queued, submissions shed with ``BackPressureError`` —
      the HTTP proxy maps it to ``503`` with a ``Retry-After`` header
      (the client contract: back off at least that many seconds), gRPC
      to ``RESOURCE_EXHAUSTED``. Shed/expired/retry counters are
      exported via ``_private.metrics`` and ``serve.status()``.
    """

    def decorate(obj):
        cfg = DeploymentConfig()
        nr = num_replicas
        asc = autoscaling_config
        if nr == "auto":
            nr = None
            if asc is None:
                asc = AutoscalingConfig(min_replicas=1, max_replicas=10)
        if isinstance(asc, dict):
            asc = AutoscalingConfig(**asc)
        if nr is not None:
            cfg.num_replicas = int(nr)
        if max_ongoing_requests is not None:
            cfg.max_ongoing_requests = max_ongoing_requests
        if max_queued_requests is not None:
            cfg.max_queued_requests = max_queued_requests
        cfg.autoscaling_config = asc
        if user_config is not None:
            cfg.user_config = user_config
        if health_check_period_s is not None:
            cfg.health_check_period_s = health_check_period_s
        if graceful_shutdown_timeout_s is not None:
            cfg.graceful_shutdown_timeout_s = graceful_shutdown_timeout_s
        if ray_actor_options is not None:
            cfg.ray_actor_options = dict(ray_actor_options)
        if engine_config is not None:
            # Decode-engine block (paged-KV / spec-decode knobs, and
            # the ISSUE 14 disaggregation ``roles:`` group sizing) —
            # the decorator twin of the schema's ``engine:`` block.
            cfg.engine_config = dict(engine_config)
        return Deployment(obj, name or obj.__name__, cfg)

    if _func_or_class is not None and callable(_func_or_class):
        return decorate(_func_or_class)
    return decorate


# ------------------------------------------------------------------ lifecycle
def start(http_options: Union[None, dict, HTTPOptions] = None,
          proxy: bool = True,
          grpc_options: Union[None, dict, gRPCOptions] = None):
    """Start the Serve control plane: controller + optional HTTP proxy,
    plus a gRPC ingress on the same proxy actor when ``grpc_options``
    is given (reference: ``proxy.py`` HTTPProxy + gRPCProxy)."""
    if not rt.is_initialized():
        rt.init()
    if isinstance(http_options, dict):
        http_options = HTTPOptions(**http_options)
    http_options = http_options or HTTPOptions()
    if isinstance(grpc_options, dict):
        grpc_options = gRPCOptions(**grpc_options)
    # Bootstrap runs OUTSIDE _client_lock (single-flighted by the boot
    # gate): the RPCs below block for up to 60 s and get_actor retries
    # with sleeps — holding the state lock across them starved every
    # other serve entry point (rtsan RS104 real finding).
    ev = _boot_enter()
    try:
        with _client_lock:
            ctrl = _client["controller"]
        if ctrl is None:
            ctrl = _get_or_create_controller()
            with _client_lock:
                _client["controller"] = ctrl
        with _client_lock:
            need_proxy = proxy and _client["proxy"] is None
        if need_proxy:
            # The CONTROLLER owns the proxy fleet — one per alive node
            # (reference: proxy_state_manager / proxy.py:1116) — and
            # keeps it reconciled as nodes join/leave. ensure_proxies is
            # get-or-create: an already-running fleet (a previous driver
            # or CLI invocation) is adopted, with its recorded bind info.
            info = dict(rt.get(
                ctrl.ensure_proxies.remote({
                    "host": http_options.host,
                    "port": http_options.port,
                    "request_timeout_s": http_options.request_timeout_s,
                }), timeout=60) or {})
            pr = rt.get_actor("SERVE_PROXY", timeout=10)
            with _client_lock:
                _client["proxy"] = pr
                _client["http"] = info
        with _client_lock:
            pr = _client["proxy"]
            http = _client["http"]
        if grpc_options is not None and pr is not None \
                and "grpc_port" not in (http or {}):
            # Bind the gRPC ingress on the running proxy (whether it was
            # just created or already existed) rather than silently
            # dropping the request.
            info = dict(http or {})
            info.update(rt.get(pr.start_grpc.remote(
                grpc_options.host, grpc_options.port), timeout=30))
            with _client_lock:
                _client["http"] = http = info
        if http is not None:
            rt.get(ctrl.set_http_info.remote(http), timeout=10)
        if pr is not None:
            from ..util import tracing

            # Mirror the driver's tracing state (both directions) so
            # per-request server spans record exactly when the driver
            # traces; picked up on every serve.start()/serve.run().
            try:
                rt.get(pr.set_tracing.remote(
                    tracing.enabled()), timeout=10)
            except Exception:  # noqa: BLE001 - adopted older proxy
                pass
    finally:
        _boot_exit(ev)
    return ctrl


def _get_or_create_controller():
    from ._controller import ServeController

    try:
        return rt.get_actor(SERVE_CONTROLLER_NAME, timeout=0.5)
    except Exception:  # noqa: BLE001 - not created yet
        pass
    try:
        ctrl = rt.remote(ServeController).options(
            name=SERVE_CONTROLLER_NAME, max_concurrency=16).remote()
        ctrl._wait_ready(timeout=30)
        return ctrl
    except Exception:  # noqa: BLE001 - lost a creation race
        return rt.get_actor(SERVE_CONTROLLER_NAME, timeout=10)


def run(app: Application, *, name: str = DEFAULT_APP_NAME,
        route_prefix: Optional[str] = "/", blocking: bool = False,
        _proxy: bool = True) -> DeploymentHandle:
    """Deploy an application and return a handle to its ingress
    (reference: ``serve/api.py:545``)."""
    if not isinstance(app, Application):
        raise TypeError("serve.run() takes an Application built with "
                        "`Deployment.bind()`")
    ctrl = start(proxy=_proxy)
    spec = _build_app_spec(app, name, route_prefix)
    rt.get(ctrl.deploy_app.remote(spec), timeout=120)
    handle = DeploymentHandle(name, spec["ingress"])
    if blocking:
        try:
            while True:
                time.sleep(1)
        except KeyboardInterrupt:
            pass
    return handle


def _build_app_spec(app: Application, name: str,
                    route_prefix: Optional[str]) -> dict:
    deployments: Dict[str, dict] = {}

    def visit(a: Application) -> str:
        d = a.deployment
        args = _strip(a.args)
        kwargs = _strip(a.kwargs)
        payload = cloudpickle.dumps((d.func_or_class, args, kwargs))
        if d.name in deployments:
            if deployments[d.name]["payload"] != payload:
                raise RayTpuError(
                    f"two different deployments named {d.name!r} in one app")
        else:
            deployments[d.name] = {"name": d.name, "payload": payload,
                                   "config": d.config}
        return d.name

    def _strip(obj):
        if isinstance(obj, Application):
            return _HandleMarker(visit(obj))
        if isinstance(obj, Deployment):
            raise RayTpuError(
                f"pass {obj!r} as an init arg via .bind(), not raw")
        if isinstance(obj, tuple):
            return tuple(_strip(x) for x in obj)
        if isinstance(obj, list):
            return [_strip(x) for x in obj]
        if isinstance(obj, dict):
            return {k: _strip(v) for k, v in obj.items()}
        return obj

    ingress = visit(app)
    # Streaming ingress detection (reference: StreamingResponse handling
    # in the proxy): a generator __call__ makes the proxy stream the
    # HTTP response chunked instead of buffering it.
    import inspect

    root = app.deployment.func_or_class
    target = root if inspect.isfunction(root) else \
        getattr(root, "__call__", None)
    stream = bool(target is not None and
                  (inspect.isgeneratorfunction(target)
                   or inspect.isasyncgenfunction(target)))
    return {"name": name, "route_prefix": route_prefix, "ingress": ingress,
            "stream": stream, "deployments": list(deployments.values())}


def get_app_handle(name: str = DEFAULT_APP_NAME) -> DeploymentHandle:
    ctrl = _controller()
    ingress = rt.get(ctrl.get_ingress.remote(name), timeout=10)
    if ingress is None:
        raise RayTpuError(f"no application named {name!r}")
    return DeploymentHandle(name, ingress)


def get_deployment_handle(deployment_name: str,
                          app_name: str = DEFAULT_APP_NAME
                          ) -> DeploymentHandle:
    return DeploymentHandle(app_name, deployment_name)


def status() -> dict:
    return rt.get(_controller().status.remote(), timeout=10)


def delete(name: str):
    rt.get(_controller().delete_app.remote(name), timeout=60)
    reset_routers()


def shutdown():
    """Tear down all apps, the proxy, and the controller. Serialized
    against an in-flight :func:`start` by the boot gate (so a teardown
    never interleaves a half-built control plane), with the teardown
    RPCs themselves OUTSIDE ``_client_lock`` — same rtsan RS104 fix as
    ``start``: the state lock is for the dict, never for the wire."""
    ev = _boot_enter()
    try:
        with _client_lock:
            ctrl = _client["controller"]
            proxy = _client["proxy"]
            _client.update({"controller": None, "proxy": None,
                            "http": None})
        if ctrl is None:
            try:
                ctrl = rt.get_actor(SERVE_CONTROLLER_NAME, timeout=0.5)
            except Exception:  # noqa: BLE001
                ctrl = None
        if ctrl is not None:
            try:
                rt.get(ctrl.shutdown_serve.remote(), timeout=60)
            except Exception:  # noqa: BLE001
                pass
            try:
                rt.kill(ctrl)
            except Exception:  # noqa: BLE001
                pass
        if proxy is None:
            # A fresh process (the CLI) has no cached handle — the
            # named actor is the source of truth.
            try:
                proxy = rt.get_actor("SERVE_PROXY", timeout=0.5)
            except Exception:  # noqa: BLE001 - no proxy running
                proxy = None
        if proxy is not None:
            try:
                rt.kill(proxy)
            except Exception:  # noqa: BLE001
                pass
    finally:
        _boot_exit(ev)
    reset_routers()


def _controller():
    with _client_lock:
        if _client["controller"] is not None:
            return _client["controller"]
    return rt.get_actor(SERVE_CONTROLLER_NAME, timeout=10)
