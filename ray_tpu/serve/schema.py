"""Declarative Serve config: YAML/dict schemas + import-path app loading.

Capability parity with the reference's config file surface (reference:
``python/ray/serve/schema.py`` — ``ServeDeploySchema`` /
``ServeApplicationSchema`` / ``DeploymentSchema`` — and
``serve/scripts.py`` ``serve deploy/run/config/status``): applications
are named by ``import_path`` ("module:attr" or "module.attr" resolving
to an ``Application`` built with ``.bind()``), with per-deployment
config overrides applied on top of the decorator values.
"""
from __future__ import annotations

import importlib
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

KV_NS = "serve"
KV_LAST_CONFIG = "last_deploy_config"


@dataclass
class DeploymentSchema:
    """Per-deployment override block (reference: ``DeploymentSchema``).

    Request-lifecycle knobs: ``max_ongoing_requests`` is enforced BOTH
    client-side (router admission) and server-side (the replica pushes
    back with a typed overload error the router answers by re-picking);
    ``max_queued_requests`` bounds how many callers may wait for
    admission once every replica is saturated — beyond it the request is
    shed (``BackPressureError``; HTTP ``503`` + ``Retry-After`` at the
    proxy). Bounded queues keep accepted-request tail latency flat under
    overload instead of letting it grow with the queue.

    ``autoscaling:`` (ISSUE 17) declares the SLO-driven control loop
    for the deployment — ``min_replicas``/``max_replicas`` bounds,
    one load signal (``target_occupancy`` for decode slot fraction,
    ``target_queue_depth`` for admission backlog,
    ``target_ongoing_requests`` as the classic fallback), an optional
    ``tpot_slo_s`` latency overlay, ``scale_to_zero_idle_s`` opt-in,
    and the bounding knobs (``hysteresis``, ``upscale_step`` /
    ``downscale_step``, per-direction cooldowns). Disaggregated
    deployments scale per role group via ``autoscaling: {roles:
    {prefill: {...}, decode: {...}}}`` — see
    :class:`ray_tpu.serve.config.AutoscalingConfig`. The block is
    validated at config-parse time so a bad key or range fails the
    ``serve deploy`` before anything is touched."""

    name: str
    num_replicas: Optional[int] = None
    max_ongoing_requests: Optional[int] = None
    max_queued_requests: Optional[int] = None
    autoscaling_config: Optional[Dict[str, Any]] = None
    user_config: Any = None
    ray_actor_options: Optional[Dict[str, Any]] = None
    #: Decode-engine block for continuous-batching deployments:
    #: ``engine: {page_size: 16, prefix_cache: true, n_pages: 512,
    #: spec_decode: ngram, draft_k: 4}`` — paged-KV knobs plus the
    #: speculative-decoding knobs. The replica applies it to every
    #: DecodeEngine the deployment constructs (see
    #: ``DeploymentConfig.engine_config``). Disaggregated
    #: prefill/decode (ISSUE 14) rides the same block:
    #: ``engine: {roles: {prefill: 1, decode: 2}, handoff_ttl_s: 30}``
    #: makes the controller reconcile heterogeneous role groups within
    #: the one deployment (each replica's engine gets its own ``role``
    #: stamped; routers two-hop generation across the groups), while a
    #: bare ``role:`` pins every replica to one role.
    engine: Optional[Dict[str, Any]] = None

    _ENGINE_KEYS = frozenset({"page_size", "prefix_cache", "n_pages",
                              "spec_decode", "draft_k",
                              "spec_threshold", "role", "roles",
                              "handoff_ttl_s", "attn_kernel",
                              "kv_dtype", "tp"})

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "DeploymentSchema":
        known = {f for f in cls.__dataclass_fields__}  # noqa: C416
        unknown = set(d) - known
        if unknown:
            raise ValueError(
                f"unknown deployment config keys {sorted(unknown)}")
        eng = d.get("engine")
        if eng is not None:
            bad = set(eng) - cls._ENGINE_KEYS
            if bad:
                raise ValueError(
                    f"unknown engine config keys {sorted(bad)}; "
                    f"known: {sorted(cls._ENGINE_KEYS)}")
        ac = d.get("autoscaling_config")
        if ac is not None:
            from .config import AutoscalingConfig

            try:
                AutoscalingConfig(**ac)  # parse-time validation only
            except TypeError as e:
                raise ValueError(
                    f"bad autoscaling block for deployment "
                    f"{d.get('name')!r}: {e}") from None
        return cls(**d)


@dataclass
class ServeApplicationSchema:
    """One application entry (reference: ``ServeApplicationSchema``)."""

    import_path: str
    name: str = "default"
    route_prefix: Optional[str] = "/"
    deployments: List[DeploymentSchema] = field(default_factory=list)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ServeApplicationSchema":
        d = dict(d)
        deps = [DeploymentSchema.from_dict(x)
                for x in d.pop("deployments", [])]
        known = {f for f in cls.__dataclass_fields__}  # noqa: C416
        unknown = set(d) - known
        if unknown:
            raise ValueError(
                f"unknown application config keys {sorted(unknown)}")
        if "import_path" not in d:
            raise ValueError("application config needs an import_path")
        return cls(deployments=deps, **d)


@dataclass
class ServeDeploySchema:
    """Top-level config file (reference: ``ServeDeploySchema``).

    ``tracing: true`` turns on request tracing for the deploy: the
    deploying process enables ``ray_tpu.util.tracing`` and the proxies
    mirror the flag on start, so every request gets a span tree
    (proxy.admission → router.queue_wait → replica.queue_wait →
    user_code → batch.wait/decode.chunk) visible via
    ``tracing.get_spans()`` and the chrome-trace timeline."""

    applications: List[ServeApplicationSchema]
    http_options: Optional[Dict[str, Any]] = None
    grpc_options: Optional[Dict[str, Any]] = None
    tracing: Optional[bool] = None

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ServeDeploySchema":
        d = dict(d)
        apps = [ServeApplicationSchema.from_dict(a)
                for a in d.pop("applications", [])]
        if not apps:
            raise ValueError("config has no applications")
        names = [a.name for a in apps]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate application names in {names}")
        known = {f for f in cls.__dataclass_fields__}  # noqa: C416
        unknown = set(d) - known
        if unknown:
            raise ValueError(f"unknown config keys {sorted(unknown)}")
        return cls(applications=apps, **d)


def import_application(import_path: str):
    """Resolve "pkg.mod:attr" (or "pkg.mod.attr") to an Application."""
    from .api import Application

    if ":" in import_path:
        mod_name, _, attr = import_path.partition(":")
    else:
        mod_name, _, attr = import_path.rpartition(".")
    if not mod_name or not attr:
        raise ValueError(f"bad import path {import_path!r}; want "
                         "'module:attr' or 'module.attr'")
    obj = getattr(importlib.import_module(mod_name), attr)
    if not isinstance(obj, Application) and callable(obj):
        # App builder function, reference-style — but only if it is
        # actually zero-arg callable (an arbitrary callable like
        # json.dumps should produce the clean type error below).
        import inspect

        try:
            params = inspect.signature(obj).parameters.values()
            zero_arg = not any(
                p.default is inspect.Parameter.empty
                and p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
                for p in params)
        except (TypeError, ValueError):
            zero_arg = False
        if zero_arg:
            obj = obj()
    if not isinstance(obj, Application):
        raise TypeError(f"{import_path} resolved to {type(obj).__name__}, "
                        "not a serve Application")
    return obj


def apply_overrides(spec: Dict[str, Any],
                    overrides: List[DeploymentSchema]) -> Dict[str, Any]:
    """Merge config-file deployment overrides into a built app spec
    (decorator values < config file, reference precedence)."""
    by_name = {o.name: o for o in overrides}
    known = {d["name"] for d in spec["deployments"]}
    missing = set(by_name) - known
    if missing:
        raise ValueError(
            f"config overrides unknown deployments {sorted(missing)}; "
            f"app has {sorted(known)}")
    import copy as _copy

    for d in spec["deployments"]:
        o = by_name.get(d["name"])
        if o is None:
            continue
        # Deep-copy before mutating: the spec shares the decorator's
        # DeploymentConfig instance, which later deploys reuse.
        cfg = d["config"] = _copy.deepcopy(d["config"])
        if o.num_replicas is not None:
            cfg.num_replicas = o.num_replicas
        if o.max_ongoing_requests is not None:
            cfg.max_ongoing_requests = o.max_ongoing_requests
        if o.max_queued_requests is not None:
            cfg.max_queued_requests = o.max_queued_requests
        if o.autoscaling_config is not None:
            from .config import AutoscalingConfig

            cfg.autoscaling_config = AutoscalingConfig(
                **o.autoscaling_config)
        if o.user_config is not None:
            cfg.user_config = o.user_config
        if o.ray_actor_options is not None:
            cfg.ray_actor_options = dict(o.ray_actor_options)
        if o.engine is not None:
            cfg.engine_config = dict(o.engine)
    return spec


def deploy_config(config: Dict[str, Any]) -> List[str]:
    """Deploy every application in a parsed config dict; returns the
    deployed app names. The raw config is stored in the cluster KV so
    ``serve config`` can echo it back from any process."""
    import json

    from .. import api as rt
    from . import api as serve_api

    schema = ServeDeploySchema.from_dict(config)
    if schema.tracing is not None:
        from ..util import tracing as _tracing

        # Before start(): serve.start mirrors the flag into the proxy
        # fleet, so per-request server spans record from the first
        # request after this deploy.
        _tracing.enable() if schema.tracing else _tracing.disable()
    serve_api.start(http_options=schema.http_options,
                    grpc_options=schema.grpc_options)
    ctrl = serve_api._controller()
    names = []
    for app in schema.applications:
        built = import_application(app.import_path)
        spec = serve_api._build_app_spec(built, app.name, app.route_prefix)
        spec = apply_overrides(spec, app.deployments)
        rt.get(ctrl.deploy_app.remote(spec), timeout=120)
        names.append(app.name)
    # Declarative semantics (reference `serve deploy`): the config IS
    # the desired state — applications it no longer lists are removed.
    live = rt.get(ctrl.status.remote(), timeout=30)["applications"]
    for stale in set(live) - set(names):
        rt.get(ctrl.delete_app.remote(stale), timeout=60)
    from ..core.worker import CoreWorker

    CoreWorker.current().kv_put(KV_LAST_CONFIG,
                                json.dumps(config).encode(), ns=KV_NS)
    return names


def get_last_config() -> Optional[Dict[str, Any]]:
    import json

    from ..core.worker import CoreWorker

    raw = CoreWorker.current().kv_get(KV_LAST_CONFIG, ns=KV_NS)
    return json.loads(raw) if raw else None
