"""DeploymentHandle: the client-side data plane (router included).

Capability parity with the reference's handle + router
(reference: ``python/ray/serve/handle.py`` ``DeploymentHandle`` /
``DeploymentResponse``; ``serve/_private/router.py:518`` and
``replica_scheduler/pow_2_scheduler.py:49`` — power-of-two-choices on
queue length with client-side ``max_ongoing_requests`` admission).

Design differences from the reference: the router lives entirely in the
caller process (no dedicated router actors), tracks in-flight counts
locally, and learns replica membership by polling the controller with a
version number — membership changes are rare; request dispatch is hot.
"""
from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, Optional, Tuple

from ..exceptions import (ActorDiedError, ActorUnavailableError, RayTpuError,
                          TaskError, WorkerCrashedError)

_RETRYABLE_CAUSES = ("ActorDiedError", "ActorUnavailableError",
                     "WorkerCrashedError", "ConnectionLost",
                     # a killed replica's worker socket refuses dials
                     # in the window before the head reaps it
                     "ConnectionRefusedError", "ConnectionResetError")


def _is_replica_failure(e: Exception) -> bool:
    if isinstance(e, (ActorDiedError, ActorUnavailableError,
                      WorkerCrashedError)):
        return True
    if not isinstance(e, TaskError):
        return False
    if getattr(e, "cause_type", "") in _RETRYABLE_CAUSES:
        return True
    # Stale-route rejection: the worker invalidates its route cache and
    # explicitly delegates the retry to this layer (core/worker.py
    # ACTOR_NOT_ON_WORKER handling) — the replica moved, it didn't fail.
    from ..core.worker import ACTOR_NOT_ON_WORKER

    return ACTOR_NOT_ON_WORKER in str(e)
from .config import SERVE_CONTROLLER_NAME

_routers: Dict[Tuple[str, str], "Router"] = {}
_routers_lock = threading.Lock()


class _HandleMarker:
    """Placeholder for a bound deployment inside init args; replaced with a
    live ``DeploymentHandle`` at replica init."""

    def __init__(self, deployment_name: str):
        self.deployment_name = deployment_name

    def __repr__(self):
        return f"_HandleMarker({self.deployment_name})"


def get_router(app_name: str, deployment_name: str) -> "Router":
    key = (app_name, deployment_name)
    with _routers_lock:
        r = _routers.get(key)
        if r is None or r.closed:
            r = Router(app_name, deployment_name)
            _routers[key] = r
        return r


def reset_routers():
    """Drop all cached routers (serve.shutdown / tests)."""
    with _routers_lock:
        for r in _routers.values():
            r.close()
        _routers.clear()


class DeploymentResponse:
    """Future-like result of ``handle.remote()``; also awaitable inside
    async actors (delegates to the ObjectRef awaitable)."""

    def __init__(self, router: "Router", rid: str, ref,
                 call: Tuple[str, tuple, dict], model_id: str = ""):
        self._router = router
        self._rid = rid
        self._ref = ref
        self._call = call
        self._model_id = model_id

    @property
    def object_ref(self):
        return self._ref

    def result(self, timeout: Optional[float] = None,
               _retries: int = 2) -> Any:
        from .. import api as rt

        try:
            return rt.get(self._ref, timeout=timeout)
        except Exception as e:  # noqa: BLE001
            # Replica died mid-request: refresh membership and retry on a
            # different replica (reference: router retry on
            # ActorDiedError, ``router.py``).
            if not _is_replica_failure(e):
                raise
            self._router.mark_dead(self._rid)
            if _retries <= 0:
                raise
            method, args, kwargs = self._call
            # Carry the multiplexed model id so a transparent retry
            # still executes in the original tenant's context.
            resp = self._router.submit(method, args, kwargs,
                                       model_id=self._model_id)
            self._rid, self._ref = resp._rid, resp._ref
            return self.result(timeout=timeout, _retries=_retries - 1)

    def __await__(self):
        return self._ref.__await__()


class DeploymentResponseGenerator:
    """Iterable result of ``handle.options(stream=True).remote()``
    (reference: ``serve/handle.py`` DeploymentResponseGenerator). Items
    arrive as the replica's generator yields them; in-flight accounting
    is released once, on exhaustion, failure, or abandonment."""

    def __init__(self, router: "Router", rid: str, gen):
        self._router = router
        self._rid = rid
        self._gen = gen
        self._done = False

    def _finish(self):
        if not self._done:
            self._done = True
            self._router.release(self._rid)

    def __iter__(self):
        return self

    def __next__(self):
        from .. import api as rt

        if self._done:
            raise StopIteration
        try:
            ref = next(self._gen)
        except StopIteration:
            self._finish()
            raise
        try:
            return rt.get(ref)
        except Exception:
            self._finish()
            raise

    def __del__(self):
        try:
            self._finish()
        except Exception:  # noqa: BLE001 - interpreter shutdown
            pass


class DeploymentHandle:
    """Picklable handle to one deployment of one app."""

    def __init__(self, app_name: str, deployment_name: str,
                 method_name: str = "__call__",
                 multiplexed_model_id: str = "", stream: bool = False,
                 flatten_chunks: bool = False):
        self.app_name = app_name
        self.deployment_name = deployment_name
        self.method_name = method_name
        self.multiplexed_model_id = multiplexed_model_id
        self.stream = stream
        # Chunked-decode replicas stream per-chunk token slices; with
        # flatten_chunks the replica re-yields each slice element-wise
        # so this caller sees per-token items over the same transport.
        self.flatten_chunks = flatten_chunks

    def __reduce__(self):
        return (DeploymentHandle,
                (self.app_name, self.deployment_name, self.method_name,
                 self.multiplexed_model_id, self.stream,
                 self.flatten_chunks))

    def options(self, *, method_name: Optional[str] = None,
                multiplexed_model_id: Optional[str] = None,
                stream: Optional[bool] = None,
                flatten_chunks: Optional[bool] = None) -> "DeploymentHandle":
        return DeploymentHandle(
            self.app_name, self.deployment_name,
            method_name or self.method_name,
            multiplexed_model_id if multiplexed_model_id is not None
            else self.multiplexed_model_id,
            self.stream if stream is None else stream,
            self.flatten_chunks if flatten_chunks is None
            else flatten_chunks)

    def __getattr__(self, name: str) -> "DeploymentHandle":
        if name.startswith("_"):
            raise AttributeError(name)
        return DeploymentHandle(self.app_name, self.deployment_name, name,
                                self.multiplexed_model_id, self.stream,
                                self.flatten_chunks)

    def remote(self, *args, **kwargs):
        router = get_router(self.app_name, self.deployment_name)
        if self.stream:
            return router.submit_stream(self.method_name, args, kwargs,
                                        model_id=self.multiplexed_model_id,
                                        flatten_chunks=self.flatten_chunks)
        return router.submit(self.method_name, args, kwargs,
                             model_id=self.multiplexed_model_id)

    def __repr__(self):
        return (f"DeploymentHandle(app={self.app_name!r}, "
                f"deployment={self.deployment_name!r})")


class Router:
    """Power-of-two-choices replica scheduler with local admission control."""

    MEMBERSHIP_TTL_S = 1.0
    _MODEL_AFFINITY_CAP = 1024

    def __init__(self, app_name: str, deployment_name: str):
        self.app_name = app_name
        self.deployment_name = deployment_name
        self.closed = False
        self._cond = threading.Condition()
        self._replicas: Dict[str, Any] = {}   # rid -> ActorHandle
        self._replica_nodes: Dict[str, Any] = {}  # rid -> node_id
        self._ongoing: Dict[str, int] = {}
        self._version = -1
        # This process's node, for locality-preferring choice
        # (reference: pow_2_scheduler prefer-local-node ranking).
        try:
            from ..core.worker import CoreWorker

            core = CoreWorker._current
            self._local_node = getattr(core, "node_id", None) \
                if core is not None else None
        except Exception:  # noqa: BLE001
            self._local_node = None
        self._max_ongoing = 16
        self._last_refresh = 0.0
        self._outstanding: Dict[Any, str] = {}  # ObjectRef -> rid
        # model_id -> replica ids that served it (multiplex affinity).
        # Advisory only (the replica's LRU may have evicted the model);
        # bounded LRU + pruned to live replicas on refresh.
        from collections import OrderedDict

        self._model_affinity: "OrderedDict[str, set]" = OrderedDict()
        self._waiter_wake = threading.Event()
        self._waiter = threading.Thread(
            target=self._completion_loop, daemon=True,
            name=f"rt-serve-router-{deployment_name}")
        self._waiter.start()

    # -------------------------------------------------------------- control
    def _controller(self):
        from .. import api as rt

        return rt.get_actor(SERVE_CONTROLLER_NAME, timeout=10)

    def refresh(self, force: bool = False):
        now = time.monotonic()
        with self._cond:
            if not force and now - self._last_refresh < self.MEMBERSHIP_TTL_S:
                return
            self._last_refresh = now
        info = self._controller().get_replicas.remote(
            self.app_name, self.deployment_name)
        from .. import api as rt

        info = rt.get(info, timeout=30)
        if info is None:
            raise RayTpuError(
                f"deployment {self.app_name}/{self.deployment_name} not found")
        with self._cond:
            if info["version"] == self._version:
                return
            self._version = info["version"]
            self._max_ongoing = info["max_ongoing_requests"]
            new = dict(info["replicas"])  # rid -> ActorHandle
            self._replicas = new
            self._replica_nodes = dict(info.get("replica_nodes") or {})
            self._ongoing = {rid: self._ongoing.get(rid, 0) for rid in new}
            # Membership changed: drop affinity entries for dead replicas.
            for mid in list(self._model_affinity):
                kept = self._model_affinity[mid] & set(new)
                if kept:
                    self._model_affinity[mid] = kept
                else:
                    del self._model_affinity[mid]
            self._cond.notify_all()

    def mark_dead(self, rid: str):
        with self._cond:
            self._replicas.pop(rid, None)
            self._ongoing.pop(rid, None)
            self._last_refresh = 0.0
            self._cond.notify_all()

    def close(self):
        self.closed = True
        self._waiter_wake.set()

    # ----------------------------------------------------------- data plane
    def submit(self, method_name: str, args: tuple, kwargs: dict,
               timeout_s: float = 60.0,
               model_id: str = "") -> DeploymentResponse:
        from .. import api as rt

        self.refresh()
        deadline = time.monotonic() + timeout_s
        while True:
            with self._cond:
                rid = self._pick_locked(model_id)
                if rid is not None:
                    self._ongoing[rid] += 1
                    handle = self._replicas[rid]
                    break
                waited = self._cond.wait(timeout=0.05)
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"no replica of {self.deployment_name} accepted the "
                    f"request within {timeout_s}s")
            if not waited:
                self.refresh()
        if model_id:
            with self._cond:
                self._model_affinity.setdefault(model_id, set()).add(rid)
                self._model_affinity.move_to_end(model_id)
                while len(self._model_affinity) > self._MODEL_AFFINITY_CAP:
                    self._model_affinity.popitem(last=False)
            ref = handle.handle_request.remote(
                method_name, args, kwargs, {"multiplexed_model_id":
                                            model_id})
        else:
            ref = handle.handle_request.remote(method_name, args, kwargs)
        with self._cond:
            self._outstanding[ref] = rid
        self._waiter_wake.set()
        return DeploymentResponse(self, rid, ref,
                                  (method_name, args, kwargs), model_id)

    def submit_stream(self, method_name: str, args: tuple, kwargs: dict,
                      timeout_s: float = 60.0, model_id: str = "",
                      flatten_chunks: bool = False
                      ) -> "DeploymentResponseGenerator":
        """Streaming dispatch: same admission + pow-2 pick as submit(),
        but the replica call rides the core streaming-generator
        transport and the in-flight slot is held until the stream ends
        (released by the DeploymentResponseGenerator, not the completion
        loop — a stream has no single completion ref to wait on)."""
        self.refresh()
        deadline = time.monotonic() + timeout_s
        while True:
            with self._cond:
                rid = self._pick_locked(model_id)
                if rid is not None:
                    self._ongoing[rid] += 1
                    handle = self._replicas[rid]
                    break
                waited = self._cond.wait(timeout=0.05)
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"no replica of {self.deployment_name} accepted the "
                    f"request within {timeout_s}s")
            if not waited:
                self.refresh()
        ctx = {}
        if model_id:
            ctx["multiplexed_model_id"] = model_id
        if flatten_chunks:
            ctx["flatten_chunks"] = True
        ctx = ctx or None
        gen = handle.handle_request_streaming.options(
            num_returns="streaming").remote(method_name, args, kwargs, ctx)
        return DeploymentResponseGenerator(self, rid, gen)

    def release(self, rid: str):
        """Return one in-flight slot (stream finished or abandoned)."""
        with self._cond:
            if rid in self._ongoing:
                self._ongoing[rid] = max(0, self._ongoing[rid] - 1)
            self._cond.notify_all()

    def _pick_locked(self, model_id: str = "") -> Optional[str]:
        rids = [r for r in self._replicas
                if self._ongoing.get(r, 0) < self._max_ongoing]
        if not rids:
            return None
        if model_id:
            # Model-affinity (reference multiplex routing): prefer a
            # replica that has already served this model — its LRU cache
            # likely still holds it, avoiding a reload.
            warm = [r for r in rids
                    if r in self._model_affinity.get(model_id, ())]
            if warm:
                rids = warm
        elif self._local_node is not None:
            # Locality: prefer same-node replicas (the response bytes
            # then ride shared memory, not TCP). Saturated locals fall
            # back to remote ones — rids is already capacity-filtered.
            local = [r for r in rids
                     if self._replica_nodes.get(r) == self._local_node]
            if local:
                rids = local
        if len(rids) <= 2:
            return min(rids, key=lambda r: self._ongoing[r])
        a, b = random.sample(rids, 2)
        return a if self._ongoing[a] <= self._ongoing[b] else b

    def _completion_loop(self):
        """Decrement in-flight counts as results land (the reference does
        this with asyncio callbacks on the replica result future)."""
        from .. import api as rt

        while not self.closed:
            with self._cond:
                refs = list(self._outstanding)
            if not refs:
                self._waiter_wake.wait(timeout=0.5)
                self._waiter_wake.clear()
                continue
            try:
                ready, _ = rt.wait(refs, num_returns=len(refs), timeout=0.05,
                                   fetch_local=False)
            except Exception:  # noqa: BLE001 - core shut down under us
                if self.closed:
                    return
                time.sleep(0.1)
                continue
            if ready:
                with self._cond:
                    for ref in ready:
                        rid = self._outstanding.pop(ref, None)
                        if rid in self._ongoing:
                            self._ongoing[rid] = max(
                                0, self._ongoing[rid] - 1)
                    self._cond.notify_all()

    # ------------------------------------------------------------- metrics
    def snapshot(self) -> Dict[str, Any]:
        with self._cond:
            return {"replicas": len(self._replicas),
                    "ongoing": dict(self._ongoing),
                    "version": self._version}
