"""DeploymentHandle: the client-side data plane (router included).

Capability parity with the reference's handle + router
(reference: ``python/ray/serve/handle.py`` ``DeploymentHandle`` /
``DeploymentResponse``; ``serve/_private/router.py:518`` and
``replica_scheduler/pow_2_scheduler.py:49`` — power-of-two-choices on
queue length with client-side ``max_ongoing_requests`` admission).

Design differences from the reference: the router lives entirely in the
caller process (no dedicated router actors), tracks in-flight counts
locally, and learns replica membership by polling the controller with a
version number — membership changes are rare; request dispatch is hot.

Request lifecycle (this module is the client half; ``_replica.py`` is
the server half):

- every submission is stamped with an **absolute deadline** that rides
  the request context to the replica and the batcher, so no layer
  restarts its own timeout window (``request.py``);
- retries are **budgeted**: a per-router token bucket earns a fraction
  of each success and spends one token per retry, so a dying deployment
  degrades to its organic failure rate instead of melting the cluster
  with a retry storm; retries back off exponentially with jitter;
- a replica's typed ``ReplicaOverloadedError`` pushback means
  "re-pick, don't mark dead"; when every replica is saturated and the
  pending queue is past ``max_queued_requests``, submissions shed with
  ``BackPressureError`` instead of queuing without bound.
"""
from __future__ import annotations

import random
import threading
import time
from typing import Any, Dict, Optional, Tuple

from ..exceptions import (ActorDiedError, ActorUnavailableError,
                          GetTimeoutError, RayTpuError, TaskError,
                          WorkerCrashedError)
from .._private import events as _events
from ..util import tracing
from .request import (HANDOFF_KEY, REQUEST_ID_KEY, RESUME_FROM_KEY,
                      SUBMITTED_AT_KEY, TRACE_CTX_KEY, BackPressureError,
                      ReplicaDrainingError, ReplicaOverloadedError,
                      RequestDeadlineExceeded, deadline_expired,
                      get_request_deadline, make_deadline, remaining_s,
                      stream_item_width)

_RETRYABLE_CAUSES = ("ActorDiedError", "ActorUnavailableError",
                     "WorkerCrashedError", "ConnectionLost",
                     # a killed replica's worker socket refuses dials
                     # in the window before the head reaps it
                     "ConnectionRefusedError", "ConnectionResetError")


def _is_replica_failure(e: Exception) -> bool:
    if isinstance(e, (ActorDiedError, ActorUnavailableError,
                      WorkerCrashedError)):
        return True
    if not isinstance(e, TaskError):
        return False
    if getattr(e, "cause_type", "") in _RETRYABLE_CAUSES:
        return True
    # Stale-route rejection: the worker invalidates its route cache and
    # explicitly delegates the retry to this layer (core/worker.py
    # ACTOR_NOT_ON_WORKER handling) — the replica moved, it didn't fail.
    from ..core.worker import ACTOR_NOT_ON_WORKER

    return ACTOR_NOT_ON_WORKER in str(e)


#: Typed replica-side pushback: the replica (or its engine) declined or
#: abandoned the request for a reason that is ROUTING state, not a
#: failure — overload, a graceful drain, an engine shutdown mid-rolling-
#: restart, or a supervised driver restart. All of them mean "re-pick
#: another replica, don't mark this one dead, don't spend retry budget";
#: membership refresh retires genuinely departing replicas shortly
#: after. Each class carries ``retryable = True``; the names matter only
#: once the error has crossed the wire as a TaskError.
_PUSHBACK_CAUSES = ("ReplicaOverloadedError", "ReplicaDrainingError",
                    "EngineShutdownError", "EngineRestartError")


def _is_overload(e: Exception) -> bool:
    """Replica-side pushback (crosses the wire as TaskError): overload,
    drain, or a retryable engine shutdown/restart."""
    if getattr(e, "retryable", False):
        return True
    return (isinstance(e, TaskError)
            and getattr(e, "cause_type", "") in _PUSHBACK_CAUSES)


def _is_draining(e: Exception) -> bool:
    """Drain pushback specifically: unlike a saturation mark (which
    self-expires — the replica stays a candidate), a draining replica
    must stay OUT of the pick set until the controller stops listing it
    as draining or membership drops it. Letting the mark self-expire
    would bounce every re-pick off the same dying replica for the whole
    graceful-drain window."""
    if isinstance(e, ReplicaDrainingError):
        return True
    return (isinstance(e, TaskError)
            and getattr(e, "cause_type", "") == "ReplicaDrainingError")


def _is_deadline_error(e: Exception) -> bool:
    """The replica (or batcher) dropped the request as already expired."""
    return isinstance(e, RequestDeadlineExceeded) or (
        isinstance(e, TaskError)
        and getattr(e, "cause_type", "") == "RequestDeadlineExceeded")


def _serve_counters():
    from .._private.metrics import serve_metrics

    return serve_metrics()


from .config import SERVE_CONTROLLER_NAME

_routers: Dict[Tuple[str, str], "Router"] = {}
_routers_lock = threading.Lock()


class _HandleMarker:
    """Placeholder for a bound deployment inside init args; replaced with a
    live ``DeploymentHandle`` at replica init."""

    def __init__(self, deployment_name: str):
        self.deployment_name = deployment_name

    def __repr__(self):
        return f"_HandleMarker({self.deployment_name})"


def get_router(app_name: str, deployment_name: str) -> "Router":
    key = (app_name, deployment_name)
    with _routers_lock:
        r = _routers.get(key)
        if r is None or r.closed:
            r = Router(app_name, deployment_name)
            _routers[key] = r
        return r


def reset_routers():
    """Drop all cached routers (serve.shutdown / tests)."""
    with _routers_lock:
        for r in _routers.values():
            r.close()
        _routers.clear()


class RetryBudget:
    """Finagle-style retry budget (token bucket).

    Each success deposits ``deposit_ratio`` tokens; a small time-based
    reserve trickles in so a cold or low-traffic router can still retry;
    each retry withdraws one token. At steady state retries are capped at
    ~``deposit_ratio`` of the success rate, which is what stops a dying
    deployment from amplifying its own load with a retry storm."""

    def __init__(self, deposit_ratio: float = 0.1,
                 reserve_per_s: float = 2.0, cap: float = 100.0,
                 initial: float = 10.0):
        self.deposit_ratio = deposit_ratio
        self.reserve_per_s = reserve_per_s
        self.cap = cap
        self._lock = threading.Lock()
        self._tokens = min(initial, cap)
        self._at = time.monotonic()

    def _replenish_locked(self):
        now = time.monotonic()
        self._tokens = min(self.cap,
                           self._tokens + (now - self._at)
                           * self.reserve_per_s)
        self._at = now

    def record_success(self):
        with self._lock:
            self._replenish_locked()
            self._tokens = min(self.cap, self._tokens + self.deposit_ratio)

    def take(self) -> bool:
        """Withdraw one retry token; False = budget exhausted, don't retry."""
        with self._lock:
            self._replenish_locked()
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                return True
            return False

    def tokens(self) -> float:
        with self._lock:
            self._replenish_locked()
            return self._tokens


def _claim_on_first(gen, claim):
    """Pass-through over a streaming-generator's refs that fires the
    handoff claim exactly once, on the first yielded item — the decode
    side produced output, so the import landed and the prefill engine
    may drop its pin before the lease expires. A stream that dies
    before its first item never claims; the lease sweep reclaims."""
    first = True
    for ref in gen:
        if first:
            first = False
            claim()
        yield ref


def _backoff_sleep(backoff_s: float, deadline_s: Optional[float]):
    """Jittered backoff, never sleeping past the request deadline.

    Deliberately a blocking sleep (rtlint RT104 audit): retries run on
    the CALLER's thread — a sync ``result()``/``__next__`` that is
    already committed to blocking until the deadline — never on an
    event loop. The async surfaces (proxy dispatch, ``__await__``)
    reach this code only through ``run_in_executor`` pool threads,
    where blocking is the contract."""
    delay = backoff_s * (0.5 + random.random() * 0.5)
    rem = remaining_s(deadline_s)
    if rem is not None:
        delay = min(delay, max(rem, 0.0))
    if delay > 0:
        time.sleep(delay)


class DeploymentResponse:
    """Future-like result of ``handle.remote()``; also awaitable inside
    async actors (delegates to the ObjectRef awaitable).

    ``result()`` owns the client half of the retry story: budgeted,
    backoff-spaced resubmission on replica death, deadline-preserving
    (a retry inherits the submission's remaining time instead of
    restarting the full window), and overload re-picks that route
    around saturated replicas without marking them dead."""

    def __init__(self, router: "Router", rid: str, ref,
                 call: Tuple[str, tuple, dict], model_id: str = "",
                 deadline_s: Optional[float] = None,
                 t0: Optional[float] = None, request_id: str = ""):
        self._router = router
        self._rid = rid
        self._ref = ref
        self._call = call
        self._model_id = model_id
        self._deadline_s = deadline_s
        #: Flight-recorder correlation id; retries reuse it.
        self._request_id = request_id
        # Submission instant (perf_counter) for the e2e latency
        # histogram; a retry keeps the ORIGINAL t0 — the caller has been
        # waiting since the first submission. Observed at most once —
        # result() is legal to call repeatedly.
        self._t0 = time.perf_counter() if t0 is None else t0
        self._e2e_observed = False

    @property
    def object_ref(self):
        return self._ref

    @property
    def request_id(self) -> str:
        """Flight-recorder correlation id of this logical request —
        stable across retries; the join key for ``rtblackbox
        --request``."""
        return self._request_id

    def result(self, timeout: Optional[float] = None,
               _retries: Optional[int] = None) -> Any:
        from .. import api as rt

        max_retries = (Router.DEFAULT_MAX_RETRIES if _retries is None
                       else _retries)
        # The wait window: an EXPLICIT result() timeout owns it (longer
        # or shorter than the submission deadline — the caller said so);
        # otherwise the submission's request deadline governs. Retries
        # below resubmit with THIS deadline, so a retried call deducts
        # time already spent instead of restarting the full 60 s window.
        deadline = (make_deadline(timeout) if timeout is not None
                    else self._deadline_s)
        attempts = 0
        backoff = Router.RETRY_BACKOFF_BASE_S
        labels = {"deployment": self._router.deployment_name}
        while True:
            try:
                out = rt.get(self._ref, timeout=remaining_s(deadline))
                self._router.budget.record_success()
                if not self._e2e_observed:
                    self._e2e_observed = True
                    _serve_counters()["e2e_latency"].observe(
                        time.perf_counter() - self._t0, labels=labels)
                return out
            except Exception as e:  # noqa: BLE001
                if isinstance(e, GetTimeoutError):
                    # With no explicit timeout, the wait bound IS the
                    # request deadline — it can fire before the
                    # replica's own typed rejection arrives; surface it
                    # as the deadline error it is. An explicit
                    # result(timeout=...) keeps its classic
                    # GetTimeoutError semantics.
                    if timeout is None and deadline_expired(deadline):
                        raise RequestDeadlineExceeded(
                            f"request to {self._router.deployment_name} "
                            f"expired after "
                            f"{self._call[0]!r} was submitted") from e
                    raise
                if _is_deadline_error(e):
                    raise RequestDeadlineExceeded(
                        f"request to {self._router.deployment_name} "
                        f"expired before execution") from e
                if _is_overload(e):
                    # Typed pushback: the replica is full (or leaving),
                    # not broken. Re-pick another one; no budget spend,
                    # no mark_dead. Draining marks persist until the
                    # controller confirms the drain is over; saturation
                    # marks self-expire.
                    if _is_draining(e):
                        self._router.note_draining(self._rid)
                    else:
                        self._router.note_overloaded(self._rid)
                    _serve_counters()["overload_repicks"].inc(labels=labels)
                elif _is_replica_failure(e):
                    self._router.mark_dead(self._rid)
                    if attempts >= max_retries \
                            or deadline_expired(deadline) \
                            or not self._router.budget.take():
                        raise
                    attempts += 1
                    _serve_counters()["retries"].inc(labels=labels)
                    _events.emit("router.retry",
                                 request=self._request_id,
                                 deployment=self._router.deployment_name,
                                 replica=self._rid, attempt=attempts,
                                 cause=type(e).__name__)
                else:
                    raise
                _backoff_sleep(backoff, deadline)
                backoff = min(backoff * 2, Router.RETRY_BACKOFF_CAP_S)
                method, args, kwargs = self._call
                # Carry the multiplexed model id so a transparent retry
                # still executes in the original tenant's context (and
                # the request id so the retry joins the same story).
                resp = self._router.submit(method, args, kwargs,
                                           deadline_s=deadline,
                                           model_id=self._model_id,
                                           request_id=self._request_id)
                self._rid, self._ref = resp._rid, resp._ref

    def __await__(self):
        return self._ref.__await__()


class DeploymentResponseGenerator:
    """Iterable result of ``handle.options(stream=True).remote()``
    (reference: ``serve/handle.py`` DeploymentResponseGenerator). Items
    arrive as the replica's generator yields them; in-flight accounting
    is released once, on exhaustion, failure, or abandonment.

    **Retry-before-first-item**: stream setup against a dead or
    saturated replica transparently re-routes — budgeted and
    backoff-spaced like unary retries — as long as no item has been
    delivered yet.

    **Mid-stream failover** (``resumable=True``): the generator keeps a
    replay token — the call itself plus the count of tokens already
    delivered to this caller — so a replica that dies (or drains, or
    restarts its engine driver) MID-stream no longer kills the stream:
    the call is resubmitted through the same budgeted retry path with
    ``resume_from=n``, and the receiving replica replays the
    deterministic generation suppressing the first ``n`` tokens. The
    caller sees a stall, then the exact continuation — token-identical
    to an uninterrupted run at temp 0 and seeded temp > 0. Only enable
    for DETERMINISTIC streams (seeded engine decodes); a nondeterministic
    handler would resume onto a different continuation. The resume
    respects the ORIGINAL deadline and withdraws from the same retry
    budget as unary retries."""

    def __init__(self, router: "Router", rid: str, gen,
                 call: Optional[Tuple[str, tuple, dict]] = None,
                 model_id: str = "", flatten_chunks: bool = False,
                 deadline_s: Optional[float] = None,
                 t0: Optional[float] = None, resumable: bool = False,
                 request_id: str = ""):
        self._router = router
        self._rid = rid
        self._gen = gen
        #: Flight-recorder correlation id; re-routes and resumes reuse
        #: it, so every hop of this stream's story joins on one key.
        self._request_id = request_id
        self._call = call
        self._model_id = model_id
        self._flatten_chunks = flatten_chunks
        self._deadline_s = deadline_s
        self._resumable = resumable
        #: Replay token: tokens (not items — a chunk slice is several)
        #: already delivered to the caller.
        self._delivered = 0
        self._done = False
        self._got_first = False
        self._reroutes = 0
        self._backoff = Router.RETRY_BACKOFF_BASE_S
        # Latency accounting: TTFT on the first item, per-token TPOT on
        # every later arrival (a fused chunk lands `width` tokens in one
        # arrival), e2e on clean exhaustion.
        self._t0 = time.perf_counter() if t0 is None else t0
        self._last_item_at: Optional[float] = None

    @property
    def request_id(self) -> str:
        """Correlation id of this stream's logical request (stable
        across re-routes and mid-stream resumes) — the id to hand to
        ``rtblackbox --request``."""
        return self._request_id

    @property
    def resumes(self) -> int:
        """Re-routes this stream survived (setup re-picks and
        mid-stream resumes combined)."""
        return self._reroutes

    def _finish(self):
        if not self._done:
            self._done = True
            self._router.release(self._rid)

    def __iter__(self):
        return self

    def __next__(self):
        from .. import api as rt

        if self._done:
            raise StopIteration
        labels = {"deployment": self._router.deployment_name}
        while True:
            try:
                try:
                    ref = next(self._gen)
                except StopIteration:
                    if self._got_first:
                        _serve_counters()["e2e_latency"].observe(
                            time.perf_counter() - self._t0, labels=labels)
                    self._finish()
                    raise
                item = rt.get(ref)
            except StopIteration:
                raise
            except Exception as e:  # noqa: BLE001
                if self._call is None \
                        or (self._got_first and not self._resumable) \
                        or not self._reroute(e):
                    self._finish()
                    raise
                continue
            now = time.perf_counter()
            # Tokens landed by this arrival (shared width contract with
            # the replica-side suppression — see stream_item_width).
            # Empty filler slices (lockstep batch handlers) land nothing
            # and must not record a bogus 1-token sample.
            width = stream_item_width(item)
            if not self._got_first:
                self._got_first = True
                self._router.budget.record_success()
                _serve_counters()["ttft"].observe(now - self._t0,
                                                  labels=labels)
            elif width > 0:
                per_token = (now - self._last_item_at) / width
                tpot = _serve_counters()["tpot"]
                for _ in range(width):
                    tpot.observe(per_token, labels=labels)
            self._delivered += width     # the mid-stream replay token
            self._last_item_at = now
            return item

    def _reroute(self, e: Exception) -> bool:
        """Re-route a not-yet-started stream — or, when ``resumable``, a
        MID-stream one (the resubmission carries ``resume_from`` = the
        delivered-token count, so the receiving replica suppresses the
        replayed prefix). True = resubmitted. A resume never extends the
        original deadline and spends the same budget as a fresh retry."""
        labels = {"deployment": self._router.deployment_name}
        if deadline_expired(self._deadline_s) or _is_deadline_error(e):
            return False
        if _is_overload(e):
            if _is_draining(e):
                self._router.note_draining(self._rid)
            else:
                self._router.note_overloaded(self._rid)
            _serve_counters()["overload_repicks"].inc(labels=labels)
        elif _is_replica_failure(e):
            self._router.mark_dead(self._rid)
        else:
            return False
        if self._got_first:
            # A MID-stream resume is never free, whatever the trigger
            # (replica death or retryable engine restart/drain/shutdown
            # pushback): the replay re-prefills real work, so it is
            # capped and budgeted exactly like a fresh retry — the
            # documented contract, and the bound that stops a
            # crash-looping replica from being resubmitted to forever.
            if self._reroutes >= Router.DEFAULT_MAX_RETRIES \
                    or not self._router.budget.take():
                return False
            self._reroutes += 1
        elif _is_replica_failure(e):
            if self._reroutes >= Router.DEFAULT_MAX_RETRIES \
                    or not self._router.budget.take():
                return False
            self._reroutes += 1
            _serve_counters()["retries"].inc(labels=labels)
        _backoff_sleep(self._backoff, self._deadline_s)
        self._backoff = min(self._backoff * 2, Router.RETRY_BACKOFF_CAP_S)
        method, args, kwargs = self._call
        old_rid = self._rid
        try:
            rid, gen = self._router._submit_stream_raw(
                method, args, kwargs, deadline_s=self._deadline_s,
                model_id=self._model_id,
                flatten_chunks=self._flatten_chunks,
                resume_from=self._delivered if self._got_first else 0,
                request_id=self._request_id)
        except Exception:  # noqa: BLE001 - nothing admitted the re-route;
            return False   # _finish() releases the old slot exactly once
        # Old slot released only now: on the failure path mark_dead
        # already dropped the rid (release is a no-op), and releasing
        # the overloaded slot before a FAILED resubmit would let
        # _finish() decrement the same slot twice.
        self._router.release(self._rid)
        self._rid, self._gen = rid, gen
        if self._got_first:
            _serve_counters()["stream_resumes"].inc(labels=labels)
            _events.emit("router.resume", request=self._request_id,
                         deployment=self._router.deployment_name,
                         from_replica=old_rid, to_replica=rid,
                         delivered=self._delivered,
                         attempt=self._reroutes,
                         cause=type(e).__name__)
        else:
            _events.emit("router.retry", request=self._request_id,
                         deployment=self._router.deployment_name,
                         from_replica=old_rid, to_replica=rid,
                         attempt=self._reroutes,
                         cause=type(e).__name__)
        return True

    def __del__(self):
        try:
            self._finish()
        except Exception:  # noqa: BLE001 - interpreter shutdown
            pass


class DeploymentHandle:
    """Picklable handle to one deployment of one app."""

    def __init__(self, app_name: str, deployment_name: str,
                 method_name: str = "__call__",
                 multiplexed_model_id: str = "", stream: bool = False,
                 flatten_chunks: bool = False,
                 timeout_s: Optional[float] = None,
                 resumable: bool = False):
        self.app_name = app_name
        self.deployment_name = deployment_name
        self.method_name = method_name
        self.multiplexed_model_id = multiplexed_model_id
        self.stream = stream
        # Chunked-decode replicas stream per-chunk token slices; with
        # flatten_chunks the replica re-yields each slice element-wise
        # so this caller sees per-token items over the same transport.
        self.flatten_chunks = flatten_chunks
        # Per-call deadline budget: requests submitted through this
        # handle are stamped with now + timeout_s (None = router
        # default). The proxy sets this from request_timeout_s so HTTP
        # deadlines propagate end to end.
        self.timeout_s = timeout_s
        # Mid-stream failover: streams submitted through this handle
        # survive replica/driver death by deterministic replay with
        # delivered-prefix suppression. Opt-in, because it requires the
        # stream to be a deterministic function of the call (seeded
        # engine decodes are; an unseeded sampling handler is not).
        self.resumable = resumable

    def __reduce__(self):
        return (DeploymentHandle,
                (self.app_name, self.deployment_name, self.method_name,
                 self.multiplexed_model_id, self.stream,
                 self.flatten_chunks, self.timeout_s, self.resumable))

    def options(self, *, method_name: Optional[str] = None,
                multiplexed_model_id: Optional[str] = None,
                stream: Optional[bool] = None,
                flatten_chunks: Optional[bool] = None,
                timeout_s: Optional[float] = None,
                resumable: Optional[bool] = None) -> "DeploymentHandle":
        return DeploymentHandle(
            self.app_name, self.deployment_name,
            method_name or self.method_name,
            multiplexed_model_id if multiplexed_model_id is not None
            else self.multiplexed_model_id,
            self.stream if stream is None else stream,
            self.flatten_chunks if flatten_chunks is None
            else flatten_chunks,
            self.timeout_s if timeout_s is None else timeout_s,
            self.resumable if resumable is None else resumable)

    def __getattr__(self, name: str) -> "DeploymentHandle":
        if name.startswith("_"):
            raise AttributeError(name)
        return DeploymentHandle(self.app_name, self.deployment_name, name,
                                self.multiplexed_model_id, self.stream,
                                self.flatten_chunks, self.timeout_s,
                                self.resumable)

    def remote(self, *args, **kwargs):
        router = get_router(self.app_name, self.deployment_name)
        if self.stream:
            return router.submit_stream(self.method_name, args, kwargs,
                                        timeout_s=self.timeout_s,
                                        model_id=self.multiplexed_model_id,
                                        flatten_chunks=self.flatten_chunks,
                                        resumable=self.resumable)
        return router.submit(self.method_name, args, kwargs,
                             timeout_s=self.timeout_s,
                             model_id=self.multiplexed_model_id)

    def __repr__(self):
        return (f"DeploymentHandle(app={self.app_name!r}, "
                f"deployment={self.deployment_name!r})")


class Router:
    """Power-of-two-choices replica scheduler with local admission control,
    budgeted retries, and bounded-queue load shedding."""

    MEMBERSHIP_TTL_S = 1.0
    _MODEL_AFFINITY_CAP = 1024
    DEFAULT_TIMEOUT_S = 60.0
    DEFAULT_MAX_RETRIES = 3
    RETRY_BACKOFF_BASE_S = 0.05
    RETRY_BACKOFF_CAP_S = 2.0
    # Admission wait: starts fine-grained, decays to the cap while no
    # replica admits (satellite fix: the old fixed 0.05 s wait +
    # unconditional refresh() hammered the controller at ~20 Hz per
    # blocked caller when no replica was up).
    ADMISSION_BACKOFF_MIN_S = 0.02
    ADMISSION_BACKOFF_MAX_S = 1.0
    # How long an overload pushback keeps a replica out of the pick set
    # (self-expiring: the mark heals even if no completion arrives).
    SATURATION_MARK_S = 0.25

    def __init__(self, app_name: str, deployment_name: str):
        self.app_name = app_name
        self.deployment_name = deployment_name
        self.closed = False
        self._cond = threading.Condition()
        self._replicas: Dict[str, Any] = {}   # rid -> ActorHandle
        self._replica_nodes: Dict[str, Any] = {}  # rid -> node_id
        self._replica_roles: Dict[str, str] = {}  # rid -> prefill|decode|both
        self._ongoing: Dict[str, int] = {}
        self._saturated: Dict[str, float] = {}  # rid -> mark expiry
        # Draining replicas: rid -> mark expiry. A ReplicaDrainingError
        # pushback plants a FINITE mark (it outlives the saturation
        # mark, covering the controller-notification lag); a membership
        # snapshot that lists the replica as draining upgrades it to
        # INFINITE — it then clears only when the controller stops
        # listing it or membership drops it, never by timeout
        # (ISSUE 14 satellite: a draining prefill replica must not
        # self-expire back into the candidate set mid-drain).
        self._draining_marks: Dict[str, float] = {}
        self._version = -1
        # This process's node, for locality-preferring choice
        # (reference: pow_2_scheduler prefer-local-node ranking).
        try:
            from ..core.worker import CoreWorker

            core = CoreWorker._current
            self._local_node = getattr(core, "node_id", None) \
                if core is not None else None
        except Exception:  # noqa: BLE001
            self._local_node = None
        self._max_ongoing = 16
        self._max_queued = 64
        self._pending = 0  # callers blocked in the admission wait loop
        # Stable id for demand reports piggybacked on membership polls
        # (the controller keys scale-from-zero pending counts by router).
        import uuid

        self._router_id = uuid.uuid4().hex[:12]
        # Flight-recorder correlation ids: minted ONCE per logical
        # request (retries and mid-stream resumes reuse the id), so the
        # post-mortem collector can follow one request across every
        # process it touched.
        self._req_seq = 0
        self.budget = RetryBudget()
        self._last_refresh = 0.0
        self._outstanding: Dict[Any, str] = {}  # ObjectRef -> rid
        # model_id -> replica ids that served it (multiplex affinity).
        # Advisory only (the replica's LRU may have evicted the model);
        # bounded LRU + pruned to live replicas on refresh.
        from collections import OrderedDict

        self._model_affinity: "OrderedDict[str, set]" = OrderedDict()
        self._waiter_wake = threading.Event()
        self._waiter = threading.Thread(
            target=self._completion_loop, daemon=True,
            name=f"rt-serve-router-{deployment_name}")
        self._waiter.start()

    # -------------------------------------------------------------- control
    def _controller(self):
        from .. import api as rt

        return rt.get_actor(SERVE_CONTROLLER_NAME, timeout=10)

    def refresh(self, force: bool = False):
        now = time.monotonic()
        with self._cond:
            if not force and now - self._last_refresh < self.MEMBERSHIP_TTL_S:
                return
            self._last_refresh = now
            pending = self._pending
        from .. import api as rt

        try:
            info = self._controller().get_replicas.remote(
                self.app_name, self.deployment_name,
                pending=pending, router_id=self._router_id)
            info = rt.get(info, timeout=30)
        except Exception:  # noqa: BLE001 - controller down (e.g. a chaos
            # kill mid-reconcile): degrade to the cached membership so
            # in-flight streams keep routing to replicas we already know
            # about — named replica actors are detached and outlive the
            # controller, and the revived controller re-adopts them.
            if self._replicas:
                return
            raise
        if info is None:
            # A just-revived controller answers RPCs before its journal
            # replay finishes; with a cached membership the right move
            # is to keep serving it (the named replicas are still up),
            # not to error every in-flight request.
            if self._replicas:
                return
            raise RayTpuError(
                f"deployment {self.app_name}/{self.deployment_name} not found")
        self._apply_membership(info)

    def _apply_membership(self, info: dict):
        """Apply one controller membership snapshot (factored out of
        :meth:`refresh` so the draining-mark interaction is unit-
        testable without a live controller)."""
        with self._cond:
            ctrl_draining = set(info.get("draining") or ())
            if info["version"] == self._version:
                # Same version: membership unchanged, but the draining
                # set is reported fresh on every poll — reconcile the
                # marks against it (the ONLY way an infinite mark
                # heals).
                self._reconcile_draining_locked(ctrl_draining,
                                                set(self._replicas))
                return
            self._version = info["version"]
            self._max_ongoing = info["max_ongoing_requests"]
            self._max_queued = info.get("max_queued_requests",
                                        self._max_queued)
            new = dict(info["replicas"])  # rid -> ActorHandle
            self._replicas = new
            self._replica_nodes = dict(info.get("replica_nodes") or {})
            self._replica_roles = dict(info.get("replica_roles") or {})
            self._ongoing = {rid: self._ongoing.get(rid, 0) for rid in new}
            self._saturated = {rid: t for rid, t in self._saturated.items()
                               if rid in new}
            self._reconcile_draining_locked(ctrl_draining, set(new))
            # Membership changed: drop affinity entries for dead replicas.
            for mid in list(self._model_affinity):
                kept = self._model_affinity[mid] & set(new)
                if kept:
                    self._model_affinity[mid] = kept
                else:
                    del self._model_affinity[mid]
            self._cond.notify_all()

    #: Floor lifetime of a LOCALLY-noted drain mark: long enough to
    #: cover the controller-notification lag (a couple of membership
    #: polls), after which only a controller-confirmed mark persists.
    DRAIN_MARK_MIN_S = 3.0

    def _reconcile_draining_locked(self, ctrl_draining: set,
                                   alive: set):
        """Merge the controller-reported draining set into the local
        marks: confirmed marks become infinite (they heal ONLY when the
        controller stops listing the replica), local pushback marks
        keep their finite floor, and marks for departed replicas drop.
        Held: ``_cond``."""
        marks = self._draining_marks
        now = time.monotonic()
        for rid in list(marks):
            if rid not in alive:
                del marks[rid]
            elif rid in ctrl_draining:
                continue
            elif marks[rid] == float("inf"):
                del marks[rid]     # controller says the drain is over
            elif marks[rid] <= now:
                # Local pushback floor lapsed and the controller never
                # confirmed the drain: drop the mark entirely (the pick
                # filter already ignores it; leaving it would overcount
                # stats()["draining"] forever).
                del marks[rid]
        for rid in ctrl_draining & alive:
            marks[rid] = float("inf")

    def note_draining(self, rid: str):
        """Replica drain pushback: keep it out of the pick set. Unlike
        :meth:`note_overloaded` the mark does not blindly self-expire —
        it is reconciled against the controller's draining list on
        every membership poll, with a finite floor only to cover the
        notification lag."""
        with self._cond:
            if rid in self._replicas:
                cur = self._draining_marks.get(rid, 0.0)
                self._draining_marks[rid] = max(
                    cur, time.monotonic() + self.DRAIN_MARK_MIN_S)

    def mark_dead(self, rid: str):
        with self._cond:
            self._replicas.pop(rid, None)
            self._ongoing.pop(rid, None)
            self._saturated.pop(rid, None)
            self._draining_marks.pop(rid, None)
            self._last_refresh = 0.0
            self._cond.notify_all()

    def note_overloaded(self, rid: str):
        """Replica pushback: keep it out of the pick set briefly so
        re-picks spread to other replicas; the mark self-expires (the
        local in-flight estimate undercounted — other routers filled the
        replica — so waiting for our own completions would never clear
        it)."""
        with self._cond:
            if rid in self._replicas:
                self._saturated[rid] = time.monotonic() \
                    + self.SATURATION_MARK_S

    def close(self):
        self.closed = True
        self._waiter_wake.set()

    # ----------------------------------------------------------- data plane
    def _acquire(self, deadline_s: Optional[float], model_id: str,
                 role: str = "", prefer_node=None) -> Tuple[str, Any]:
        """Admission wait, instrumented: the elapsed time is the
        ``router.queue_wait`` stage — observed into the queue-wait
        histogram always, and recorded as a span when the request is
        traced (near-zero when a slot is free, the interesting tail
        when every replica is saturated)."""
        t0_wall = time.time()
        t0 = time.perf_counter()
        out = self._acquire_inner(deadline_s, model_id, role,
                                  prefer_node)
        _serve_counters()["queue_wait"].observe(
            time.perf_counter() - t0,
            labels={"deployment": self.deployment_name, "where": "router"})
        # Only under an active request span: with tracing enabled but no
        # ambient span (bare handle calls), a root-less record here
        # would mint one junk single-span trace per submission.
        if tracing.current_context() is not None:
            tracing.record_span("router.queue_wait", t0_wall,
                                deployment=self.deployment_name)
        return out

    def _acquire_inner(self, deadline_s: Optional[float], model_id: str,
                       role: str = "", prefer_node=None
                       ) -> Tuple[str, Any]:
        """Admission: block until a replica has an in-flight slot, with
        capped exponential backoff between controller refreshes.

        Sheds with ``BackPressureError`` when every known replica is
        saturated AND ``max_queued_requests`` callers are already
        waiting — bounded queues, not unbounded ones, are what keep
        accepted-request latency flat under overload. An empty replica
        set (deployment still starting) queues rather than sheds."""
        self.refresh()
        backoff = self.ADMISSION_BACKOFF_MIN_S
        queued = False
        try:
            while True:
                with self._cond:
                    rid = self._pick_locked(model_id, role, prefer_node)
                    if rid is not None:
                        self._ongoing[rid] += 1
                        return rid, self._replicas[rid]
                    if not queued:
                        if self._replicas \
                                and self._pending >= self._max_queued:
                            _serve_counters()["requests_shed"].inc(
                                labels={"deployment": self.deployment_name,
                                        "where": "router"})
                            _events.emit(
                                "router.shed",
                                deployment=self.deployment_name,
                                pending=self._pending,
                                max_queued=self._max_queued)
                            raise BackPressureError(
                                f"all replicas of {self.deployment_name} "
                                f"saturated and {self._pending} requests "
                                f"already queued "
                                f"(max_queued_requests="
                                f"{self._max_queued})")
                        self._pending += 1
                        queued = True
                    # rtsan RS104 audit (ISSUE 13): bounded wait inside
                    # a predicate loop — backoff caps at 1 s, the loop
                    # re-picks and re-checks the deadline every wake,
                    # so a lost notify costs one backoff, never a hang.
                    notified = self._cond.wait(timeout=backoff)
                if deadline_expired(deadline_s):
                    raise TimeoutError(
                        f"no replica of {self.deployment_name} accepted "
                        f"the request before its deadline")
                if notified:
                    backoff = self.ADMISSION_BACKOFF_MIN_S
                else:
                    backoff = min(backoff * 2, self.ADMISSION_BACKOFF_MAX_S)
                    self.refresh()
        finally:
            if queued:
                with self._cond:
                    self._pending -= 1

    def _stamp_deadline(self, timeout_s: Optional[float]) -> float:
        """Fresh submission deadline: now + timeout, CAPPED by the
        ambient request deadline when called from inside a replica (a
        composed deployment's nested call inherits the outer request's
        remaining time instead of minting a fresh 60 s window)."""
        deadline_s = make_deadline(
            self.DEFAULT_TIMEOUT_S if timeout_s is None else timeout_s)
        ambient = get_request_deadline()
        if ambient is not None and ambient < deadline_s:
            deadline_s = ambient
        return deadline_s

    def new_request_id(self) -> str:
        """Mint a cluster-wide request correlation id. Minted once per
        LOGICAL request — retries and mid-stream resumes re-send the
        same id — so rings from every process a request touched (alive
        or dead) join on it."""
        import os as _os

        with self._cond:
            self._req_seq += 1
            return f"rq-{_os.getpid():x}-{self._req_seq}"

    def submit(self, method_name: str, args: tuple, kwargs: dict,
               timeout_s: Optional[float] = None,
               model_id: str = "",
               deadline_s: Optional[float] = None,
               request_id: str = "") -> DeploymentResponse:
        # A fresh submission stamps its deadline once; a retry passes
        # the original deadline through so the window never restarts.
        t0 = time.perf_counter()
        if deadline_s is None:
            deadline_s = self._stamp_deadline(timeout_s)
        request_id = request_id or self.new_request_id()
        rid, handle = self._acquire(deadline_s, model_id)
        ctx = self._request_ctx(deadline_s, request_id)
        if model_id:
            with self._cond:
                self._model_affinity.setdefault(model_id, set()).add(rid)
                self._model_affinity.move_to_end(model_id)
                while len(self._model_affinity) > self._MODEL_AFFINITY_CAP:
                    self._model_affinity.popitem(last=False)
            ctx["multiplexed_model_id"] = model_id
        ref = handle.handle_request.remote(method_name, args, kwargs, ctx)
        with self._cond:
            self._outstanding[ref] = rid
        self._waiter_wake.set()
        return DeploymentResponse(self, rid, ref,
                                  (method_name, args, kwargs), model_id,
                                  deadline_s=deadline_s, t0=t0,
                                  request_id=request_id)

    def _request_ctx(self, deadline_s: Optional[float],
                     request_id: str = "") -> Dict[str, Any]:
        """Request context that rides the wire to the replica: the
        absolute deadline, the dispatch stamp (the replica measures its
        queue-wait stage against it), the flight-recorder correlation
        id, and — when the caller is traced — the wire trace context,
        so replica/batcher stage spans join the request's trace."""
        ctx: Dict[str, Any] = {"deadline_s": deadline_s,
                               SUBMITTED_AT_KEY: time.time()}
        if request_id:
            ctx[REQUEST_ID_KEY] = request_id
        tctx = tracing.current_context()
        if tctx is not None:
            ctx[TRACE_CTX_KEY] = tctx
        return ctx

    def _submit_stream_raw(self, method_name: str, args: tuple, kwargs: dict,
                           deadline_s: Optional[float], model_id: str,
                           flatten_chunks: bool, resume_from: int = 0,
                           request_id: str = "") -> Tuple[str, Any]:
        """Admission + dispatch for one stream attempt; returns
        (rid, core streaming generator). Shared by first submission and
        the generator's re-routes. ``resume_from`` is the mid-stream
        replay token: the receiving replica replays the deterministic
        stream and suppresses that many already-delivered tokens.

        Role-aware two-hop routing (ISSUE 14): when the deployment runs
        disaggregated role groups, the stream dispatch becomes pick
        prefill replica → export a leased KV handoff → pick decode
        replica (locality-preferring) → import + decode. Every failure
        on the prefill hop degrades to a LOCAL prefill on a decode
        replica — token-identical by determinism — so disaggregation
        can only ever add capacity, never a new way to break a stream.
        A resumed stream re-enters here and re-prefills on whatever
        survivors exist."""
        self.refresh()   # roles ride membership; a cold router must
        with self._cond:  # learn them BEFORE deciding the hop count
            disagg = self._roles_active()
            want_decode = self._prefill_present()
        handoff = None
        prefill_node = None
        claim = None
        if disagg:
            handoff, claim, prefill_node = self._prefill_hop(
                method_name, args, kwargs, deadline_s, model_id,
                request_id)
            if handoff is None:
                _serve_counters()["prefill_fallbacks"].inc(
                    labels={"deployment": self.deployment_name,
                            "where": "router"})
        rid, handle = self._acquire(deadline_s, model_id,
                                    role="decode" if want_decode else "",
                                    prefer_node=prefill_node)
        ctx = self._request_ctx(deadline_s, request_id)
        if model_id:
            ctx["multiplexed_model_id"] = model_id
        if flatten_chunks:
            ctx["flatten_chunks"] = True
        if resume_from:
            ctx[RESUME_FROM_KEY] = int(resume_from)
        if handoff is not None:
            ctx[HANDOFF_KEY] = handoff
        gen = handle.handle_request_streaming.options(
            num_returns="streaming").remote(method_name, args, kwargs, ctx)
        if claim is not None:
            gen = _claim_on_first(gen, claim)
        return rid, gen

    def _prefill_hop(self, method_name: str, args: tuple, kwargs: dict,
                     deadline_s: Optional[float], model_id: str,
                     request_id: str = ""):
        """Hop 1 of a disaggregated stream: a unary call to a
        prefill-role replica whose continuous-batching wrapper answers
        with a leased handoff descriptor. Budgeted and backoff-spaced
        like every retry; returns ``(descriptor, claim_fn, node_id)``
        or ``(None, None, None)`` — the caller then falls back to a
        local prefill on a decode replica (the stream must never hang
        on a missing prefill tier)."""
        from .. import api as rt

        attempts = 0
        backoff = self.RETRY_BACKOFF_BASE_S
        labels = {"deployment": self.deployment_name}
        while attempts <= self.DEFAULT_MAX_RETRIES:
            if deadline_expired(deadline_s):
                return None, None, None
            with self._cond:
                rid = self._pick_locked(model_id, role="prefill")
                if rid is None:
                    # No prefill replica admits RIGHT NOW (all dead,
                    # draining, or saturated): fall back rather than
                    # queue — a decode replica can always prefill
                    # locally.
                    return None, None, None
                self._ongoing[rid] += 1
                handle = self._replicas[rid]
            ctx = self._request_ctx(deadline_s, request_id)
            if model_id:
                ctx["multiplexed_model_id"] = model_id
            ctx[HANDOFF_KEY] = "export"
            try:
                ref = handle.handle_request.remote(
                    method_name, args, kwargs, ctx)
                rem = remaining_s(deadline_s)
                desc = rt.get(ref, timeout=min(rem, 30.0)
                              if rem is not None else 30.0)
                self.release(rid)
                if not isinstance(desc, dict) \
                        or "lease_id" not in desc:
                    # Handler is not handoff-capable (no continuous
                    # engine behind it): disable disagg for this call.
                    return None, None, None

                def claim(h=handle, d=desc):
                    try:
                        h.claim_handoff.remote(d["lease_id"],
                                               d["epoch"])
                    except Exception:  # noqa: BLE001 - lease expiry
                        pass           # sweeps the orphan anyway

                return desc, claim, desc.get("node_id")
            except Exception as e:  # noqa: BLE001 - classified below
                self.release(rid)
                if _is_deadline_error(e):
                    return None, None, None
                if _is_draining(e):
                    self.note_draining(rid)
                elif _is_overload(e):
                    self.note_overloaded(rid)
                    _serve_counters()["overload_repicks"].inc(
                        labels=labels)
                elif _is_replica_failure(e):
                    self.mark_dead(rid)
                    attempts += 1
                    if attempts > self.DEFAULT_MAX_RETRIES \
                            or not self.budget.take():
                        return None, None, None
                    _serve_counters()["retries"].inc(labels=labels)
                else:
                    # Unclassified failure (wedged-but-alive replica
                    # timing out the get, serialization trouble, a
                    # deterministic user error...): the prefill hop is
                    # an optimization, never a new way to break a
                    # stream. Fall back to local prefill — a genuine
                    # request error reproduces identically there and
                    # surfaces through the normal stream path.
                    return None, None, None
                _backoff_sleep(backoff, deadline_s)
                backoff = min(backoff * 2, self.RETRY_BACKOFF_CAP_S)
        return None, None, None

    def submit_stream(self, method_name: str, args: tuple, kwargs: dict,
                      timeout_s: Optional[float] = None, model_id: str = "",
                      flatten_chunks: bool = False,
                      resumable: bool = False
                      ) -> "DeploymentResponseGenerator":
        """Streaming dispatch: same admission + pow-2 pick as submit(),
        but the replica call rides the core streaming-generator
        transport and the in-flight slot is held until the stream ends
        (released by the DeploymentResponseGenerator, not the completion
        loop — a stream has no single completion ref to wait on). The
        deadline bounds stream SETUP (time to first item); an
        already-flowing stream may outlive it."""
        t0 = time.perf_counter()
        deadline_s = self._stamp_deadline(timeout_s)
        request_id = self.new_request_id()
        rid, gen = self._submit_stream_raw(
            method_name, args, kwargs, deadline_s=deadline_s,
            model_id=model_id, flatten_chunks=flatten_chunks,
            request_id=request_id)
        return DeploymentResponseGenerator(
            self, rid, gen, call=(method_name, args, kwargs),
            model_id=model_id, flatten_chunks=flatten_chunks,
            deadline_s=deadline_s, t0=t0, resumable=resumable,
            request_id=request_id)

    def release(self, rid: str):
        """Return one in-flight slot (stream finished or abandoned)."""
        with self._cond:
            if rid in self._ongoing:
                self._ongoing[rid] = max(0, self._ongoing[rid] - 1)
            self._cond.notify_all()

    def _prefill_present(self) -> bool:
        """True when this deployment's membership has EVER advertised a
        prefill role group (the roles map survives individual replica
        deaths until the next membership snapshot). While true, plain
        traffic must keep filtering to decode-capable replicas — a
        momentarily empty decode group (its replica just died) means
        WAIT for the controller to respawn it, never spill decode
        streams onto prefill-only replicas that reject them."""
        return any(r == "prefill"
                   for r in self._replica_roles.values())

    def _roles_active(self) -> bool:
        """True when two-hop dispatch can run RIGHT NOW: at least one
        prefill-role replica AND one decode-capable one alive. When
        only the prefill side survives, streams fall back to single-hop
        — still decode-filtered via :meth:`_prefill_present`, blocking
        in admission until decode capacity returns."""
        roles = self._replica_roles
        return any(roles.get(rid, "both") == "prefill"
                   for rid in self._replicas) and \
            any(roles.get(rid, "both") in ("decode", "both")
                for rid in self._replicas)

    def _pick_locked(self, model_id: str = "", role: str = "",
                     prefer_node=None) -> Optional[str]:
        now = time.monotonic()
        if self._saturated:
            for r in [r for r, t in self._saturated.items() if t <= now]:
                del self._saturated[r]
        draining = {r for r, t in self._draining_marks.items()
                    if t > now}
        rids = [r for r in self._replicas
                if self._ongoing.get(r, 0) < self._max_ongoing
                and r not in self._saturated and r not in draining]
        # Role filter (ISSUE 14): an explicit role picks its group
        # ("both" replicas serve either); with a prefill group present
        # and no explicit role, plain traffic targets decode-capable
        # replicas — a prefill-only engine rejects decode streams, and
        # an EMPTY decode group must mean "wait for respawn", not
        # "spill onto prefill replicas".
        want = role or ("decode" if self._prefill_present() else "")
        if want:
            rids = [r for r in rids
                    if self._replica_roles.get(r, "both")
                    in (want, "both")]
        if not rids:
            return None
        if prefer_node is not None:
            # Handoff locality: land the decode hop on the node already
            # holding the shipped bytes (the pull then rides shm, not
            # the wire).
            near = [r for r in rids
                    if self._replica_nodes.get(r) == prefer_node]
            if near:
                rids = near
        if model_id:
            # Model-affinity (reference multiplex routing): prefer a
            # replica that has already served this model — its LRU cache
            # likely still holds it, avoiding a reload.
            warm = [r for r in rids
                    if r in self._model_affinity.get(model_id, ())]
            if warm:
                rids = warm
        elif self._local_node is not None:
            # Locality: prefer same-node replicas (the response bytes
            # then ride shared memory, not TCP). Saturated locals fall
            # back to remote ones — rids is already capacity-filtered.
            local = [r for r in rids
                     if self._replica_nodes.get(r) == self._local_node]
            if local:
                rids = local
        if len(rids) <= 2:
            return min(rids, key=lambda r: self._ongoing[r])
        a, b = random.sample(rids, 2)
        return a if self._ongoing[a] <= self._ongoing[b] else b

    def _completion_loop(self):
        """Decrement in-flight counts as results land (the reference does
        this with asyncio callbacks on the replica result future)."""
        from .. import api as rt

        while not self.closed:
            with self._cond:
                refs = list(self._outstanding)
            if not refs:
                self._waiter_wake.wait(timeout=0.5)
                self._waiter_wake.clear()
                continue
            try:
                ready, _ = rt.wait(refs, num_returns=len(refs), timeout=0.05,
                                   fetch_local=False)
            except Exception:  # noqa: BLE001 - core shut down under us
                if self.closed:
                    return
                time.sleep(0.1)
                continue
            if ready:
                with self._cond:
                    for ref in ready:
                        rid = self._outstanding.pop(ref, None)
                        if rid in self._ongoing:
                            self._ongoing[rid] = max(
                                0, self._ongoing[rid] - 1)
                    self._cond.notify_all()

    # ------------------------------------------------------------- metrics
    def snapshot(self) -> Dict[str, Any]:
        with self._cond:
            return {"replicas": len(self._replicas),
                    "ongoing": dict(self._ongoing),
                    "pending": self._pending,
                    "saturated": len(self._saturated),
                    "draining": len(self._draining_marks),
                    "roles": dict(self._replica_roles),
                    "retry_tokens": self.budget.tokens(),
                    "version": self._version}
