"""Serve controller actor: deployment state machine + autoscaler + health.

Capability parity with the reference controller
(reference: ``python/ray/serve/_private/controller.py:86`` — app/deployment
state reconciliation; ``deployment_state.py`` — replica lifecycle;
``autoscaling_state.py:262`` — metrics-driven target computation), rebuilt
as a single sync actor whose reconcile loop runs on a daemon thread and
whose RPC methods run on the actor's thread pool (this runtime's actors are
thread-concurrent, not asyncio-concurrent).
"""
from __future__ import annotations

import math
import threading
import time
import traceback
from typing import Any, Dict, List, Optional

from .config import AutoscalingConfig, DeploymentConfig


class ServeController:
    RECONCILE_INTERVAL_S = 0.1

    def __init__(self):
        # Lock order: _reconcile_lock (outer, serializes every scaling /
        # teardown mutation across the RPC threads and the loop thread)
        # then _lock (inner, guards state reads/writes).
        self._reconcile_lock = threading.RLock()
        self._lock = threading.RLock()
        self._apps: Dict[str, dict] = {}
        self._http_info: Optional[dict] = None
        self._replica_counter = 0
        self._stop = threading.Event()
        self._loop_thread = threading.Thread(
            target=self._reconcile_loop, daemon=True, name="rt-serve-ctrl")
        self._loop_thread.start()

    # -------------------------------------------------------------- deploy
    def deploy_app(self, spec: dict) -> dict:
        """Deploy (or redeploy) an application.

        ``spec`` = {name, route_prefix, ingress,
        deployments: [{name, payload, config: DeploymentConfig}]}.
        Blocks until every deployment has its initial target of healthy
        replicas (reference: ``serve.run(..., _blocking=True)``).
        """
        name = spec["name"]
        with self._reconcile_lock:
            with self._lock:
                app = self._apps.setdefault(
                    name, {"name": name, "route_prefix": None,
                           "ingress": None, "deployments": {}})
                app["route_prefix"] = spec.get("route_prefix")
                app["ingress"] = spec["ingress"]
                app["stream"] = bool(spec.get("stream"))
                wanted = {d["name"] for d in spec["deployments"]}
                removed = [app["deployments"].pop(dname)
                           for dname in list(app["deployments"])
                           if dname not in wanted]
            for dstate in removed:
                self._teardown_deployment(dstate)
            # _apply_deployment only mutates state under _lock; the
            # blocking replica RPCs it schedules (teardown of replaced
            # deployments, reconfigure fan-out) run here, outside _lock,
            # so status()/get_replicas() stay responsive during redeploys.
            deferred = []
            with self._lock:
                for dspec in spec["deployments"]:
                    deferred.extend(self._apply_deployment(app, dspec))
            for action in deferred:
                action()
            self._reconcile_once()
        deadline = time.time() + 60
        while time.time() < deadline:
            if self._app_ready(name):
                return self.status()
            time.sleep(0.05)
        raise TimeoutError(f"app {name!r} did not become ready")

    def _apply_deployment(self, app: dict, dspec: dict) -> list:
        """Mutate deployment state; returns deferred blocking actions for
        the caller to run outside the state lock."""
        dname = dspec["name"]
        cfg: DeploymentConfig = dspec["config"]
        cur = app["deployments"].get(dname)
        deferred = []
        if cur is not None and cur["payload"] == dspec["payload"]:
            if cur["config"] != cfg:
                cur["config"] = cfg
                cur["target"] = cfg.initial_target()
                replicas = list(cur["replicas"].values())
                deferred.append(lambda: [
                    self._call_quietly(r["handle"].reconfigure,
                                       cfg.user_config) for r in replicas])
                cur["version"] += 1
            return deferred
        if cur is not None:
            deferred.append(lambda c=cur: self._teardown_deployment(c))
        app["deployments"][dname] = {
            "app": app["name"],
            "name": dname,
            "payload": dspec["payload"],
            "config": cfg,
            "target": cfg.initial_target(),
            "version": 0,
            "replicas": {},
            "scale": {"desired": None, "since": 0.0, "last_metric": 0.0},
            "last_health": 0.0,
        }
        return deferred

    def _teardown_deployment(self, dstate: dict):
        from .. import api as rt

        with self._reconcile_lock:
            with self._lock:
                dstate["deleted"] = True
                victims = list(dstate["replicas"].values())
                dstate["replicas"] = {}
            for r in victims:
                self._call_quietly(
                    r["handle"].drain,
                    dstate["config"].graceful_shutdown_timeout_s)
                try:
                    rt.kill(r["handle"])
                except Exception:  # noqa: BLE001
                    pass

    # ------------------------------------------------------------ queries
    def get_replicas(self, app_name: str, deployment_name: str
                     ) -> Optional[dict]:
        with self._lock:
            app = self._apps.get(app_name)
            if app is None:
                return None
            d = app["deployments"].get(deployment_name)
            if d is None:
                return None
            return {"version": d["version"],
                    "max_ongoing_requests": d["config"].max_ongoing_requests,
                    "replicas": {rid: r["handle"]
                                 for rid, r in d["replicas"].items()}}

    def get_routes(self) -> Dict[str, dict]:
        with self._lock:
            out = {}
            for name, app in self._apps.items():
                if app["route_prefix"]:
                    out[app["route_prefix"]] = {
                        "app": name, "ingress": app["ingress"],
                        "stream": bool(app.get("stream"))}
            return out

    def get_ingress(self, app_name: str) -> Optional[str]:
        with self._lock:
            app = self._apps.get(app_name)
            return app["ingress"] if app else None

    def status(self) -> dict:
        with self._lock:
            apps = {}
            for name, app in self._apps.items():
                deps = {}
                for dname, d in app["deployments"].items():
                    n_healthy = len(d["replicas"])
                    deps[dname] = {
                        "status": ("HEALTHY" if n_healthy >= d["target"]
                                   else "UPDATING"),
                        "replicas": n_healthy,
                        "target": d["target"],
                    }
                apps[name] = {"route_prefix": app["route_prefix"],
                              "ingress": app["ingress"],
                              "deployments": deps}
            return {"applications": apps, "http": self._http_info}

    def set_http_info(self, info: dict):
        self._http_info = info

    def get_http_info(self) -> Optional[dict]:
        return self._http_info

    def delete_app(self, name: str) -> bool:
        with self._lock:
            app = self._apps.pop(name, None)
        if app is None:
            return False
        for d in app["deployments"].values():
            self._teardown_deployment(d)
        return True

    def shutdown_serve(self):
        self._stop.set()
        for name in list(self._apps):
            self.delete_app(name)
        return True

    def ping(self) -> bool:
        return True

    # --------------------------------------------------------- reconcile
    def _app_ready(self, name: str) -> bool:
        with self._lock:
            app = self._apps.get(name)
            if app is None:
                return False
            return all(len(d["replicas"]) >= d["target"]
                       for d in app["deployments"].values())

    def _reconcile_loop(self):
        while not self._stop.wait(self.RECONCILE_INTERVAL_S):
            try:
                self._reconcile_once()
            except Exception:  # noqa: BLE001 - keep the loop alive
                traceback.print_exc()

    def _reconcile_once(self):
        with self._reconcile_lock:
            with self._lock:
                work = [(app_name, dname, d)
                        for app_name, app in self._apps.items()
                        for dname, d in app["deployments"].items()]
            for app_name, dname, d in work:
                if d.get("deleted"):
                    continue
                try:
                    self._health_check(d)
                    self._autoscale(d)
                    self._scale_to_target(app_name, dname, d)
                except Exception:  # noqa: BLE001
                    traceback.print_exc()

    def _health_check(self, d: dict):
        from .. import api as rt

        period = d["config"].health_check_period_s
        if time.time() - d["last_health"] < period:
            return
        d["last_health"] = time.time()
        with self._lock:
            probes = [(rid, r["handle"].check_health.remote())
                      for rid, r in d["replicas"].items()]
        dead = []
        for rid, ref in probes:
            try:
                ok = rt.get(ref, timeout=5)
                if not ok:
                    dead.append(rid)
            except Exception:  # noqa: BLE001 - died or hung
                dead.append(rid)
        if dead:
            with self._lock:
                for rid in dead:
                    r = d["replicas"].pop(rid, None)
                    if r is not None:
                        try:
                            rt.kill(r["handle"])
                        except Exception:  # noqa: BLE001
                            pass
                d["version"] += 1

    def _autoscale(self, d: dict):
        from .. import api as rt

        ac: Optional[AutoscalingConfig] = d["config"].autoscaling_config
        if ac is None:
            return
        if time.time() - d["scale"]["last_metric"] < ac.metrics_interval_s:
            return
        d["scale"]["last_metric"] = time.time()
        with self._lock:
            refs = [r["handle"].get_metrics.remote()
                    for r in d["replicas"].values()]
        total_ongoing = 0.0
        for ref in refs:
            try:
                m = rt.get(ref, timeout=5)
                total_ongoing += m["ongoing"]
            except Exception:  # noqa: BLE001 - health loop reaps it
                pass
        cur = d["target"]
        desired = math.ceil(total_ongoing / max(ac.target_ongoing_requests,
                                                1e-9))
        desired = max(ac.min_replicas, min(ac.max_replicas, desired))
        sc = d["scale"]
        if desired == cur:
            sc["desired"] = None
            return
        if sc["desired"] != desired:
            sc["desired"] = desired
            sc["since"] = time.time()
            return
        delay = ac.upscale_delay_s if desired > cur else ac.downscale_delay_s
        if time.time() - sc["since"] >= delay:
            d["target"] = desired
            sc["desired"] = None

    def _scale_to_target(self, app_name: str, dname: str, d: dict):
        from .. import api as rt

        with self._lock:
            have = len(d["replicas"])
            target = d["target"]
            cfg = d["config"]
        if have < target:
            new = [self._start_replica(app_name, dname, d)
                   for _ in range(target - have)]
            ok = []
            for rid, handle in new:
                try:
                    handle._wait_ready(timeout=60)
                    ok.append((rid, handle))
                except Exception:  # noqa: BLE001
                    traceback.print_exc()
            if ok:
                with self._lock:
                    for rid, handle in ok:
                        d["replicas"][rid] = {"handle": handle,
                                              "created": time.time()}
                    d["version"] += 1
        elif have > target:
            with self._lock:
                victims = sorted(d["replicas"].items(),
                                 key=lambda kv: kv[1]["created"],
                                 reverse=True)[:have - target]
                for rid, _ in victims:
                    d["replicas"].pop(rid, None)
                d["version"] += 1
            for rid, r in victims:
                self._call_quietly(r["handle"].drain,
                                   cfg.graceful_shutdown_timeout_s)
                try:
                    rt.kill(r["handle"])
                except Exception:  # noqa: BLE001
                    pass

    def _start_replica(self, app_name: str, dname: str, d: dict):
        from .. import api as rt
        from ._replica import Replica

        cfg: DeploymentConfig = d["config"]
        self._replica_counter += 1
        rid = f"{dname}#{self._replica_counter}"
        opts = dict(cfg.ray_actor_options)
        opts.setdefault("num_cpus", 1)
        actor_cls = rt.remote(Replica).options(
            max_concurrency=cfg.max_ongoing_requests + 4, **opts)
        handle = actor_cls.remote(app_name, dname, rid, d["payload"],
                                  cfg.user_config)
        return rid, handle

    @staticmethod
    def _call_quietly(method, *args):
        from .. import api as rt

        try:
            rt.get(method.remote(*args), timeout=10)
        except Exception:  # noqa: BLE001
            pass
