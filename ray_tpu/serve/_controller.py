"""Serve controller actor: deployment state machine + autoscaler + health.

Capability parity with the reference controller
(reference: ``python/ray/serve/_private/controller.py:86`` — app/deployment
state reconciliation; ``deployment_state.py`` — replica lifecycle;
``autoscaling_state.py:262`` — metrics-driven target computation), rebuilt
as a single sync actor whose reconcile loop runs on a daemon thread and
whose RPC methods run on the actor's thread pool (this runtime's actors are
thread-concurrent, not asyncio-concurrent).
"""
from __future__ import annotations

import math
import threading
import time
import traceback
from typing import Any, Dict, List, Optional

from .._private import events as _events
from .autoscaler import (PLAIN_GROUP, Autoscaler, DesiredStateJournal,
                         replica_actor_name)
from .config import AutoscalingConfig, DeploymentConfig


class ServeController:
    RECONCILE_INTERVAL_S = 0.1

    def __init__(self):
        # Lock order: _reconcile_lock (outer, serializes every scaling /
        # teardown mutation across the RPC threads and the loop thread)
        # then _lock (inner, guards state reads/writes).
        self._reconcile_lock = threading.RLock()
        self._lock = threading.RLock()
        self._apps: Dict[str, dict] = {}
        self._http_info: Optional[dict] = None
        self._replica_counter = 0
        # SLO-driven autoscaling + crash-safe desired state (ISSUE 17):
        # the autoscaler turns health-pass signals into bounded scaling
        # decisions; the journal write-aheads every target change and
        # replica intent to the cluster KV so a SIGKILLed controller's
        # successor resumes reconciliation idempotently (_maybe_recover).
        self._autoscaler = Autoscaler()
        self._journal = DesiredStateJournal()
        self._recovered = False
        # dname -> (tpot_p95_or_None, fetched_at): head-merged latency,
        # refreshed at most ~1/s for deployments with a TPOT SLO.
        self._tpot_cache: Dict[str, tuple] = {}
        # Test hook (mirrors engine.inject_fault): named reconcile
        # points that hard-exit the controller process, for crash-safe
        # reconciliation chaos tests.
        self._crash_points: set = set()
        # Proxy fleet (reference: proxy_state_manager — one proxy per
        # node): node_id -> {"handle", "info"}. Populated once
        # ensure_proxies() records the bind options.
        self._proxies: Dict[str, dict] = {}
        self._proxy_opts: Optional[dict] = None
        # node_id -> {"shed_total", "expired_total"} pulled from each
        # proxy on the health pass (request-lifecycle visibility).
        self._proxy_stats: Dict[str, dict] = {}
        self._stop = threading.Event()
        self._loop_thread = threading.Thread(
            target=self._reconcile_loop, daemon=True, name="rt-serve-ctrl")
        self._loop_thread.start()

    # -------------------------------------------------------------- deploy
    def deploy_app(self, spec: dict) -> dict:
        """Deploy (or redeploy) an application.

        ``spec`` = {name, route_prefix, ingress,
        deployments: [{name, payload, config: DeploymentConfig}]}.
        Blocks until every deployment has its initial target of healthy
        replicas (reference: ``serve.run(..., _blocking=True)``).
        """
        name = spec["name"]
        with self._reconcile_lock:
            # Adopt any journaled fleet FIRST: a redeploy racing a
            # controller restart must see the adopted replicas or it
            # would start a duplicate set (double scale-up).
            self._maybe_recover()
            with self._lock:
                app = self._apps.setdefault(
                    name, {"name": name, "route_prefix": None,
                           "ingress": None, "deployments": {}})
                app["route_prefix"] = spec.get("route_prefix")
                app["ingress"] = spec["ingress"]
                app["stream"] = bool(spec.get("stream"))
                wanted = {d["name"] for d in spec["deployments"]}
                removed = [app["deployments"].pop(dname)
                           for dname in list(app["deployments"])
                           if dname not in wanted]
            for dstate in removed:
                self._teardown_deployment(dstate)
            # _apply_deployment only mutates state under _lock; the
            # blocking replica RPCs it schedules (teardown of replaced
            # deployments, reconfigure fan-out) run here, outside _lock,
            # so status()/get_replicas() stay responsive during redeploys.
            deferred = []
            with self._lock:
                for dspec in spec["deployments"]:
                    deferred.extend(self._apply_deployment(app, dspec))
            for action in deferred:
                action()
            # Journal the app spec + desired targets BEFORE the first
            # reconcile actuates them: a controller killed mid-rollout
            # must find the full desired state, not a torso.
            self._journal_app(name)
            self._reconcile_once()
        deadline = time.time() + 60
        while time.time() < deadline:
            if self._app_ready(name):
                return self.status()
            time.sleep(0.05)
        raise TimeoutError(f"app {name!r} did not become ready")

    def _apply_deployment(self, app: dict, dspec: dict) -> list:
        """Mutate deployment state; returns deferred blocking actions for
        the caller to run outside the state lock."""
        dname = dspec["name"]
        cfg: DeploymentConfig = dspec["config"]
        cur = app["deployments"].get(dname)
        deferred = []
        if cur is not None and cur["payload"] == dspec["payload"]:
            if cur["config"] != cfg:
                cur["config"] = cfg
                cur["target"] = cfg.initial_target()
                cur["role_targets"] = self._role_targets(cfg)
                replicas = list(cur["replicas"].values())
                deferred.append(lambda: [
                    self._call_quietly(r["handle"].reconfigure,
                                       cfg.user_config) for r in replicas])
                cur["version"] += 1
            return deferred
        if cur is not None:
            deferred.append(lambda c=cur: self._teardown_deployment(c))
        app["deployments"][dname] = {
            "app": app["name"],
            "name": dname,
            "payload": dspec["payload"],
            "config": cfg,
            "target": cfg.initial_target(),
            # Heterogeneous role groups within ONE deployment
            # (ISSUE 14): an ``engine: roles: {prefill: n, decode: m}``
            # block reconciles per role — each replica is started with
            # its role stamped into its engine config, and roles scale
            # and drain independently.
            "role_targets": self._role_targets(cfg),
            "version": 0,
            "replicas": {},
            "scale": {"desired": None, "since": 0.0, "last_metric": 0.0},
            "last_health": 0.0,
        }
        return deferred

    @staticmethod
    def _role_targets(cfg: DeploymentConfig) -> Optional[Dict[str, int]]:
        eng = cfg.engine_config or {}
        roles = eng.get("roles")
        if eng.get("role") == "prefill":
            # The bare spelling pins EVERY replica's engine to one
            # role, but only a ``roles:`` group teaches the controller
            # and router to two-hop — an all-prefill deployment would
            # hard-fail every plain stream (engine.submit refuses on a
            # prefill-role engine). Same trap the roles-block guard
            # below rejects, so reject this spelling too.
            raise ValueError(
                "engine role 'prefill' cannot be applied "
                "deployment-wide (no replica could decode); use "
                "roles: {prefill: n, decode: m} for disaggregation")
        if roles and eng.get("role"):
            raise ValueError(
                "engine block carries both 'role' and 'roles'; pick "
                "one (a roles: group stamps each replica's role)")
        if not roles:
            return None
        out = {}
        for role, n in roles.items():
            if role not in ("prefill", "decode", "both"):
                raise ValueError(f"unknown engine role {role!r} in "
                                 f"roles block {roles}")
            if int(n) < 0:
                raise ValueError(f"negative target for role {role!r}")
            out[role] = int(n)
        if out.get("prefill", 0) > 0 and \
                out.get("decode", 0) + out.get("both", 0) == 0:
            # A prefill-only fleet can never finish a stream: the
            # router filters all traffic to decode-capable replicas
            # the moment a prefill role exists, so every request would
            # queue until its deadline. Reject at deploy time.
            raise ValueError(
                f"roles block {roles} has prefill replicas but no "
                f"decode-capable ones (decode/both); streams could "
                f"never complete")
        return out

    def _teardown_deployment(self, dstate: dict):
        with self._reconcile_lock:
            with self._lock:
                dstate["deleted"] = True
                victims = list(dstate["replicas"].values())
                dstate["replicas"] = {}
                dstate["version"] += 1
            self._drain_and_kill(
                victims, dstate["config"].graceful_shutdown_timeout_s,
                dstate["name"], app_name=dstate.get("app"))

    def _drain_and_kill(self, victims: list, timeout_s: float,
                        deployment: str, app_name: Optional[str] = None):
        """Graceful drain before any teardown (reconfigure, scale-down,
        health replacement, app delete), then the kill: each replica
        stops admitting (retryable pushback → routers re-pick), running
        engine lanes finish, stragglers fail retryably so clients
        resume elsewhere. Drains are fired in PARALLEL and gathered
        under ONE shared budget — N stalled victims cost the same wall
        time as one, so a wide scale-down cannot wedge the control
        loop. Drain count/duration are observed HERE — the controller
        outlives the replica, so the observation always ships.

        With ``app_name`` the victims are journaled CONDEMNED before
        the first drain RPC (crash-safe scale-down, ISSUE 17): a
        controller killed anywhere in this method leaves its successor
        a durable instruction to re-drain and kill them — named
        replicas are detached actors and would otherwise outlive
        everyone as orphans."""
        from .. import api as rt
        from .._private.metrics import serve_metrics

        if not victims:
            return
        if app_name is not None:
            try:
                self._journal_intents(
                    app_name, deployment,
                    {r["rid"]: ("condemned", r.get("role"))
                     for r in victims if r.get("rid")})
            except Exception:  # noqa: BLE001 - journal lag; drain anyway
                traceback.print_exc()
            self._maybe_crash("drain_condemned")
        _events.emit("controller.drain", phase="begin",
                     deployment=deployment,
                     replicas=[r.get("rid", "") for r in victims],
                     timeout_s=timeout_s)
        t0 = time.time()
        refs = []
        for r in victims:
            try:
                refs.append(r["handle"].drain.remote(timeout_s))
            except Exception:  # noqa: BLE001 - already-dead actor
                pass
        if refs:
            try:
                rt.wait(refs, num_returns=len(refs),
                        timeout=timeout_s + 2)
            except Exception:  # noqa: BLE001 - degrade to the kills
                pass
        self._maybe_crash("drain_pre_kill")
        sm = serve_metrics()
        labels = {"deployment": deployment}
        dt = time.time() - t0
        for r in victims:
            sm["replica_drains"].inc(labels=labels)
            sm["drain_duration"].observe(dt, labels=labels)
            try:
                rt.kill(r["handle"])
            except Exception:  # noqa: BLE001
                pass
        _events.emit("controller.drain", phase="end",
                     deployment=deployment,
                     replicas=[r.get("rid", "") for r in victims],
                     elapsed_s=round(dt, 3))
        if app_name is not None:
            try:
                self._journal_intents(
                    app_name, deployment,
                    {r["rid"]: None for r in victims if r.get("rid")})
            except Exception:  # noqa: BLE001 - stale CONDEMNED entries
                # are re-killed (idempotent) by the next recovery sweep
                traceback.print_exc()

    # ------------------------------------------------------------ queries
    def get_replicas(self, app_name: str, deployment_name: str,
                     pending: int = 0, router_id: str = ""
                     ) -> Optional[dict]:
        # Routers piggyback their blocked-admission queue depth on the
        # membership refresh (ISSUE 17): with zero replicas there is no
        # replica to report load, so this is the scale-from-zero demand
        # signal. Reports of 0 matter too — they clear the demand.
        if router_id:
            self._autoscaler.note_pending(app_name, deployment_name,
                                          router_id, pending, time.time())
        with self._lock:
            app = self._apps.get(app_name)
            if app is None:
                return None
            d = app["deployments"].get(deployment_name)
            if d is None:
                return None
            return {"version": d["version"],
                    "max_ongoing_requests": d["config"].max_ongoing_requests,
                    # Router-side pending bound before shedding with
                    # BackPressureError (request-lifecycle layer).
                    "max_queued_requests": getattr(
                        d["config"], "max_queued_requests", 64),
                    "replicas": {rid: r["handle"]
                                 for rid, r in d["replicas"].items()},
                    # rid -> node_id, for locality-preferring routing
                    # (reference: pow_2_scheduler prefer_local_node).
                    "replica_nodes": {rid: r.get("node_id")
                                      for rid, r in d["replicas"].items()},
                    # Disaggregation role groups (ISSUE 14): routers
                    # two-hop generation across prefill/decode groups.
                    "replica_roles": {rid: r.get("role") or "both"
                                      for rid, r in
                                      d["replicas"].items()},
                    # Replicas mid-graceful-drain: routers must keep
                    # them OUT of the pick set until this list clears
                    # (a drain pushback mark must not self-expire).
                    "draining": [rid for rid, r in d["replicas"].items()
                                 if r.get("draining")]}

    def get_routes(self) -> Dict[str, dict]:
        with self._lock:
            out = {}
            for name, app in self._apps.items():
                if app["route_prefix"]:
                    out[app["route_prefix"]] = {
                        "app": name, "ingress": app["ingress"],
                        "stream": bool(app.get("stream"))}
            return out

    def get_ingress(self, app_name: str) -> Optional[str]:
        with self._lock:
            app = self._apps.get(app_name)
            return app["ingress"] if app else None

    def status(self) -> dict:
        with self._lock:
            apps = {}
            for name, app in self._apps.items():
                deps = {}
                for dname, d in app["deployments"].items():
                    n_healthy = len(d["replicas"])
                    role_targets = d.get("role_targets")
                    if role_targets:
                        # Role-split deployments (ISSUE 14): the fleet
                        # target is the SUM over role groups, and the
                        # deployment is healthy only when EVERY group
                        # meets its own target — one surviving prefill
                        # replica serves nothing if both decode
                        # replicas are gone.
                        role_counts: Dict[str, int] = {}
                        for r in d["replicas"].values():
                            rr = r.get("role") or "both"
                            role_counts[rr] = role_counts.get(rr, 0) + 1
                        target = sum(role_targets.values())
                        healthy = all(role_counts.get(role, 0) >= n
                                      for role, n in role_targets.items())
                    else:
                        target = d["target"]
                        healthy = n_healthy >= target
                    deps[dname] = {
                        "status": "HEALTHY" if healthy else "UPDATING",
                        "replicas": n_healthy,
                        "target": target,
                        # Shed/expired/overload visibility (collected on
                        # the health pass; see _health_check).
                        "lifecycle": dict(d.get("lifecycle") or
                                          {"expired": 0, "overloaded": 0,
                                           "total": 0, "drains": 0}),
                    }
                    # Paged decode-engine visibility (pages free/used,
                    # prefix hits, COW forks), same health-pass ride.
                    if d.get("engine"):
                        deps[dname]["engine"] = dict(d["engine"])
                    # Autoscaler diagnosability (ISSUE 17): per-group
                    # signal freshness next to the engine block — a
                    # held decision (stale_signal / missing_signal) is
                    # explicable from status() alone — plus the last
                    # decision per group.
                    if d["config"].autoscaling_config is not None \
                            or role_targets:
                        groups: Dict[str, list] = {}
                        if role_targets:
                            for role in role_targets:
                                groups[role] = [
                                    rid for rid, r in
                                    d["replicas"].items()
                                    if (r.get("role") or "both") == role]
                        else:
                            groups[PLAIN_GROUP] = list(d["replicas"])
                        deps[dname]["signal_age_s"] = \
                            self._autoscaler.signal_ages(
                                name, dname, groups, time.time())
                        last = self._autoscaler.last_decisions(name,
                                                               dname)
                        if last:
                            deps[dname]["autoscale"] = last
                apps[name] = {"route_prefix": app["route_prefix"],
                              "ingress": app["ingress"],
                              "deployments": deps}
            proxy_stats = dict(self._proxy_stats)
            lifecycle = {
                "proxy_shed_total": sum(s.get("shed_total", 0)
                                        for s in proxy_stats.values()),
                "proxy_expired_total": sum(s.get("expired_total", 0)
                                           for s in proxy_stats.values()),
            }
            out = {"applications": apps, "http": self._http_info,
                   "lifecycle": lifecycle}
        self._attach_latency(out)
        return out

    def _attach_latency(self, status: dict):
        """Per-deployment latency block (p50/p95/p99 from the
        cluster-merged histogram buckets): e2e, TTFT, and TPOT as
        observed by every caller-side router in the cluster, plus the
        queue-wait split. Best-effort — a head hiccup leaves status
        without the block rather than failing it. Runs OUTSIDE the state
        lock (it is an RPC to the head)."""
        try:
            from ..core.worker import CoreWorker

            merged = CoreWorker.current().head_call("metrics_merged")
        except Exception:  # noqa: BLE001 - status stays useful without it
            return
        from .._private.metrics import histogram_summary

        for app in status["applications"].values():
            for dname, d in app["deployments"].items():
                block = {}
                for key, metric in (
                        ("e2e", "serve_request_e2e_seconds"),
                        ("ttft", "serve_ttft_seconds"),
                        ("tpot", "serve_tpot_seconds")):
                    s = histogram_summary(merged, metric,
                                          {"deployment": dname})
                    if s is not None:
                        block[key] = s
                for where in ("router", "replica"):
                    s = histogram_summary(
                        merged, "serve_queue_wait_seconds",
                        {"deployment": dname, "where": where})
                    if s is not None:
                        block[f"queue_wait_{where}"] = s
                if block:
                    d["latency"] = block

    def set_http_info(self, info: dict):
        # rtlint RT101 (real finding): every other writer/reader of
        # _http_info holds _lock; an unguarded RPC write here could be
        # lost under a concurrent _reconcile_proxies publish.
        with self._lock:
            self._http_info = info

    def get_http_info(self) -> Optional[dict]:
        return self._http_info

    def delete_app(self, name: str) -> bool:
        with self._lock:
            app = self._apps.pop(name, None)
        if app is None:
            return False
        for d in app["deployments"].values():
            self._teardown_deployment(d)
        # Journal LAST: the condemn/kill path above is crash-safe on
        # its own, and clearing first would leave a killed controller's
        # successor no instruction to finish the teardown.
        try:
            self._journal.del_app(name)
        except Exception:  # noqa: BLE001 - stale journal; recovery
            # re-drains the (already dead) fleet idempotently
            traceback.print_exc()
        self._autoscaler.forget(name)
        return True

    def shutdown_serve(self):
        from .. import api as rt

        self._stop.set()
        for name in list(self._apps):
            self.delete_app(name)
        # Under _reconcile_lock: an in-flight _reconcile_proxies could
        # otherwise finish creating a proxy AFTER this teardown and
        # leak it (still holding the SERVE_PROXY name) past shutdown.
        with self._reconcile_lock:
            with self._lock:
                proxies, self._proxies = dict(self._proxies), {}
                self._proxy_opts = None
            for p in proxies.values():
                try:
                    rt.kill(p["handle"])
                except Exception:  # noqa: BLE001
                    pass
        return True

    def ping(self) -> bool:
        return True

    # --------------------------------------------------------- reconcile
    def _app_ready(self, name: str) -> bool:
        with self._lock:
            app = self._apps.get(name)
            if app is None:
                return False
            return all(len(d["replicas"]) >= d["target"]
                       for d in app["deployments"].values())

    def _reconcile_loop(self):
        while not self._stop.wait(self.RECONCILE_INTERVAL_S):
            try:
                self._reconcile_once()
            except Exception:  # noqa: BLE001 - keep the loop alive
                traceback.print_exc()

    def _reconcile_once(self):
        with self._reconcile_lock:
            try:
                self._maybe_recover()
            except Exception:  # noqa: BLE001 - retried next tick
                traceback.print_exc()
            try:
                self._reconcile_proxies()
            except Exception:  # noqa: BLE001
                traceback.print_exc()
            with self._lock:
                work = [(app_name, dname, d)
                        for app_name, app in self._apps.items()
                        for dname, d in app["deployments"].items()]
            for app_name, dname, d in work:
                if d.get("deleted"):
                    continue
                try:
                    self._health_check(d)
                    self._autoscale(d)
                    self._scale_to_target(app_name, dname, d)
                except Exception:  # noqa: BLE001
                    traceback.print_exc()

    #: Whole-pass budget for gathering health probes. A replica that
    #: accepts the RPC but never replies used to wedge the entire pass
    #: (serial per-probe waits); now the pass waits AT MOST this long in
    #: aggregate and any probe still unanswered counts as FAILED.
    _HEALTH_PROBE_TIMEOUT_S = 5.0

    def _health_check(self, d: dict):
        from .. import api as rt

        period = d["config"].health_check_period_s
        ac = d["config"].autoscaling_config
        if ac is not None:
            # The health pass doubles as the autoscaler's signal
            # scrape: cap its cadence at the configured metrics
            # interval so decision freshness tracks the config, not
            # the (coarser) health period.
            period = min(period, max(ac.metrics_interval_s, 0.05))
        if time.time() - d["last_health"] < period:
            return
        d["last_health"] = time.time()
        with self._lock:
            probes = [(rid, r["handle"].check_health.remote(),
                       r["handle"].get_metrics.remote())
                      for rid, r in d["replicas"].items()]
        if not probes:
            return
        # Bounded gather: one shared deadline for the whole pass, not a
        # fresh window per replica — N wedged replicas cost the same as
        # one. Probes not ready at the deadline are failed probes.
        deadline = time.monotonic() + self._HEALTH_PROBE_TIMEOUT_S
        try:
            ready, _ = rt.wait([ref for _rid, ref, _m in probes],
                               num_returns=len(probes),
                               timeout=self._HEALTH_PROBE_TIMEOUT_S)
            ready = set(ready)
        except Exception:  # noqa: BLE001 - degrade to bounded gets
            ready = {ref for _rid, ref, _m in probes}
        dead = []
        # Live-replica lifecycle totals (expired / overloaded / served),
        # piggybacked on the health pass and surfaced via status().
        life = {"expired": 0, "overloaded": 0, "total": 0, "drains": 0}
        # Engine page/prefix totals (paged decode engines only),
        # summed across replicas, same piggyback.
        engine: dict = {}
        for rid, ref, mref in probes:
            try:
                if ref not in ready:
                    raise TimeoutError(
                        f"health probe to {rid} unanswered after "
                        f"{self._HEALTH_PROBE_TIMEOUT_S}s")
                ok = rt.get(ref,
                            timeout=max(deadline - time.monotonic(), 0.1))
                if not ok:
                    dead.append(rid)
                    continue
            except Exception:  # noqa: BLE001 - died, hung, or timed out
                dead.append(rid)
                continue
            # Metrics scrape is best-effort: only a failed HEALTH probe
            # may kill a replica — a momentarily stalled get_metrics
            # (e.g. user code holding the GIL through a long compile)
            # must not take down a healthy replica.
            try:
                m = rt.get(mref,
                           timeout=max(deadline - time.monotonic(), 0.1))
                # Autoscaler signal feed (ISSUE 17): a replica whose
                # scrape fails simply records nothing this round, and
                # the decision loop degrades to a hold for its group.
                self._autoscaler.record(d["app"], d["name"], rid, m,
                                        time.time())
                life["expired"] += int(m.get("expired", 0))
                life["overloaded"] += int(m.get("overloaded", 0))
                life["total"] += int(m.get("total", 0))
                life["drains"] += int(m.get("drains", 0))
                for est in m.get("engines") or []:
                    for key in ("pages_free", "pages_used",
                                "prefix_hits", "cow_copies",
                                "admissions_deferred", "lane_parks",
                                "preempted", "prefix_tokens_reused",
                                "active_slots", "slots", "queue_depth",
                                "resumed", "driver_restarts",
                                "attn_kernel_dispatches"):
                        if key in est:
                            engine[key] = engine.get(key, 0) + est[key]
                    engine["paged"] = engine.get("paged", False) \
                        or bool(est.get("paged"))
                    # Kernel/quantization identity (ISSUE 16): config,
                    # not counters — pass through, don't sum. Replicas
                    # of one deployment share the knobs, so last wins.
                    for key in ("attn_kernel", "kv_dtype",
                                "kv_bytes_per_token", "tp"):
                        if key in est:
                            engine[key] = est[key]
                    sp = est.get("spec")
                    if sp:
                        agg = engine.setdefault(
                            "spec", {"drafter": sp.get("drafter", "")})
                        for key in ("rounds", "proposed", "accepted",
                                    "lanes", "fallback_rounds"):
                            agg[key] = agg.get(key, 0) + int(
                                sp.get(key, 0))
                    ev = est.get("events")
                    if ev and ev.get("enabled"):
                        # Flight-recorder health (ISSUE 19): summed
                        # emit/drop totals plus the WORST ring fill —
                        # a deployment-wide view of whether the rings
                        # are keeping up, from serve.status() alone.
                        agg = engine.setdefault("events", {})
                        for key in ("emitted", "dropped_total",
                                    "truncated"):
                            agg[key] = agg.get(key, 0) + int(
                                ev.get(key, 0))
                        agg["ring_fill"] = max(
                            agg.get("ring_fill", 0.0),
                            float(ev.get("ring_fill", 0.0)))
                    ho = est.get("handoff")
                    if ho:
                        # Disaggregation visibility (ISSUE 14): summed
                        # across roles, so exported ~= imported +
                        # fallbacks + outstanding + reclaimed is
                        # checkable from serve.status() alone.
                        agg = engine.setdefault("handoff", {})
                        for key in ("exported", "imported",
                                    "import_fallbacks", "ship_bytes",
                                    "leases_outstanding",
                                    "leases_claimed",
                                    "leases_reclaimed"):
                            agg[key] = agg.get(key, 0) + int(
                                ho.get(key, 0))
            except Exception:  # noqa: BLE001 - totals dip this round
                pass
        # Prune autoscaler signals for replicas the controller no
        # longer lists (dead, drained, or scaled away) — a ghost entry
        # would keep feeding a stale load reading into the decision.
        with self._lock:
            live = set(d["replicas"])
        self._autoscaler.prune(d["app"], d["name"], live, time.time())
        d["lifecycle"] = life
        if engine:
            sp = engine.get("spec")
            if sp:
                # Deployment-wide acceptance: replica counters summed
                # above, the rates derived once here.
                sp["acceptance_rate"] = round(
                    sp["accepted"] / max(sp["proposed"], 1), 4)
                sp["accepted_per_forward"] = round(
                    (sp["accepted"] + sp["lanes"])
                    / max(sp["lanes"], 1), 3)
            d["engine"] = engine
        if dead:
            for rid in dead:
                _events.emit("controller.replica_dead", replica=rid,
                             deployment=d["name"], cause="health_probe")
            with self._lock:
                victims = []
                for rid in dead:
                    r = d["replicas"].pop(rid, None)
                    if r is not None:
                        victims.append(r)
                d["version"] += 1
            # Membership already dropped (routers stop picking on the
            # next refresh); give a wedged-but-alive replica the chance
            # to fail its in-flight lanes RETRYABLY before the kill —
            # hard-killing first would turn every stream it still holds
            # into an actor-death error race. A genuinely dead actor
            # fails the drain RPC instantly. The budget is CAPPED at the
            # probe timeout here — the victim already failed a health
            # probe, and a wedged replica that swallows the drain RPC
            # must not stall the control loop for the full graceful
            # window per victim.
            self._drain_and_kill(
                victims, min(d["config"].graceful_shutdown_timeout_s,
                             self._HEALTH_PROBE_TIMEOUT_S), d["name"],
                app_name=d["app"])

    def _autoscale(self, d: dict):
        """SLO-driven autoscale tick (ISSUE 17): per role group, turn
        the health-pass signal book into a bounded target change. The
        decision logic lives in ``autoscaler.decide`` (hysteresis,
        cooldowns, step caps, stale-signal holds, scale-to-zero,
        cold-start grace); this method only snapshots the groups,
        applies the returned targets, and journals them — actuation
        stays with ``_scale_to_target``, whose scale-down path drains
        before every kill."""
        ac: Optional[AutoscalingConfig] = d["config"].autoscaling_config
        if ac is None:
            return
        role_targets = d.get("role_targets")
        if role_targets and not ac.roles:
            # Without per-role autoscaling overrides the roles block IS
            # the target per role (declarative disaggregation, ISSUE
            # 14); a fleet-wide ongoing signal cannot apportion
            # replicas between compute-bound prefill and
            # bandwidth-bound decode.
            return
        now = time.time()
        if now - d["scale"]["last_metric"] < ac.metrics_interval_s:
            return
        d["scale"]["last_metric"] = now
        app_name, dname = d["app"], d["name"]
        with self._lock:
            if role_targets:
                groups = {
                    role: {"cur": tgt,
                           "rids": [rid for rid, r in
                                    d["replicas"].items()
                                    if (r.get("role") or "both") == role]}
                    for role, tgt in role_targets.items()}
            else:
                groups = {PLAIN_GROUP: {"cur": d["target"],
                                        "rids": list(d["replicas"])}}
        decisions = self._autoscaler.tick(
            app_name, dname, ac, groups, now,
            tpot_p95=self._tpot_p95(dname, ac, now))
        changed = False
        with self._lock:
            for group, dec in decisions.items():
                if dec.direction == "hold":
                    continue
                if group == PLAIN_GROUP:
                    if d["target"] != dec.target:
                        d["target"] = dec.target
                        changed = True
                elif d.get("role_targets") is not None and \
                        d["role_targets"].get(group) != dec.target:
                    d["role_targets"][group] = dec.target
                    changed = True
        if changed:
            try:
                self._journal_desired(app_name)
            except Exception:  # noqa: BLE001 - journal lag: a crash
                # now resumes from the previous targets, which the
                # next tick's decision re-derives from live signals
                traceback.print_exc()

    def _tpot_p95(self, dname: str, ac: AutoscalingConfig,
                  now: float) -> Optional[float]:
        """Cluster-merged TPOT p95 for one deployment, cached ~1 s.
        Only fetched when a TPOT SLO is configured; any head hiccup
        degrades the SLO overlay to absent rather than failing the
        tick."""
        wants = ac.tpot_slo_s is not None or any(
            (o or {}).get("tpot_slo_s") is not None
            for o in (ac.roles or {}).values())
        if not wants:
            return None
        cached = self._tpot_cache.get(dname)
        if cached and now - cached[1] < max(ac.metrics_interval_s, 1.0):
            return cached[0]
        val = None
        try:
            from ..core.worker import CoreWorker

            from .._private.metrics import histogram_summary

            merged = CoreWorker.current().head_call("metrics_merged")
            s = histogram_summary(merged, "serve_tpot_seconds",
                                  {"deployment": dname})
            val = s.get("p95_s") if s else None
        except Exception:  # noqa: BLE001 - SLO overlay absent this tick
            pass
        self._tpot_cache[dname] = (val, now)
        return val

    def _scale_to_target(self, app_name: str, dname: str, d: dict):
        with self._lock:
            role_targets = d.get("role_targets")
        self._reap_stray_roles(dname, d, role_targets)
        if role_targets:
            # Heterogeneous role groups (ISSUE 14): each role
            # reconciles against ITS target — prefill and decode scale
            # and drain independently inside one deployment.
            for role, target in role_targets.items():
                self._scale_role(app_name, dname, d, role, target)
            return
        self._scale_role(app_name, dname, d, None, None)

    def _reap_stray_roles(self, dname: str, d: dict,
                          role_targets: Optional[Dict[str, int]]):
        """Drain replicas whose stamped role matches no current role
        group (a redeploy added, removed, or reshaped the ``roles:``
        block): without this, a plain replica would sit outside every
        per-role count forever, and a role-stamped leftover under a
        plain target would keep rejecting the traffic routed to it —
        its engine role cannot be changed live."""
        with self._lock:
            valid = set(role_targets) if role_targets else {None}
            stray = {rid: r for rid, r in d["replicas"].items()
                     if r.get("role") not in valid}
            if not stray:
                return
            for rid in stray:
                d["replicas"].pop(rid, None)
            d["version"] += 1
            cfg = d["config"]
        self._drain_and_kill(list(stray.values()),
                             cfg.graceful_shutdown_timeout_s, dname,
                             app_name=d["app"])

    def _scale_role(self, app_name: str, dname: str, d: dict,
                    role: Optional[str], target: Optional[int]):
        from .. import api as rt

        with self._lock:
            members = {rid: r for rid, r in d["replicas"].items()
                       if role is None or (r.get("role") or "both")
                       == role}
            have = len(members)
            if target is None:
                target = d["target"]
            cfg = d["config"]
        if have < target:
            new = []
            for _ in range(target - have):
                try:
                    new.append(self._start_replica(app_name, dname, d,
                                                   role=role))
                except Exception:  # noqa: BLE001 - journal/create
                    # failure: retried next tick (intent, if written,
                    # is swept by recovery)
                    traceback.print_exc()
            ok = []
            for rid, handle in new:
                try:
                    handle._wait_ready(timeout=60)
                    try:
                        node_id = rt.get(handle.get_node_id.remote(),
                                         timeout=10)
                    except Exception:  # noqa: BLE001 - routing hint only
                        node_id = None
                    self._maybe_crash("scale_up_created")
                    ok.append((rid, handle, node_id))
                except Exception:  # noqa: BLE001
                    traceback.print_exc()
                    # Never-ready replica: kill it and clear its
                    # intent, or the named (detached) actor would
                    # linger as an orphan no journal entry describes.
                    try:
                        rt.kill(handle)
                    except Exception:  # noqa: BLE001
                        pass
                    try:
                        self._journal_intents(app_name, dname,
                                              {rid: None})
                    except Exception:  # noqa: BLE001 - swept later
                        pass
            if ok:
                with self._lock:
                    for rid, handle, node_id in ok:
                        d["replicas"][rid] = {"handle": handle,
                                              "rid": rid,
                                              "node_id": node_id,
                                              "role": role,
                                              "created": time.time()}
                    d["version"] += 1
                # Confirm AFTER membership: a crash in between leaves
                # STARTING + a live actor, which recovery adopts.
                try:
                    self._journal_intents(
                        app_name, dname,
                        {rid: ("live", role) for rid, _h, _n in ok})
                except Exception:  # noqa: BLE001 - stays STARTING;
                    # recovery adopts it the same way
                    traceback.print_exc()
        elif have > target:
            with self._lock:
                victims = sorted(members.items(),
                                 key=lambda kv: kv[1]["created"],
                                 reverse=True)[:have - target]
                for rid, _ in victims:
                    d["replicas"].pop(rid, None)
                d["version"] += 1
            self._drain_and_kill([r for _rid, r in victims],
                                 cfg.graceful_shutdown_timeout_s, dname,
                                 app_name=app_name)

    def drain_role(self, app_name: str, deployment_name: str, role: str,
                   remove: bool = True,
                   timeout_s: Optional[float] = None) -> list:
        """Drain ONE role group of a disaggregated deployment
        independently of the others (ISSUE 14): its replicas are marked
        draining (``get_replicas`` lists them, so routers pin them out
        of the pick set — no self-expiring mark), their engines drain
        gracefully, and with ``remove=True`` they are torn down and the
        role's target zeroed so the reconcile loop does not respawn
        them. Returns the drained replica ids."""
        with self._reconcile_lock:
            with self._lock:
                app = self._apps.get(app_name)
                d = (app or {"deployments": {}})["deployments"] \
                    .get(deployment_name)
                if d is None:
                    return []
                victims = {rid: r for rid, r in d["replicas"].items()
                           if (r.get("role") or "both") == role}
                for r in victims.values():
                    r["draining"] = True
                d["version"] += 1
                cfg = d["config"]
            budget = cfg.graceful_shutdown_timeout_s \
                if timeout_s is None else float(timeout_s)
            if not victims:
                return []
            if not remove:
                # Mark-and-drain only: replicas stay listed (as
                # draining) so routers hold their marks; the caller
                # removes them later (or redeploys).
                from .. import api as rt

                refs = []
                for r in victims.values():
                    try:
                        refs.append(r["handle"].drain.remote(budget))
                    except Exception:  # noqa: BLE001 - already dead
                        pass
                if refs:
                    try:
                        rt.wait(refs, num_returns=len(refs),
                                timeout=budget + 2)
                    except Exception:  # noqa: BLE001 - best-effort
                        pass
                return sorted(victims)
            with self._lock:
                for rid in victims:
                    d["replicas"].pop(rid, None)
                if d.get("role_targets"):
                    d["role_targets"][role] = 0
                d["version"] += 1
            try:
                self._journal_desired(app_name)
            except Exception:  # noqa: BLE001 - recovery re-zeroes via
                # the condemned intents below
                traceback.print_exc()
            self._drain_and_kill(list(victims.values()), budget,
                                 deployment_name, app_name=app_name)
            return sorted(victims)

    # -------------------------- crash-safe desired state (ISSUE 17)
    def _journal_app(self, name: str):
        """Journal one app's full spec (payloads + configs) and its
        desired targets. Raises on journal failure — deploy_app is the
        only caller and a deploy that cannot be made durable should
        fail loudly, not silently lose crash safety."""
        with self._lock:
            app = self._apps.get(name)
            if app is None:
                return
            blob = {"name": name,
                    "route_prefix": app["route_prefix"],
                    "ingress": app["ingress"],
                    "stream": bool(app.get("stream")),
                    "deployments": [
                        {"name": d["name"], "payload": d["payload"],
                         "config": d["config"]}
                        for d in app["deployments"].values()]}
        self._journal.put_app(name, blob)
        self._journal_desired(name)

    def _journal_desired(self, app_name: str):
        with self._lock:
            app = self._apps.get(app_name)
            if app is None:
                return
            desired = {dname: {"target": d["target"],
                               "role_targets": d.get("role_targets")}
                       for dname, d in app["deployments"].items()}
        self._journal.put_desired(app_name, desired)

    def _journal_intents(self, app_name: str, dname: str,
                         updates: Dict[str, Any]):
        """Apply ``{rid: None | (state, role)}`` to the app's replica
        intent document (one read-modify-write; every caller holds
        ``_reconcile_lock``, which serializes them)."""
        intents = self._journal.get_replicas(app_name)
        ents = intents.setdefault(dname, {})
        for rid, up in updates.items():
            if up is None:
                ents.pop(rid, None)
            else:
                state, role = up
                ents[rid] = {"role": role, "state": state,
                             "t": time.time()}
        if not ents:
            intents.pop(dname, None)
        self._journal.put_replicas(app_name, intents)

    def _maybe_recover(self):
        """Resume reconciliation from the journal after a controller
        restart (idempotent, runs once per controller life).

        For every journaled app: rebuild deployment state from the
        spec + desired-target documents, then reconcile the replica
        intents against reality — a LIVE/STARTING entry whose named
        actor answers is ADOPTED (counted toward its group's target,
        so no double scale-up), an entry with no live actor is dropped
        (the create never landed, or the replica died with nobody
        watching), and CONDEMNED entries are re-drained and killed
        (the predecessor was mid-scale-down; clients resume on the
        survivors). Orphans are impossible as long as intents are
        written ahead of creates — every live replica has an entry,
        and every entry is either adopted or torn down here."""
        with self._lock:
            if self._recovered:
                return
            self._recovered = True
        try:
            names = self._journal.list_apps()
        except Exception:  # noqa: BLE001 - head unreachable: flip the
            # gate back so the next tick retries recovery
            with self._lock:
                self._recovered = False
            return
        for name in names:
            with self._lock:
                if name in self._apps:
                    continue
            try:
                self._recover_app(name)
            except Exception:  # noqa: BLE001 - one app's bad journal
                # must not block the others (or the loop)
                traceback.print_exc()

    def _recover_app(self, name: str):
        from .. import api as rt

        blob = self._journal.get_app(name)
        if blob is None:
            return
        desired = self._journal.get_desired(name)
        intents = self._journal.get_replicas(name)
        app = {"name": name, "route_prefix": blob.get("route_prefix"),
               "ingress": blob.get("ingress"),
               "stream": bool(blob.get("stream")), "deployments": {}}
        for dspec in blob.get("deployments", []):
            dname = dspec["name"]
            cfg: DeploymentConfig = dspec["config"]
            want = desired.get(dname) or {}
            app["deployments"][dname] = {
                "app": name, "name": dname,
                "payload": dspec["payload"], "config": cfg,
                "target": int(want.get("target",
                                       cfg.initial_target())),
                "role_targets": want.get("role_targets",
                                         self._role_targets(cfg)),
                "version": 0, "replicas": {},
                "scale": {"desired": None, "since": 0.0,
                          "last_metric": 0.0},
                "last_health": 0.0,
            }
        survivors: Dict[str, dict] = {}
        condemned: Dict[str, list] = {}
        for dname, ents in intents.items():
            d = app["deployments"].get(dname)
            for rid, ent in ents.items():
                try:
                    n = int(rid.rsplit("#", 1)[1])
                except (IndexError, ValueError):
                    n = 0
                # Past the journaled ids, or a fresh create would
                # collide with an adopted name.
                self._replica_counter = max(self._replica_counter, n)
                try:
                    handle = rt.get_actor(replica_actor_name(name, rid),
                                          timeout=2)
                except Exception:  # noqa: BLE001 - no such actor
                    handle = None
                if handle is None:
                    continue       # entry dropped: nothing to adopt
                if d is None or ent.get("state") == "condemned":
                    # Keep the entry CONDEMNED until the kill below
                    # completes — a crash mid-recovery must leave the
                    # re-drain instruction in place.
                    survivors.setdefault(dname, {})[rid] = {
                        "role": ent.get("role"), "state": "condemned",
                        "t": time.time()}
                    condemned.setdefault(dname, []).append(
                        {"handle": handle, "rid": rid,
                         "role": ent.get("role")})
                    continue
                try:
                    node_id = rt.get(handle.get_node_id.remote(),
                                     timeout=5)
                except Exception:  # noqa: BLE001 - routing hint only
                    node_id = None
                d["replicas"][rid] = {"handle": handle, "rid": rid,
                                      "node_id": node_id,
                                      "role": ent.get("role"),
                                      "created": time.time()}
                survivors.setdefault(dname, {})[rid] = {
                    "role": ent.get("role"), "state": "live",
                    "t": time.time()}
        with self._lock:
            self._apps[name] = app
        self._journal.put_replicas(name, survivors)
        for dname, victims in condemned.items():
            d = app["deployments"].get(dname)
            budget = d["config"].graceful_shutdown_timeout_s if d \
                else 5.0
            self._drain_and_kill(victims, budget, dname, app_name=name)

    def inject_crash(self, point: str) -> bool:
        """Chaos-test hook (mirrors ``engine.inject_fault``): hard-exit
        the controller process (``os._exit(44)``) the next time the
        reconcile path passes ``point``. Points: ``scale_up_intent``
        (intent journaled, actor not yet created), ``scale_up_created``
        (actor live, membership/journal not yet confirmed),
        ``drain_condemned`` (victims condemned, drain not yet sent),
        ``drain_pre_kill`` (drained, not yet killed)."""
        self._crash_points.add(point)
        return True

    def _maybe_crash(self, point: str):
        if point in self._crash_points:
            import os

            os._exit(44)

    def _start_replica(self, app_name: str, dname: str, d: dict,
                       role: Optional[str] = None):
        from .. import api as rt
        from ._replica import Replica

        cfg: DeploymentConfig = d["config"]
        self._replica_counter += 1
        rid = f"{dname}#{self._replica_counter}"
        # WRITE-AHEAD (ISSUE 17): the intent reaches the journal BEFORE
        # the create RPC, so every replica that can possibly exist has
        # an entry a restarted controller reconciles against — adopt if
        # it came up, sweep if it never did. A failed journal write
        # aborts the create (the safe side: no actor without an entry).
        self._journal_intents(app_name, dname, {rid: ("starting", role)})
        self._maybe_crash("scale_up_intent")
        opts = dict(cfg.ray_actor_options)
        opts.setdefault("num_cpus", 1)
        # Replicas spread across nodes by default so one node's death
        # never takes a whole deployment down (reference:
        # deployment_scheduler.py spread policy).
        opts.setdefault("scheduling_strategy", "SPREAD")
        # Named => DETACHED in this runtime: the replica survives a
        # SIGKILLed controller (streams keep flowing) and the successor
        # re-attaches by name instead of starting a duplicate.
        opts["name"] = replica_actor_name(app_name, rid)
        actor_cls = rt.remote(Replica).options(
            max_concurrency=cfg.max_ongoing_requests + 4, **opts)
        # Role stamping (ISSUE 14): the replica sees its OWN role in
        # the engine block; the deployment-level ``roles:`` group
        # sizing is controller state and never reaches the engine.
        engine_config = dict(getattr(cfg, "engine_config", None) or {})
        engine_config.pop("roles", None)
        if role:
            engine_config["role"] = role
        # The replica enforces max_ongoing_requests itself: client-side
        # admission undercounts when several routers share one replica,
        # so the server gate (typed ReplicaOverloadedError pushback) is
        # the authoritative one.
        handle = actor_cls.remote(app_name, dname, rid, d["payload"],
                                  cfg.user_config,
                                  cfg.max_ongoing_requests,
                                  engine_config or None)
        return rid, handle

    # ------------------------------------------------------------- proxies
    def ensure_proxies(self, http_options: dict) -> Optional[dict]:
        """Record the proxy bind options and start one proxy per alive
        node (reference: ``proxy.py:1116`` — a proxy on every node, any
        of them serves external traffic). Returns the primary proxy's
        bind info. The reconcile loop keeps the fleet in sync as nodes
        join and leave."""
        with self._reconcile_lock:
            self._proxy_opts = dict(http_options)
            self._reconcile_proxies()
            return self._http_info

    def get_proxies(self) -> Dict[str, dict]:
        """node_id -> {"name", "info"} for every live proxy."""
        with self._lock:
            return {nid: {"name": p["name"], "info": p["info"]}
                    for nid, p in self._proxies.items()}

    _PROXY_HEALTH_PERIOD_S = 5.0

    def _reconcile_proxies(self):
        if self._proxy_opts is None:
            return
        from .. import api as rt
        from ..util.state import list_nodes
        from ._proxy import ProxyActor

        alive = {n["node_id"]: n for n in list_nodes()
                 if n.get("state") == "ALIVE"}
        with self._lock:
            have = set(self._proxies)
        # Reap proxies whose node died (the actor died with it).
        for nid in have - set(alive):
            with self._lock:
                p = self._proxies.pop(nid, None)
            if p is not None:
                try:
                    rt.kill(p["handle"])
                except Exception:  # noqa: BLE001 - already dead
                    pass
        # A proxy can also die on a LIVE node (crash/OOM): probe each
        # one periodically and drop dead entries so the create loop
        # below resurrects them — replicas get health checks, proxies
        # must too (reference: proxy_state_manager health states).
        now = time.time()
        if now - getattr(self, "_proxies_checked_at", 0.0) \
                >= self._PROXY_HEALTH_PERIOD_S:
            self._proxies_checked_at = now
            with self._lock:
                probes = [(nid, p["handle"], p["name"])
                          for nid, p in self._proxies.items()]
            for nid, handle, name in probes:
                try:
                    rt.get(handle.get_port.remote(), timeout=5)
                except Exception:  # noqa: BLE001 - proxy dead
                    with self._lock:
                        self._proxies.pop(nid, None)
                        self._proxy_stats.pop(nid, None)
                    try:
                        rt.kill(handle)
                    except Exception:  # noqa: BLE001
                        pass
                    continue
                # Piggyback shed/expired totals for status(); tolerate
                # adopted proxies predating the RPC.
                try:
                    stats = rt.get(handle.get_lifecycle_stats.remote(),
                                   timeout=5)
                    with self._lock:
                        self._proxy_stats[nid] = stats
                except Exception:  # noqa: BLE001 - older proxy
                    pass
        opts = self._proxy_opts
        primary_missing = not any(p["name"] == "SERVE_PROXY"
                                  for p in self._proxies.values())
        for nid, node in alive.items():
            if nid in self._proxies:
                continue
            # The first proxy keeps the legacy cluster-wide name (and
            # the configured port); secondaries are per-node actors on
            # an ephemeral port — co-hosted test nodes must not fight
            # over one port, and real deployments address each node's
            # proxy by its own host anyway.
            name = "SERVE_PROXY" if primary_missing \
                else f"SERVE_PROXY:{nid[:12]}"
            port = opts.get("port", 0) if primary_missing else 0
            try:
                handle = rt.remote(ProxyActor).options(
                    name=name, max_concurrency=8, num_cpus=0,
                    scheduling_strategy=rt.NodeAffinitySchedulingStrategy(
                        nid, soft=True)).remote()
                info = rt.get(handle.start.remote(
                    opts.get("host", "127.0.0.1"), port,
                    opts.get("request_timeout_s", 60.0)), timeout=30)
            except Exception as e:  # noqa: BLE001 - node raced away; retry
                # A prior fleet's proxy may still hold the name (this
                # controller restarted or lost state): adopt the live
                # actor instead of colliding with the identical create
                # on every reconcile tick and never publishing
                # _http_info (ADVICE.md low).
                adopted = None
                if "already taken" in str(e):
                    adopted = self._adopt_proxy(name, opts, port)
                if adopted is None:
                    traceback.print_exc()
                    continue
                handle, info = adopted
            with self._lock:
                self._proxies[nid] = {"handle": handle, "name": name,
                                      "info": info}
                if primary_missing:
                    self._http_info = dict(info)
                    primary_missing = False

    def _adopt_proxy(self, name: str, opts: dict, bind_port: int):
        """Adopt a live proxy actor that already holds ``name``:
        ``get_port`` is idempotent (None until started), and ``start``
        is only issued when the actor never bound — re-starting a bound
        proxy would spawn a second server thread. ``bind_port`` is the
        caller's computed port for this slot (configured port for the
        primary, 0 for secondaries — adopting a secondary must not bind
        the primary's port). Returns (handle, info) or None if the
        actor is gone/unresponsive (the name then frees up and the next
        tick's create succeeds)."""
        from .. import api as rt

        try:
            handle = rt.get_actor(name, timeout=5)
            port = rt.get(handle.get_port.remote(), timeout=5)
            if port is None:
                info = rt.get(handle.start.remote(
                    opts.get("host", "127.0.0.1"), bind_port,
                    opts.get("request_timeout_s", 60.0)), timeout=30)
            else:
                info = {"host": opts.get("host", "127.0.0.1"),
                        "port": port}
            return handle, info
        except Exception:  # noqa: BLE001 - stale name or dead actor
            return None

    @staticmethod
    def _call_quietly(method, *args):
        from .. import api as rt

        try:
            rt.get(method.remote(*args), timeout=10)
        except Exception:  # noqa: BLE001
            pass
