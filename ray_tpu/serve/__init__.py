"""ray_tpu.serve — model serving library.

Capability parity with ``ray.serve`` (reference:
``python/ray/serve/__init__.py``): deployments, applications, handles,
an HTTP proxy, dynamic batching, and replica autoscaling — rebuilt for
this runtime's threaded actors, with TPU-aware bucketed-padding batching
so jitted models see a fixed set of static batch shapes.
"""
from .api import (Application, Deployment, delete, deployment,
                  get_app_handle, get_deployment_handle, run, shutdown,
                  start, status)
from .batching import batch, default_buckets, pad_to_bucket
from .config import (AutoscalingConfig, DeploymentConfig, HTTPOptions, gRPCOptions)
from .draft import Drafter, ModelDrafter, NGramDrafter
from .engine import DecodeEngine, EngineRestartError, EngineShutdownError
from .handle import (DeploymentHandle, DeploymentResponse,
                     DeploymentResponseGenerator)
from .multiplex import get_multiplexed_model_id, multiplexed
from .request import (BackPressureError, ReplicaDrainingError,
                      ReplicaOverloadedError, Request,
                      RequestDeadlineExceeded, Response,
                      get_request_deadline)

__all__ = [
    "Application", "AutoscalingConfig", "BackPressureError", "DecodeEngine",
    "Deployment", "Drafter", "ModelDrafter", "NGramDrafter",
    "DeploymentConfig", "EngineRestartError", "EngineShutdownError",
    "DeploymentHandle", "DeploymentResponse", "DeploymentResponseGenerator",
    "HTTPOptions", "gRPCOptions", "ReplicaDrainingError",
    "ReplicaOverloadedError", "Request",
    "RequestDeadlineExceeded",
    "Response", "batch", "default_buckets", "delete", "deployment",
    "get_multiplexed_model_id", "get_request_deadline", "multiplexed",
    "get_app_handle", "get_deployment_handle", "pad_to_bucket", "run",
    "shutdown", "start", "status",
]

from ray_tpu._private.usage_stats import record_feature as _rf  # noqa: E402
_rf("serve")
del _rf
