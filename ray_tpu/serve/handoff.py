"""Crash-safe KV handoff between prefill and decode replicas (ISSUE 14).

Disaggregated serving splits one generation across two engines: a
prefill-role engine runs the prompt and samples the first token, then
EXPORTS the slot's K/V (page-granular ship buffers, trimmed to the true
prompt length) instead of keeping the slot; a decode-role engine IMPORTS
those bytes into its own pool and decodes the rest. The bytes in flight
between the two hosts are the crash surface this module owns:

- **Payloads** are self-verifying: :func:`build_payload` stamps a
  SHA-256 digest over the K/V bytes plus every replay-relevant field
  (prompt, first token, PRNG lane, seed, positions), and
  :func:`verify_payload` re-hashes on the importing side — a torn or
  corrupted transfer downgrades to a local re-prefill (the stream is a
  deterministic function of prompt+knobs+seed, so the fallback is
  token-identical), never a silently wrong cache.
- **Leases** bound every shipped payload's lifetime: the prefill engine
  grants an epoch-stamped lease per handoff and keeps the only pin on
  the shipped object. A decode replica that claims in time releases the
  pin; one that dies (or a router that falls back) simply never claims,
  and the lease sweep — run from the prefill engine's driver loop —
  reclaims the pin at expiry. A crash can therefore never pin the
  object plane: orphaned ship buffers free themselves on the lease
  clock.

The payload rides the existing object plane (``rt.put`` → chunked
multi-source shm pulls, the same machinery as the collective broadcast
path); descriptors — the small routing record carrying the lease, the
digest, and the replay fields — travel inline over the RPC plane.
"""
from __future__ import annotations

import hashlib
import threading
import time
from typing import Any, Dict, Optional, Tuple

import numpy as np


class HandoffError(RuntimeError):
    """A shipped KV payload could not be resolved or verified (lease
    expired and the object was reclaimed, bytes failed the digest, or
    the shipper died mid-transfer). Always recoverable: the descriptor
    carries prompt+seed, so the importer falls back to a local
    re-prefill that is token-identical by determinism."""


#: Payloads at or under this many bytes travel inline in the descriptor
#: (one RPC hop, no object-plane round trip); larger ones are put into
#: the object store once and pulled by the decode side via the chunked
#: transfer path.
SHIP_INLINE_MAX = 64 * 1024


def _meta_bytes(payload: Dict[str, Any]) -> bytes:
    return (f"pos={int(payload['pos'])};first={int(payload['first'])};"
            f"seed={int(payload['seed'])};"
            f"max_new={int(payload['max_new'])}").encode()


def payload_digest(payload: Dict[str, Any]) -> str:
    """SHA-256 over the shipped K/V bytes AND every replay-relevant
    field — byte-verification of the shipped pages, not just a length
    check. Deterministic across flat/paged exporters because both trim
    to the true prompt length before hashing. Quantized payloads
    (ISSUE 16) additionally fold the per-page scales and the layout
    identity (``kv_dtype``, ``page_size``) into the hash — ONLY when
    present, so fp digests are byte-for-byte what they were before the
    int8 plane existed."""
    h = hashlib.sha256()
    h.update(np.ascontiguousarray(payload["k"]).tobytes())
    h.update(np.ascontiguousarray(payload["v"]).tobytes())
    h.update(np.ascontiguousarray(
        np.asarray(payload["prompt"], np.int32)).tobytes())
    h.update(np.ascontiguousarray(
        np.asarray(payload["rng"], np.uint32)).tobytes())
    h.update(_meta_bytes(payload))
    if payload.get("ks") is not None:
        h.update(np.ascontiguousarray(
            np.asarray(payload["ks"], np.float32)).tobytes())
        h.update(np.ascontiguousarray(
            np.asarray(payload["vs"], np.float32)).tobytes())
        h.update((f"kv_dtype={payload.get('kv_dtype', 'int8')};"
                  f"page_size={int(payload.get('page_size', 0))}"
                  ).encode())
    if payload.get("layout", "canonical") != "canonical":
        # tp resharding boundary (ISSUE 20): the hash is defined over
        # the CANONICAL host-order bytes — exporters gather their mesh
        # before building the payload, so "canonical" (the only layout
        # this protocol ships) folds nothing in and every existing
        # digest is unchanged. A non-canonical stamp is hashed so it
        # cannot be stripped in flight to sneak mesh-local bytes past
        # the importer's layout check.
        h.update(f"layout={payload['layout']}".encode())
    return h.hexdigest()


def build_payload(*, k: np.ndarray, v: np.ndarray, prompt: np.ndarray,
                  pos: int, first: int, rng: np.ndarray, seed: int,
                  max_new: int, ks: Optional[np.ndarray] = None,
                  vs: Optional[np.ndarray] = None,
                  kv_dtype: Optional[str] = None,
                  page_size: Optional[int] = None,
                  layout: Optional[str] = None) -> Dict[str, Any]:
    """Assemble one ship buffer: the slot's K/V trimmed to ``pos``
    (``[L, pos, H, hd]``, contiguous), the first sampled token, the
    post-prefill PRNG lane, and the replay identity (prompt, seed,
    max_new) — everything a decode engine needs to continue the stream
    bit-exactly, and everything a survivor needs to re-prefill it from
    scratch if the bytes are lost. int8 exporters (ISSUE 16) pass the
    codes as ``k``/``v`` plus the per-page scales ``ks``/``vs``
    (``[L, n_cover, H]``) and the layout identity; the digest then
    covers codes AND scales."""
    payload = {
        "k": np.ascontiguousarray(k),
        "v": np.ascontiguousarray(v),
        "prompt": np.ascontiguousarray(np.asarray(prompt, np.int32)),
        "pos": int(pos),
        "first": int(first),
        "rng": np.ascontiguousarray(np.asarray(rng, np.uint32)),
        "seed": int(seed),
        "max_new": int(max_new),
    }
    if ks is not None:
        payload["ks"] = np.ascontiguousarray(np.asarray(ks, np.float32))
        payload["vs"] = np.ascontiguousarray(np.asarray(vs, np.float32))
        payload["kv_dtype"] = str(kv_dtype or "int8")
        payload["page_size"] = int(page_size or 0)
    if layout is not None and layout != "canonical":
        # Only a NON-canonical stamp is recorded (and digest-folded):
        # canonical is the protocol default, so tp-aware exporters —
        # which always gather to host order first — emit payloads
        # byte-identical to the single-chip plane.
        payload["layout"] = str(layout)
    payload["digest"] = payload_digest(payload)
    return payload


def verify_payload(payload: Dict[str, Any]) -> None:
    """Byte-verify a resolved payload against its stamped digest."""
    want = payload.get("digest")
    if not want:
        raise HandoffError("handoff payload carries no digest")
    got = payload_digest(payload)
    if got != want:
        raise HandoffError(
            f"handoff payload failed byte verification "
            f"(digest {got[:12]} != shipped {want[:12]})")


def payload_nbytes(payload: Dict[str, Any]) -> int:
    n = int(payload["k"].nbytes) + int(payload["v"].nbytes)
    if payload.get("ks") is not None:
        n += int(payload["ks"].nbytes) + int(payload["vs"].nbytes)
    return n


def ship_payload(payload: Dict[str, Any]) -> Tuple[Dict[str, Any], int]:
    """Turn a payload into its wire descriptor half: inline for small
    payloads, an object-plane ref (``rt.put`` → chunked shm pull on the
    consumer) past :data:`SHIP_INLINE_MAX`. Returns ``(fields, nbytes)``
    where ``fields`` carries exactly one of ``payload``/``ref`` — the
    caller merges lease and routing fields on top. Outside a running
    runtime (in-process engine tests) the payload always ships inline.
    """
    nbytes = payload_nbytes(payload)
    core = None
    try:
        from ..core.worker import CoreWorker

        core = CoreWorker._current
    except Exception:  # noqa: BLE001 - no runtime in this process
        core = None
    if core is None or nbytes <= SHIP_INLINE_MAX:
        return {"payload": payload}, nbytes
    from .. import api as rt

    return {"ref": rt.put(payload)}, nbytes


def resolve_payload(desc: Dict[str, Any],
                    timeout_s: float = 30.0) -> Dict[str, Any]:
    """Materialize a descriptor's payload: inline copy, or a pull of
    the shipped object through the chunked-transfer path. Raises
    :class:`HandoffError` when the object is gone — a reclaimed lease
    or a shipper that died mid-transfer — so the caller falls back to a
    local re-prefill."""
    if "payload" in desc:
        return desc["payload"]
    ref = desc.get("ref")
    if ref is None:
        raise HandoffError("handoff descriptor has neither payload nor ref")
    from .. import api as rt

    try:
        return rt.get(ref, timeout=timeout_s)
    except Exception as e:  # noqa: BLE001 - owner died / lease reclaimed
        raise HandoffError(
            f"shipped KV payload unavailable ({type(e).__name__}: {e}); "
            f"lease expired or the prefill replica died mid-ship") from e


class HandoffLease:
    """One granted handoff: the pin keeping the shipped payload alive
    (an ObjectRef, or None for inline ships), its epoch stamp, and its
    expiry on the lease clock."""

    __slots__ = ("lease_id", "epoch", "expires_at", "pin", "nbytes")

    def __init__(self, lease_id: str, epoch: int, expires_at: float,
                 pin: Any, nbytes: int):
        self.lease_id = lease_id
        self.epoch = epoch
        self.expires_at = expires_at
        self.pin = pin
        self.nbytes = nbytes


class LeaseTable:
    """Epoch-stamped lease bookkeeping for shipped KV payloads.

    The prefill engine grants a lease per handoff and holds the only
    pin on the shipped object; :meth:`claim` (the decode side imported
    successfully) and :meth:`sweep` (lease expired unclaimed — the
    decode replica or the router died between grant and claim) both
    drop the pin, each exactly once. Accessed from the engine driver
    thread (grant at export, sweep in the loop) AND replica RPC threads
    (claim), so every mutation runs under ``_lock``.
    """

    def __init__(self, ttl_s: float = 30.0):
        self.ttl_s = float(ttl_s)
        self._lock = threading.Lock()
        self._leases: Dict[str, HandoffLease] = {}
        self._counter = 0
        self.granted = 0
        self.claimed = 0
        self.reclaimed = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._leases)

    def grant(self, *, epoch: int, pin: Any = None, nbytes: int = 0,
              ttl_s: Optional[float] = None) -> Tuple[str, float]:
        """Grant one lease; returns ``(lease_id, expires_at)``. The pin
        (if any) is dropped on claim or sweep, never by the caller."""
        ttl = self.ttl_s if ttl_s is None else float(ttl_s)
        with self._lock:
            self._counter += 1
            lease_id = f"ho-{self._counter}-{epoch}"
            expires = time.monotonic() + ttl
            self._leases[lease_id] = HandoffLease(
                lease_id, int(epoch), expires, pin, int(nbytes))
            self.granted += 1
        return lease_id, expires

    def claim(self, lease_id: str, epoch: int) -> bool:
        """Release a lease after a successful import. False when the
        lease is unknown (already swept — the payload may be gone, but
        the importer that claims late already HAS the bytes) or the
        epoch does not match (a stale claim from before a restart must
        not release a newer grant that reused the id space)."""
        with self._lock:
            lease = self._leases.get(lease_id)
            if lease is None or lease.epoch != int(epoch):
                return False
            del self._leases[lease_id]
            self.claimed += 1
            lease.pin = None       # drop the pin: the owner may free
            return True

    def _expired_locked(self, now: float) -> list:  # rtlint: holds=_lock
        """Lease ids past expiry at ``now``. Both call sites (sweep;
        tests poking the clock) hold ``_lock`` — the scan and the pop
        must see one consistent table."""
        return [lid for lid, lease in self._leases.items()
                if lease.expires_at <= now]

    def sweep(self, now: Optional[float] = None) -> int:
        """Reclaim every expired lease, dropping its pin so the object
        plane frees the orphaned ship buffer. Returns the reclaim
        count. Run from the prefill engine's driver loop — the lease
        clock that guarantees a crashed consumer can never pin the
        pool."""
        now = time.monotonic() if now is None else now
        with self._lock:
            expired = self._expired_locked(now)
            for lid in expired:
                lease = self._leases.pop(lid)
                lease.pin = None   # drop the pin: the owner may free
            self.reclaimed += len(expired)
        return len(expired)

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"granted": self.granted, "claimed": self.claimed,
                    "reclaimed": self.reclaimed,
                    "outstanding": len(self._leases)}
