"""Picklable HTTP request/response surface handed to ingress deployments.

The reference hands replicas a Starlette ``Request`` over ASGI
(reference: ``python/ray/serve/_private/http_util.py``); this runtime ships
a plain picklable snapshot instead, because requests cross a process
boundary (proxy actor -> replica actor) rather than staying inside one
asyncio app.
"""
from __future__ import annotations

import json as _json
from dataclasses import dataclass, field
from typing import Any, Dict, Optional
from urllib.parse import parse_qsl, urlsplit


@dataclass
class Request:
    method: str = "GET"
    path: str = "/"
    query_params: Dict[str, str] = field(default_factory=dict)
    headers: Dict[str, str] = field(default_factory=dict)
    body: bytes = b""

    def json(self) -> Any:
        return _json.loads(self.body or b"null")

    def text(self) -> str:
        return self.body.decode("utf-8", errors="replace")

    @classmethod
    def from_target(cls, method: str, target: str, headers: Dict[str, str],
                    body: bytes) -> "Request":
        parts = urlsplit(target)
        return cls(method=method, path=parts.path,
                   query_params=dict(parse_qsl(parts.query)),
                   headers=headers, body=body)


@dataclass
class Response:
    """Optional rich response; plain return values are auto-encoded."""

    body: Any = b""
    status: int = 200
    content_type: Optional[str] = None

    def encode(self):
        ctype, body = encode_body(self.body)
        return self.status, self.content_type or ctype, body


def encode_body(value: Any):
    """Encode a handler return value to (content_type, bytes)."""
    if isinstance(value, Response):
        _, ctype, body = value.encode()
        return ctype, body
    if isinstance(value, bytes):
        return "application/octet-stream", value
    if isinstance(value, str):
        return "text/plain; charset=utf-8", value.encode()
    try:
        import numpy as np

        if isinstance(value, np.ndarray):
            value = value.tolist()
        elif isinstance(value, np.generic):
            value = value.item()
    except Exception:  # noqa: BLE001
        pass
    return "application/json", _json.dumps(value).encode()
