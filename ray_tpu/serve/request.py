"""Picklable HTTP request/response surface handed to ingress deployments,
plus the request-lifecycle vocabulary shared by the whole serve data
plane: absolute deadlines and the typed overload/expiry errors.

The reference hands replicas a Starlette ``Request`` over ASGI
(reference: ``python/ray/serve/_private/http_util.py``); this runtime ships
a plain picklable snapshot instead, because requests cross a process
boundary (proxy actor -> replica actor) rather than staying inside one
asyncio app.

Deadlines are **absolute wall-clock timestamps** (``time.time()``), like
gRPC deadlines: a request is stamped once at the edge (proxy or handle)
and every downstream hop — router admission, replica dispatch, the
batcher's flush — compares against the same instant instead of restarting
its own timeout window. Wall-clock (not monotonic) because the stamp
crosses process boundaries; NTP-level skew is negligible against
second-scale request timeouts.
"""
from __future__ import annotations

import contextvars
import json as _json
import time as _time
from dataclasses import dataclass, field
from typing import Any, Dict, Optional
from urllib.parse import parse_qsl, urlsplit

from ..exceptions import RayTpuError


class RequestDeadlineExceeded(RayTpuError, TimeoutError):
    """The request's absolute deadline passed before (or while) a replica
    could produce an answer. Never retried — nobody is waiting."""


class ReplicaOverloadedError(RayTpuError):
    """Typed replica pushback: the replica is at ``max_ongoing_requests``.

    The router treats this as "re-pick another replica, don't mark this
    one dead" — overload is a routing signal, not a failure."""

    #: Routing signal, not a failure: the router may resubmit elsewhere
    #: without marking the replica dead. Mirrored by the drain/restart
    #: errors (``ReplicaDrainingError``, ``EngineShutdownError``,
    #: ``EngineRestartError``) so one marker covers every
    #: re-pick-don't-bury pushback.
    retryable = True


class ReplicaDrainingError(RayTpuError):
    """Typed drain pushback: the replica stopped admitting because it is
    being torn down (reconfigure, scale-down, health replacement). Like
    overload, this is a routing signal — the router re-picks another
    replica; membership refresh drops the draining one shortly after."""

    retryable = True


class BackPressureError(RayTpuError):
    """Every replica is saturated and the pending queue is past its bound;
    the request was shed instead of queued. The HTTP proxy maps this to
    ``503`` + ``Retry-After``; handle callers receive it directly.

    ``retry_after_s`` is the server's backoff hint."""

    def __init__(self, message: str = "deployment is overloaded",
                 retry_after_s: float = 1.0):
        self.retry_after_s = retry_after_s
        super().__init__(message)

    def __reduce__(self):
        return (BackPressureError, (str(self.args[0] if self.args else ""),
                                    self.retry_after_s))


def make_deadline(timeout_s: Optional[float]) -> Optional[float]:
    """Absolute wall-clock deadline for a fresh request (None = no limit)."""
    return None if timeout_s is None else _time.time() + timeout_s


def remaining_s(deadline_s: Optional[float]) -> Optional[float]:
    """Seconds until the deadline (may be <= 0); None = unbounded."""
    return None if deadline_s is None else deadline_s - _time.time()


def deadline_expired(deadline_s: Optional[float]) -> bool:
    return deadline_s is not None and _time.time() > deadline_s


#: Per-request deadline, set by the replica around user code so nested
#: work (the batcher, composed handle calls) inherits the caller's
#: deadline without threading it through user signatures.
_request_deadline: "contextvars.ContextVar[Optional[float]]" = \
    contextvars.ContextVar("rt_serve_request_deadline", default=None)


def get_request_deadline() -> Optional[float]:
    """Absolute deadline of the request being handled on this thread
    (None outside a deadline-stamped request)."""
    return _request_deadline.get()


#: Name of the deployment handling the current request, set by the
#: replica around user code. Nested layers with no deployment identity
#: of their own — the @serve.batch flusher above all — read it to label
#: their histograms and spans by deployment instead of guessing.
_request_deployment: "contextvars.ContextVar[Optional[str]]" = \
    contextvars.ContextVar("rt_serve_request_deployment", default=None)


def get_request_deployment() -> Optional[str]:
    """Deployment name of the request being handled on this thread
    (None outside a replica's request scope)."""
    return _request_deployment.get()


#: Wire trace context of the current request's submission
#: (``{"trace_id", "span_id"}``), stamped by the router next to the
#: deadline and activated by the replica so stage spans recorded on
#: foreign threads (the batcher) can join the request's trace.
TRACE_CTX_KEY = "trace_ctx"
SUBMITTED_AT_KEY = "submitted_at"
#: Mid-stream failover replay token (count of tokens the caller already
#: holds): a resumed stream re-executes the SAME deterministic call and
#: the serving side suppresses the first ``resume_from`` tokens, so the
#: client's concatenated stream is token-identical to an uninterrupted
#: run. Stamped by ``DeploymentResponseGenerator`` on re-route after a
#: mid-stream replica failure.
RESUME_FROM_KEY = "resume_from"
#: Cluster-wide request correlation id (``rq-<pid>-<n>``), stamped ONCE
#: by the handle/router when the logical request is born and re-sent
#: verbatim on every retry, resume, and disaggregated hop — the join
#: key the flight recorder (``_private/events.py``) and the post-mortem
#: collector (``tools/rtblackbox``) use to stitch one request's story
#: across processes, including dead ones.
REQUEST_ID_KEY = "rt_request_id"
#: Disaggregated prefill/decode hop marker (ISSUE 14), stamped by the
#: router's two-hop dispatch: the literal string ``"export"`` on the
#: prefill hop (the continuous-batching wrapper answers with a leased
#: handoff descriptor instead of a stream), or the descriptor dict on
#: the decode hop (the wrapper imports it via
#: ``engine.admit_prefilled`` instead of prefilling locally).
HANDOFF_KEY = "handoff"


#: Tokens already delivered to the caller of the request being handled
#: on this thread (0 for a fresh stream). Set by the replica around user
#: code; the continuous-batching wrapper forwards it into
#: ``DecodeEngine.submit(resume_from=...)`` so the engine replays the
#: delivered prefix deterministically and suppresses it.
_request_resume_from: "contextvars.ContextVar[int]" = \
    contextvars.ContextVar("rt_serve_request_resume_from", default=0)


def get_request_resume_from() -> int:
    """Delivered-token count of the stream being resumed on this thread
    (0 outside a resumed stream)."""
    return _request_resume_from.get()


#: Handoff hop of the request being handled on this thread: ``None``
#: (plain colocated request), ``"export"`` (prefill hop), or the
#: handoff descriptor dict (decode hop). Set by the replica around user
#: code from :data:`HANDOFF_KEY`; read by the continuous-batching
#: wrapper to pick the engine entry point.
_request_handoff: "contextvars.ContextVar[Any]" = \
    contextvars.ContextVar("rt_serve_request_handoff", default=None)


def get_request_handoff() -> Any:
    """The current request's handoff hop marker (see
    :data:`HANDOFF_KEY`); None outside a disaggregated dispatch."""
    return _request_handoff.get()


#: Correlation id of the request being handled on this thread, set by
#: the replica around user code from :data:`REQUEST_ID_KEY` so nested
#: layers (the continuous-batching wrapper above all) can stamp their
#: flight-recorder events with the router's id instead of minting a
#: disconnected local one.
_request_id: "contextvars.ContextVar[Optional[str]]" = \
    contextvars.ContextVar("rt_serve_request_id", default=None)


def get_request_id() -> Optional[str]:
    """Correlation id of the request being handled on this thread
    (None outside a request scope or for an unstamped legacy caller)."""
    return _request_id.get()


def stream_item_width(item) -> int:
    """Tokens carried by ONE stream item: list/tuple chunk slice →
    its length, ndarray slice → its element count (a ``[B, j]`` slice
    is B*j tokens — ``len()`` would say B), anything else → 1.

    This is the single shared definition behind the replay token: the
    caller-side generator COUNTS delivered tokens with it and the
    replica-side fallback SUPPRESSES that many on resume — if the two
    ever classified an item differently, a resumed stream would
    duplicate or swallow tokens."""
    if isinstance(item, (list, tuple)):
        return len(item)
    if getattr(item, "ndim", 0):
        return int(getattr(item, "size", 1))
    return 1


@dataclass
class Request:
    method: str = "GET"
    path: str = "/"
    query_params: Dict[str, str] = field(default_factory=dict)
    headers: Dict[str, str] = field(default_factory=dict)
    body: bytes = b""

    def json(self) -> Any:
        return _json.loads(self.body or b"null")

    def text(self) -> str:
        return self.body.decode("utf-8", errors="replace")

    @classmethod
    def from_target(cls, method: str, target: str, headers: Dict[str, str],
                    body: bytes) -> "Request":
        parts = urlsplit(target)
        return cls(method=method, path=parts.path,
                   query_params=dict(parse_qsl(parts.query)),
                   headers=headers, body=body)


@dataclass
class Response:
    """Optional rich response; plain return values are auto-encoded."""

    body: Any = b""
    status: int = 200
    content_type: Optional[str] = None

    def encode(self):
        ctype, body = encode_body(self.body)
        return self.status, self.content_type or ctype, body


def encode_body(value: Any):
    """Encode a handler return value to (content_type, bytes)."""
    if isinstance(value, Response):
        _, ctype, body = value.encode()
        return ctype, body
    if isinstance(value, bytes):
        return "application/octet-stream", value
    if isinstance(value, str):
        return "text/plain; charset=utf-8", value.encode()
    try:
        import numpy as np

        if isinstance(value, np.ndarray):
            value = value.tolist()
        elif isinstance(value, np.generic):
            value = value.item()
    except Exception:  # noqa: BLE001
        pass
    return "application/json", _json.dumps(value).encode()
