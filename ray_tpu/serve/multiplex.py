"""Model multiplexing: many models per replica with LRU eviction
(reference: ``python/ray/serve/multiplex.py`` ``@serve.multiplexed`` +
``serve.get_multiplexed_model_id`` — one deployment serves a fleet of
per-tenant models, loading each on first use and evicting the least
recently used when the per-replica budget is hit).

Usage::

    @serve.deployment
    class ModelZoo:
        @serve.multiplexed(max_num_models_per_replica=3)
        def get_model(self, model_id: str):
            return load_model_somehow(model_id)   # may be async

        def __call__(self, request):
            model = self.get_model(serve.get_multiplexed_model_id())
            return model.predict(request)

    handle.options(multiplexed_model_id="tenant-42").remote(x)
"""
from __future__ import annotations

import contextvars
import functools
import inspect
import threading
from collections import OrderedDict
from typing import Callable, Optional

# Set by the replica around each request (from the handle's options).
_request_model_id: contextvars.ContextVar = contextvars.ContextVar(
    "rt_serve_multiplexed_model_id", default="")


def get_multiplexed_model_id() -> str:
    """The ``multiplexed_model_id`` the current request was sent with
    (empty string when the caller did not set one)."""
    return _request_model_id.get()


def _run_coro_blocking(coro):
    """Run an async loader to completion from sync code. A plain
    ``asyncio.run`` would raise when the calling thread already has a
    running loop (async deployments execute requests under
    ``asyncio.run``), so the coroutine gets its own thread + loop."""
    import asyncio

    result: dict = {}

    def runner():
        try:
            result["value"] = asyncio.run(coro)
        except BaseException as e:  # noqa: BLE001 - re-raised below
            result["error"] = e

    t = threading.Thread(target=runner, name="rt-multiplex-loader")
    t.start()
    t.join()
    if "error" in result:
        raise result["error"]
    return result["value"]


class _ModelCache:
    """Per-replica LRU of loaded models. Loads are serialized per
    model_id: concurrent first requests for the same tenant wait on one
    loader call instead of loading (and transiently double-allocating)
    the model twice."""

    def __init__(self, loader: Callable, capacity: int):
        self.loader = loader
        self.capacity = capacity
        self._lock = threading.Lock()
        self._models: OrderedDict = OrderedDict()
        self._loading: dict = {}   # model_id -> threading.Event

    def get(self, model_id: str):
        with self._lock:
            if model_id in self._models:
                self._models.move_to_end(model_id)
                return True, self._models[model_id]
        return False, None

    def get_or_load(self, self_obj, model_id: str):
        while True:
            hit, model = self.get(model_id)
            if hit:
                return model
            with self._lock:
                if model_id in self._models:
                    self._models.move_to_end(model_id)
                    return self._models[model_id]
                ev = self._loading.get(model_id)
                if ev is None:
                    ev = self._loading[model_id] = threading.Event()
                    leader = True
                else:
                    leader = False
            if not leader:
                ev.wait()
                continue  # loader finished (or failed) — re-check cache
            try:
                out = self.loader(self_obj, model_id)
                if inspect.iscoroutine(out):
                    out = _run_coro_blocking(out)
                return self._put(model_id, out)
            finally:
                with self._lock:
                    self._loading.pop(model_id, None)
                ev.set()

    def _put(self, model_id: str, model):
        evicted = []
        with self._lock:
            self._models[model_id] = model
            self._models.move_to_end(model_id)
            while len(self._models) > self.capacity:
                evicted.append(self._models.popitem(last=False))
        # Dropped outside the lock: a model's __del__ may be heavy
        # (freeing device buffers).
        del evicted
        return model

    def model_ids(self):
        with self._lock:
            return list(self._models)


def multiplexed(_fn: Optional[Callable] = None, *,
                max_num_models_per_replica: int = 3):
    """Decorator for a replica's model-loader method: caches up to
    ``max_num_models_per_replica`` loaded models per replica, LRU-evicted.
    The wrapped loader may be sync or async; the wrapper is sync (our
    replicas are thread-concurrent)."""
    if max_num_models_per_replica < 1:
        raise ValueError("max_num_models_per_replica must be >= 1")

    def decorate(fn):
        attr = f"__rt_model_cache_{fn.__name__}"

        @functools.wraps(fn)
        def wrapper(self, model_id: str):
            # dict.setdefault is atomic under the GIL — no closure lock
            # (a lock in the closure would make the deployment class
            # unpicklable).
            cache = self.__dict__.get(attr)
            if cache is None:
                cache = self.__dict__.setdefault(
                    attr, _ModelCache(fn, max_num_models_per_replica))
            return cache.get_or_load(self, model_id)

        wrapper.__rt_is_multiplexed__ = True
        return wrapper

    if _fn is not None and callable(_fn):
        return decorate(_fn)
    return decorate
