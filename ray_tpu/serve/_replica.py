"""Replica actor: hosts one copy of a deployment's user callable.

Capability parity with the reference's replica
(reference: ``python/ray/serve/_private/replica.py:231`` — user callable
wrapper, ongoing-request accounting, health checks, reconfigure), rebuilt
for this runtime's threaded actors: requests execute on the actor's
``max_concurrency`` thread pool, ongoing counts are plain
lock-protected integers, and metrics are pulled by the controller.
"""
from __future__ import annotations

import asyncio
import inspect
import threading
import time
from typing import Any, Dict, Optional

import cloudpickle

from .._private import events as _events
from ..util import tracing
from .request import (HANDOFF_KEY, REQUEST_ID_KEY, RESUME_FROM_KEY,
                      SUBMITTED_AT_KEY, TRACE_CTX_KEY,
                      ReplicaDrainingError, ReplicaOverloadedError,
                      RequestDeadlineExceeded, _request_deadline,
                      _request_deployment, _request_handoff, _request_id,
                      _request_resume_from, deadline_expired)

#: Bound on the fault-injection invocation log (test hook, see below).
_INVOCATION_LOG_CAP = 10_000


class Replica:
    """Created by the controller with
    ``max_concurrency = max_ongoing_requests + headroom`` so that metrics and
    health probes still run while requests saturate the pool.

    Request lifecycle (server half; ``handle.py`` is the client half):
    every request is admitted under the lock BEFORE user code runs —
    a replica at ``max_ongoing_requests`` pushes back with the typed
    ``ReplicaOverloadedError`` (the router re-picks, it does not mark
    the replica dead), and a request whose absolute deadline already
    passed is dropped with ``RequestDeadlineExceeded`` so TPU cycles are
    never spent computing answers nobody is waiting for. The deadline is
    exposed to user code (and the batcher) via a contextvar."""

    def __init__(self, app_name: str, deployment_name: str, replica_id: str,
                 payload: bytes, user_config: Any = None,
                 max_ongoing_requests: int = 0,
                 engine_config: Optional[dict] = None):
        self.app_name = app_name
        self.deployment_name = deployment_name
        self.replica_id = replica_id
        callable_def, init_args, init_kwargs = cloudpickle.loads(payload)
        init_args = _resolve_handles(app_name, init_args)
        init_kwargs = _resolve_handles(app_name, init_kwargs)
        if inspect.isclass(callable_def):
            self._user = callable_def(*init_args, **init_kwargs)
        else:
            self._user = callable_def  # plain function deployment
        if engine_config:
            self._apply_engine_config(engine_config)
        self._lock = threading.Lock()
        # Signalled when the last in-flight request finishes, so drain()
        # wakes immediately instead of polling (rtlint RT104 audit: the
        # old 10 ms sleep loop burned a controller RPC thread and added
        # up to 10 ms to every graceful teardown). Shares _lock, so
        # _ongoing stays single-lock state.
        self._idle_cond = threading.Condition(self._lock)
        self._ongoing = 0
        self._total = 0
        # Server-side admission bound; 0 = unlimited (the controller
        # passes the deployment's max_ongoing_requests).
        self._max_ongoing = int(max_ongoing_requests or 0)
        self._expired = 0
        self._overloaded = 0
        # Graceful-drain state: once draining, admissions push back with
        # the retryable ReplicaDrainingError (router re-picks) while
        # running work finishes.
        self._draining = False
        self._drains = 0
        self._start_time = time.time()
        # Fault-injection hook (armed via set_fault_injection; testing
        # only): optional per-request latency/error plus an invocation
        # log recording (method, start, deadline) for every admitted
        # request — overload and deadline tests assert on it instead of
        # relying on real slowness.
        self._fault: Dict[str, Any] = {}
        self._invocations: list = []
        if user_config is not None:
            self.reconfigure(user_config)

    # ------------------------------------------------------------ data plane
    def _admit(self, method_name: str, ctx: Optional[dict]
               ) -> Optional[float]:
        """Admission gate run before any user code; returns the request
        deadline. Raises the typed pushback/expiry errors."""
        deadline = (ctx or {}).get("deadline_s")
        with self._lock:
            if self._draining:
                # Routing signal, not a failure: the router re-picks
                # another replica; this one is being torn down.
                raise ReplicaDrainingError(
                    f"{self.replica_id} is draining for shutdown")
            if deadline_expired(deadline):
                self._expired += 1
                self._count_lifecycle("requests_expired", "replica")
                raise RequestDeadlineExceeded(
                    f"request deadline passed before {self.replica_id} "
                    f"started {method_name}")
            if self._max_ongoing and self._ongoing >= self._max_ongoing:
                self._overloaded += 1
                raise ReplicaOverloadedError(
                    f"{self.replica_id} at max_ongoing_requests="
                    f"{self._max_ongoing}")
            self._ongoing += 1
            self._total += 1
            ongoing = self._ongoing
        _events.emit("replica.admit",
                     request=(ctx or {}).get(REQUEST_ID_KEY, ""),
                     replica=self.replica_id,
                     deployment=self.deployment_name,
                     method=method_name, ongoing=ongoing)
        self._observe_queue_wait(ctx)
        return deadline

    def _observe_queue_wait(self, ctx: Optional[dict]):
        """``replica.queue_wait`` stage: submission stamp (router side)
        to admission here — transit plus any actor-mailbox queueing.
        Wall-clock across processes, like the deadline it rides with."""
        submitted_at = (ctx or {}).get(SUBMITTED_AT_KEY)
        if submitted_at is None:
            return
        now = time.time()
        # Cross-machine wall clocks: clamp so skew never yields a
        # negative wait (histogram) or an end-before-start span.
        start = min(submitted_at, now)
        from .._private.metrics import serve_metrics

        serve_metrics()["queue_wait"].observe(
            now - start,
            labels={"deployment": self.deployment_name,
                    "where": "replica"})
        tctx = (ctx or {}).get(TRACE_CTX_KEY)
        if tctx is not None:
            tracing.record_span("replica.queue_wait", start, now,
                                parent_ctx=tctx,
                                deployment=self.deployment_name,
                                replica=self.replica_id)

    def _count_lifecycle(self, name: str, where: str):
        from .._private.metrics import serve_metrics

        serve_metrics()[name].inc(
            labels={"deployment": self.deployment_name, "where": where})

    def _pre_invoke(self, method_name: str, deadline: Optional[float]):
        """Fault-injection hook: log the invocation, then apply the
        configured latency/error. A no-op unless armed."""
        fi = self._fault
        if not fi:
            return
        with self._lock:
            self._invocations.append(
                {"method": method_name, "start": time.time(),
                 "deadline": deadline})
            if len(self._invocations) > _INVOCATION_LOG_CAP:
                del self._invocations[:-_INVOCATION_LOG_CAP]
        if fi.get("latency_s"):
            time.sleep(fi["latency_s"])
        rate = fi.get("error_rate", 0.0)
        if rate:
            import random

            if random.random() < rate:
                raise RuntimeError(
                    f"injected fault on {self.replica_id}.{method_name}")

    def handle_request(self, method_name: str, args: tuple, kwargs: dict,
                       ctx: dict = None):
        deadline = self._admit(method_name, ctx)
        token = None
        if ctx and ctx.get("multiplexed_model_id"):
            from .multiplex import _request_model_id

            token = _request_model_id.set(ctx["multiplexed_model_id"])
        dl_token = _request_deadline.set(deadline)
        dep_token = _request_deployment.set(self.deployment_name)
        rid_token = _request_id.set(
            (ctx or {}).get(REQUEST_ID_KEY) or None)
        # Prefill hop of a disaggregated dispatch (ISSUE 14): the
        # continuous-batching wrapper answers with a leased handoff
        # descriptor instead of a stream.
        ho_token = _request_handoff.set((ctx or {}).get(HANDOFF_KEY))
        try:
            self._pre_invoke(method_name, deadline)
            if inspect.isfunction(self._user) or inspect.isbuiltin(self._user):
                method = self._user
            else:
                method = getattr(self._user, method_name)
            # user_code stage span: the slice of the request actually
            # spent in the deployment's handler (queue waits and
            # transport excluded). Nested spans/handle calls/batch
            # submissions inside the handler parent under it.
            with tracing.span("user_code", kind="stage",
                              deployment=self.deployment_name,
                              method=method_name):
                out = method(*args, **kwargs)
                if inspect.iscoroutine(out):
                    # Per-call loop: our replicas are thread-concurrent,
                    # not loop-concurrent; shared batching state lives
                    # in serve.batching's thread queues instead.
                    out = asyncio.run(out)
            return out
        finally:
            _request_handoff.reset(ho_token)
            _request_id.reset(rid_token)
            _request_deployment.reset(dep_token)
            _request_deadline.reset(dl_token)
            if token is not None:
                from .multiplex import _request_model_id

                _request_model_id.reset(token)
            with self._lock:
                self._ongoing -= 1
                if self._ongoing == 0:
                    self._idle_cond.notify_all()

    def handle_request_streaming(self, method_name: str, args: tuple,
                                 kwargs: dict, ctx: dict = None):
        """Generator twin of ``handle_request`` (reference:
        ``serve/_private/replica.py:391-543`` handle_request_streaming):
        items from the user generator stream back to the caller one at a
        time over the core streaming-generator transport instead of
        buffering the whole response.

        Chunked-decode mode: handlers on the fused decode path yield
        per-chunk token SLICES (one list per device dispatch) rather
        than per-token items. Those stream through unchanged — one
        stream item per chunk — unless the caller sets
        ``ctx["flatten_chunks"]``, which re-yields each list/tuple item
        element-wise so per-token consumers keep token granularity
        without a second code path on the replica.

        Mid-stream failover (``ctx["resume_from"] = n``): the caller
        already holds the first ``n`` tokens of this deterministic
        stream, delivered by a replica that has since died. Engine-fed
        streams suppress the replayed prefix INSIDE the engine (the
        continuous-batching wrapper forwards the count into
        ``engine.submit``); any other handler gets the generic fallback
        — the replica drops the first ``n`` tokens of the replayed
        stream before they reach the wire."""
        deadline = self._admit(method_name, ctx)
        token = None
        if ctx and ctx.get("multiplexed_model_id"):
            from .multiplex import _request_model_id

            token = _request_model_id.set(ctx["multiplexed_model_id"])
        resume_from = int((ctx or {}).get(RESUME_FROM_KEY, 0) or 0)
        dl_token = _request_deadline.set(deadline)
        dep_token = _request_deployment.set(self.deployment_name)
        rid_token = _request_id.set(
            (ctx or {}).get(REQUEST_ID_KEY) or None)
        rf_token = _request_resume_from.set(resume_from)
        # Decode hop of a disaggregated dispatch (ISSUE 14): the
        # continuous-batching wrapper imports the shipped KV instead of
        # prefilling locally (or falls back to a local prefill when the
        # payload is gone/corrupt — token-identical by determinism).
        ho_token = _request_handoff.set((ctx or {}).get(HANDOFF_KEY))
        try:
            self._pre_invoke(method_name, deadline)
            # user_code stage span covers the ITERATION of the handler
            # (the whole stream), mirroring _traced_gen's contract for
            # generator tasks; per-dispatch chunk spans nest inside it.
            with tracing.span("user_code", kind="stage",
                              deployment=self.deployment_name,
                              method=method_name):
                out = self._invoke_user(method_name, args, kwargs)
                # Continuous-engine streams (@serve.batch(continuous=
                # True)) carry their own per-dispatch decode.chunk spans
                # with real device timing — recording pull-wait spans
                # here too would double-count the stage.
                engine_fed = bool(getattr(out, "__rt_engine_stream__",
                                          False))
                items = self._traced_items(self._normalize_stream(out),
                                           engine_fed=engine_fed)
                if resume_from and not engine_fed:
                    items = self._suppress_prefix(items, resume_from)
                if ctx and ctx.get("flatten_chunks"):
                    for item in items:
                        if isinstance(item, (list, tuple)):
                            yield from item
                        elif getattr(item, "ndim", 0):
                            # ndarray chunk slice (e.g. generate_chunked's
                            # [B, j]): row-major flatten to scalars — for
                            # the B == 1 serving case that is exactly
                            # per-token order.
                            yield from item.ravel().tolist()
                        else:
                            yield item
                else:
                    yield from items
        finally:
            _request_handoff.reset(ho_token)
            _request_resume_from.reset(rf_token)
            _request_id.reset(rid_token)
            _request_deployment.reset(dep_token)
            _request_deadline.reset(dl_token)
            if token is not None:
                from .multiplex import _request_model_id

                _request_model_id.reset(token)
            with self._lock:
                self._ongoing -= 1
                if self._ongoing == 0:
                    self._idle_cond.notify_all()

    @staticmethod
    def _suppress_prefix(items, n: int):
        """Replay-token suppression for non-engine streams: drop the
        first ``n`` TOKENS — counted by the same
        :func:`~.request.stream_item_width` contract the caller-side
        generator records deliveries with — from a deterministically
        replayed stream, so a resumed caller never sees a duplicate.
        The chunk containing the boundary is trimmed, not dropped."""
        from .request import stream_item_width

        for item in items:
            if n <= 0:
                yield item
                continue
            w = stream_item_width(item)
            if w <= n:
                n -= w
                continue
            if isinstance(item, (list, tuple)):
                yield list(item[n:])
            else:
                yield item.reshape(-1)[n:]
            n = 0

    @staticmethod
    def _traced_items(items, engine_fed: bool = False):
        """Pass-through iterator that records one stage span per stream
        item when the request is traced: ``decode.chunk`` for chunk
        slices (list/tuple/array — one fused device dispatch each),
        ``stream.item`` for scalar items. The span covers the time this
        replica spent PRODUCING the item (the pull from the user
        generator), which for chunked decode is exactly one dispatch.
        ``engine_fed`` streams skip span recording entirely: the decode
        engine records one authoritative ``decode.chunk`` span per fused
        dispatch on its driver thread."""
        from ..util.tracing import current_context, record_span

        if engine_fed or current_context() is None:
            yield from items  # untraced / engine-traced: no overhead
            return
        idx = 0
        while True:
            t0 = time.time()
            try:
                item = next(items)
            except StopIteration:
                return
            chunk = isinstance(item, (list, tuple)) or \
                bool(getattr(item, "ndim", 0))
            if isinstance(item, (list, tuple)):
                width = len(item)
            elif getattr(item, "ndim", 0):
                # ndarray chunk slice [B, j]: every element is a token
                # (len() would report B, undercounting by the chunk
                # factor the span exists to record).
                width = int(getattr(item, "size", 1))
            else:
                width = 1
            record_span("decode.chunk" if chunk else "stream.item",
                        t0, index=idx, tokens=width)
            idx += 1
            yield item

    def _invoke_user(self, method_name: str, args: tuple, kwargs: dict):
        """Call the user callable and return its RAW result (generator,
        coroutine, engine stream, plain value) without starting any
        iteration — the caller inspects it before normalization."""
        if inspect.isfunction(self._user) or inspect.isbuiltin(self._user):
            method = self._user
        else:
            method = getattr(self._user, method_name)
        return method(*args, **kwargs)

    def _normalize_stream(self, out):
        """Normalize one raw handler result into a sync iterator."""
        if inspect.isasyncgen(out):
            # Drain the async generator on a private loop; the
            # replica's concurrency model is threads, not one loop.
            loop = asyncio.new_event_loop()
            try:
                while True:
                    try:
                        yield loop.run_until_complete(out.__anext__())
                    except StopAsyncIteration:
                        break
            finally:
                # Abandoned stream: run the handler's cleanup
                # (try/finally, context managers) before the loop
                # goes away — GC would otherwise try to aclose on a
                # closed loop.
                try:
                    loop.run_until_complete(out.aclose())
                except Exception:  # noqa: BLE001 - cleanup best-effort
                    pass
                loop.close()
        elif inspect.isgenerator(out) or hasattr(out, "__next__"):
            yield from out
        else:
            if inspect.iscoroutine(out):
                out = asyncio.run(out)
            # Non-generator handler called in streaming mode: a
            # single-item stream keeps the caller's contract.
            yield out

    # ---------------------------------------------------------- control plane
    def _engines(self) -> list:
        """Every DecodeEngine the user callable constructed (the units
        the supervisor, drain, and chaos fault points operate on)."""
        from .engine import DecodeEngine

        if not hasattr(self._user, "__dict__"):
            return []
        return [v for v in vars(self._user).values()
                if isinstance(v, DecodeEngine)]

    def _apply_engine_config(self, engine_config: dict):
        """Push the deployment schema's ``engine:`` block (paged-KV +
        speculative-decoding knobs) into every DecodeEngine the user
        callable constructed — applied right after ``__init__``, before
        any traffic, which is the only window an engine may be repaged
        or given a drafter in."""
        for eng in self._engines():
            eng.apply_config(**engine_config)

    def get_metrics(self) -> Dict[str, Any]:
        with self._lock:
            out = {"replica_id": self.replica_id, "ongoing": self._ongoing,
                   "total": self._total,
                   "expired": self._expired,
                   "overloaded": self._overloaded,
                   "draining": self._draining,
                   "drains": self._drains,
                   "uptime": time.time() - self._start_time}
        try:
            engines = self._engines()
            if engines:
                out["engines"] = [e.stats() for e in engines]
        except Exception:  # noqa: BLE001 - metrics stay useful without it
            pass
        return out

    def claim_handoff(self, lease_id: str, epoch: int) -> bool:
        """Release one handoff lease on this (prefill) replica's
        engines — the decode side imported the shipped KV, so the pin
        on the shipped object may drop before the lease expires. Fired
        by the router after the decode hop's first item; an unknown or
        already-swept lease returns False, which is fine (the importer
        holds its bytes)."""
        return any(eng.claim_handoff(lease_id, epoch)
                   for eng in self._engines())

    def inject_engine_fault(self, kind: str = "driver_die",
                            at_tokens: int = 0,
                            wedge_s: float = 0.0) -> int:
        """Arm one chaos fault (driver death / wedge / process kill at
        token N) on every DecodeEngine of this replica — the fault
        points behind ``tests/test_serve_chaos.py`` and
        ``benchmarks/serve_gpt.py --chaos``. Returns how many engines
        were armed. Testing only."""
        engines = self._engines()
        for eng in engines:
            eng.inject_fault(kind, at_tokens=at_tokens, wedge_s=wedge_s)
        return len(engines)

    def set_fault_injection(self, latency_s: float = 0.0,
                            error_rate: float = 0.0) -> bool:
        """Arm the per-request fault-injection hook (testing only): every
        admitted request is logged, then delayed ``latency_s`` and failed
        with probability ``error_rate`` before user code runs."""
        with self._lock:
            self._fault = {"latency_s": float(latency_s),
                           "error_rate": float(error_rate)}
            self._invocations = []
        return True

    def clear_fault_injection(self) -> bool:
        with self._lock:
            self._fault = {}
        return True

    def get_invocation_log(self) -> list:
        """Invocation records ({method, start, deadline}) captured while
        fault injection is armed — the overload tests assert that no
        invocation STARTED after its request deadline."""
        with self._lock:
            return list(self._invocations)

    def get_node_id(self):
        """The node hosting this replica (locality routing hint)."""
        from ..core.worker import CoreWorker

        core = CoreWorker._current
        return getattr(core, "node_id", None) if core is not None else None

    def check_health(self) -> bool:
        # Engine driver supervision first (ISSUE 7): a dead or wedged
        # driver thread is restarted ONCE — its lanes fail with the
        # retryable EngineRestartError, so clients resume on another
        # replica — and the replica stays healthy. Only a REPEAT failure
        # reports unhealthy, escalating to controller-driven replica
        # replacement.
        for eng in self._engines():
            if not eng.supervise():
                return False
        fn = getattr(self._user, "check_health", None)
        if fn is not None:
            out = fn()
            if inspect.iscoroutine(out):
                out = asyncio.run(out)
            return bool(out) if out is not None else True
        return True

    def reconfigure(self, user_config: Any):
        fn = getattr(self._user, "reconfigure", None)
        if fn is not None:
            out = fn(user_config)
            if inspect.iscoroutine(out):
                asyncio.run(out)
        return True

    def drain(self, timeout_s: float = 5.0) -> bool:
        """Graceful shutdown (controller teardown / scale-down / health
        replacement path): stop admissions — new requests push back with
        the retryable :class:`ReplicaDrainingError` so routers re-pick —
        drain every DecodeEngine (queued requests fail retryably at
        once, running lanes finish, stragglers fail retryably at the
        deadline so clients resume elsewhere), then wait for the
        remaining in-flight requests. Returns True when everything
        finished inside the budget; False means stragglers were failed
        retryably. Idempotent. The drain counter/duration metrics are
        observed by the CONTROLLER around this RPC — a replica about to
        be killed may never ship its final metrics snapshot."""
        t0 = time.time()
        deadline = t0 + max(float(timeout_s), 0.0)
        with self._lock:
            self._draining = True
            self._drains += 1
            ongoing = self._ongoing
        _events.emit("replica.drain", replica=self.replica_id,
                     deployment=self.deployment_name, phase="begin",
                     ongoing=ongoing, timeout_s=float(timeout_s))
        for eng in self._engines():
            eng.drain(max(deadline - time.time(), 0.0))
        _events.emit("replica.drain", replica=self.replica_id,
                     deployment=self.deployment_name,
                     phase="engines_drained")
        # Condition wait, not a poll: the last finishing request
        # notifies, so an idle replica returns immediately and a busy
        # one wakes the moment its in-flight count hits zero.
        # rtsan RS104 audit (ISSUE 13): the wait is deadline-bounded
        # AND re-checks the predicate (_ongoing) each wake — a lost
        # notify degrades to the drain budget, never a hang; the only
        # lock held is the condition's own (_idle_cond shares _lock).
        with self._idle_cond:
            while self._ongoing and time.time() < deadline:
                self._idle_cond.wait(
                    timeout=max(deadline - time.time(), 0.0))
            clean = self._ongoing == 0
            stragglers = self._ongoing
        _events.emit("replica.drain", replica=self.replica_id,
                     deployment=self.deployment_name, phase="end",
                     clean=clean, stragglers=stragglers,
                     elapsed_s=round(time.time() - t0, 4))
        return clean


def _resolve_handles(app_name: str, obj):
    """Replace bound-deployment markers with live handles at init time
    (reference analogue: init-arg DAG resolution in
    ``serve/_private/deployment_graph_build.py``)."""
    from .handle import DeploymentHandle, _HandleMarker

    if isinstance(obj, _HandleMarker):
        return DeploymentHandle(app_name, obj.deployment_name)
    if isinstance(obj, tuple):
        return tuple(_resolve_handles(app_name, x) for x in obj)
    if isinstance(obj, list):
        return [_resolve_handles(app_name, x) for x in obj]
    if isinstance(obj, dict):
        return {k: _resolve_handles(app_name, v) for k, v in obj.items()}
    return obj
