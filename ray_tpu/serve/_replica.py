"""Replica actor: hosts one copy of a deployment's user callable.

Capability parity with the reference's replica
(reference: ``python/ray/serve/_private/replica.py:231`` — user callable
wrapper, ongoing-request accounting, health checks, reconfigure), rebuilt
for this runtime's threaded actors: requests execute on the actor's
``max_concurrency`` thread pool, ongoing counts are plain
lock-protected integers, and metrics are pulled by the controller.
"""
from __future__ import annotations

import asyncio
import inspect
import threading
import time
from typing import Any, Dict

import cloudpickle


class Replica:
    """Created by the controller with
    ``max_concurrency = max_ongoing_requests + headroom`` so that metrics and
    health probes still run while requests saturate the pool."""

    def __init__(self, app_name: str, deployment_name: str, replica_id: str,
                 payload: bytes, user_config: Any = None):
        self.app_name = app_name
        self.deployment_name = deployment_name
        self.replica_id = replica_id
        callable_def, init_args, init_kwargs = cloudpickle.loads(payload)
        init_args = _resolve_handles(app_name, init_args)
        init_kwargs = _resolve_handles(app_name, init_kwargs)
        if inspect.isclass(callable_def):
            self._user = callable_def(*init_args, **init_kwargs)
        else:
            self._user = callable_def  # plain function deployment
        self._lock = threading.Lock()
        self._ongoing = 0
        self._total = 0
        self._start_time = time.time()
        if user_config is not None:
            self.reconfigure(user_config)

    # ------------------------------------------------------------ data plane
    def handle_request(self, method_name: str, args: tuple, kwargs: dict,
                       ctx: dict = None):
        with self._lock:
            self._ongoing += 1
            self._total += 1
        token = None
        if ctx and ctx.get("multiplexed_model_id"):
            from .multiplex import _request_model_id

            token = _request_model_id.set(ctx["multiplexed_model_id"])
        try:
            if inspect.isfunction(self._user) or inspect.isbuiltin(self._user):
                method = self._user
            else:
                method = getattr(self._user, method_name)
            out = method(*args, **kwargs)
            if inspect.iscoroutine(out):
                # Per-call loop: our replicas are thread-concurrent, not
                # loop-concurrent; shared batching state lives in
                # serve.batching's thread queues instead.
                out = asyncio.run(out)
            return out
        finally:
            if token is not None:
                from .multiplex import _request_model_id

                _request_model_id.reset(token)
            with self._lock:
                self._ongoing -= 1

    def handle_request_streaming(self, method_name: str, args: tuple,
                                 kwargs: dict, ctx: dict = None):
        """Generator twin of ``handle_request`` (reference:
        ``serve/_private/replica.py:391-543`` handle_request_streaming):
        items from the user generator stream back to the caller one at a
        time over the core streaming-generator transport instead of
        buffering the whole response.

        Chunked-decode mode: handlers on the fused decode path yield
        per-chunk token SLICES (one list per device dispatch) rather
        than per-token items. Those stream through unchanged — one
        stream item per chunk — unless the caller sets
        ``ctx["flatten_chunks"]``, which re-yields each list/tuple item
        element-wise so per-token consumers keep token granularity
        without a second code path on the replica."""
        with self._lock:
            self._ongoing += 1
            self._total += 1
        token = None
        if ctx and ctx.get("multiplexed_model_id"):
            from .multiplex import _request_model_id

            token = _request_model_id.set(ctx["multiplexed_model_id"])
        try:
            items = self._user_stream(method_name, args, kwargs)
            if ctx and ctx.get("flatten_chunks"):
                for item in items:
                    if isinstance(item, (list, tuple)):
                        yield from item
                    elif getattr(item, "ndim", 0):
                        # ndarray chunk slice (e.g. generate_chunked's
                        # [B, j]): row-major flatten to scalars — for
                        # the B == 1 serving case that is exactly
                        # per-token order.
                        yield from item.ravel().tolist()
                    else:
                        yield item
            else:
                yield from items
        finally:
            if token is not None:
                from .multiplex import _request_model_id

                _request_model_id.reset(token)
            with self._lock:
                self._ongoing -= 1

    def _user_stream(self, method_name: str, args: tuple, kwargs: dict):
        """Invoke the user callable and normalize every handler shape
        (sync/async generator, coroutine, plain value) into one sync
        iterator."""
        if inspect.isfunction(self._user) or inspect.isbuiltin(self._user):
            method = self._user
        else:
            method = getattr(self._user, method_name)
        out = method(*args, **kwargs)
        if inspect.isasyncgen(out):
            # Drain the async generator on a private loop; the
            # replica's concurrency model is threads, not one loop.
            loop = asyncio.new_event_loop()
            try:
                while True:
                    try:
                        yield loop.run_until_complete(out.__anext__())
                    except StopAsyncIteration:
                        break
            finally:
                # Abandoned stream: run the handler's cleanup
                # (try/finally, context managers) before the loop
                # goes away — GC would otherwise try to aclose on a
                # closed loop.
                try:
                    loop.run_until_complete(out.aclose())
                except Exception:  # noqa: BLE001 - cleanup best-effort
                    pass
                loop.close()
        elif inspect.isgenerator(out) or hasattr(out, "__next__"):
            yield from out
        else:
            if inspect.iscoroutine(out):
                out = asyncio.run(out)
            # Non-generator handler called in streaming mode: a
            # single-item stream keeps the caller's contract.
            yield out

    # ---------------------------------------------------------- control plane
    def get_metrics(self) -> Dict[str, Any]:
        with self._lock:
            return {"replica_id": self.replica_id, "ongoing": self._ongoing,
                    "total": self._total, "uptime": time.time() - self._start_time}

    def get_node_id(self):
        """The node hosting this replica (locality routing hint)."""
        from ..core.worker import CoreWorker

        core = CoreWorker._current
        return getattr(core, "node_id", None) if core is not None else None

    def check_health(self) -> bool:
        fn = getattr(self._user, "check_health", None)
        if fn is not None:
            out = fn()
            if inspect.iscoroutine(out):
                out = asyncio.run(out)
            return bool(out) if out is not None else True
        return True

    def reconfigure(self, user_config: Any):
        fn = getattr(self._user, "reconfigure", None)
        if fn is not None:
            out = fn(user_config)
            if inspect.iscoroutine(out):
                asyncio.run(out)
        return True

    def drain(self, timeout_s: float = 5.0) -> bool:
        """Graceful shutdown: wait for in-flight requests to finish."""
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            with self._lock:
                if self._ongoing == 0:
                    return True
            time.sleep(0.01)
        return False


def _resolve_handles(app_name: str, obj):
    """Replace bound-deployment markers with live handles at init time
    (reference analogue: init-arg DAG resolution in
    ``serve/_private/deployment_graph_build.py``)."""
    from .handle import DeploymentHandle, _HandleMarker

    if isinstance(obj, _HandleMarker):
        return DeploymentHandle(app_name, obj.deployment_name)
    if isinstance(obj, tuple):
        return tuple(_resolve_handles(app_name, x) for x in obj)
    if isinstance(obj, list):
        return [_resolve_handles(app_name, x) for x in obj]
    if isinstance(obj, dict):
        return {k: _resolve_handles(app_name, v) for k, v in obj.items()}
    return obj
