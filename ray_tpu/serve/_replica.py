"""Replica actor: hosts one copy of a deployment's user callable.

Capability parity with the reference's replica
(reference: ``python/ray/serve/_private/replica.py:231`` — user callable
wrapper, ongoing-request accounting, health checks, reconfigure), rebuilt
for this runtime's threaded actors: requests execute on the actor's
``max_concurrency`` thread pool, ongoing counts are plain
lock-protected integers, and metrics are pulled by the controller.
"""
from __future__ import annotations

import asyncio
import inspect
import threading
import time
from typing import Any, Dict, Optional

import cloudpickle

from ..util import tracing
from .request import (SUBMITTED_AT_KEY, TRACE_CTX_KEY,
                      ReplicaOverloadedError, RequestDeadlineExceeded,
                      _request_deadline, _request_deployment,
                      deadline_expired)

#: Bound on the fault-injection invocation log (test hook, see below).
_INVOCATION_LOG_CAP = 10_000


class Replica:
    """Created by the controller with
    ``max_concurrency = max_ongoing_requests + headroom`` so that metrics and
    health probes still run while requests saturate the pool.

    Request lifecycle (server half; ``handle.py`` is the client half):
    every request is admitted under the lock BEFORE user code runs —
    a replica at ``max_ongoing_requests`` pushes back with the typed
    ``ReplicaOverloadedError`` (the router re-picks, it does not mark
    the replica dead), and a request whose absolute deadline already
    passed is dropped with ``RequestDeadlineExceeded`` so TPU cycles are
    never spent computing answers nobody is waiting for. The deadline is
    exposed to user code (and the batcher) via a contextvar."""

    def __init__(self, app_name: str, deployment_name: str, replica_id: str,
                 payload: bytes, user_config: Any = None,
                 max_ongoing_requests: int = 0,
                 engine_config: Optional[dict] = None):
        self.app_name = app_name
        self.deployment_name = deployment_name
        self.replica_id = replica_id
        callable_def, init_args, init_kwargs = cloudpickle.loads(payload)
        init_args = _resolve_handles(app_name, init_args)
        init_kwargs = _resolve_handles(app_name, init_kwargs)
        if inspect.isclass(callable_def):
            self._user = callable_def(*init_args, **init_kwargs)
        else:
            self._user = callable_def  # plain function deployment
        if engine_config:
            self._apply_engine_config(engine_config)
        self._lock = threading.Lock()
        self._ongoing = 0
        self._total = 0
        # Server-side admission bound; 0 = unlimited (the controller
        # passes the deployment's max_ongoing_requests).
        self._max_ongoing = int(max_ongoing_requests or 0)
        self._expired = 0
        self._overloaded = 0
        self._start_time = time.time()
        # Fault-injection hook (armed via set_fault_injection; testing
        # only): optional per-request latency/error plus an invocation
        # log recording (method, start, deadline) for every admitted
        # request — overload and deadline tests assert on it instead of
        # relying on real slowness.
        self._fault: Dict[str, Any] = {}
        self._invocations: list = []
        if user_config is not None:
            self.reconfigure(user_config)

    # ------------------------------------------------------------ data plane
    def _admit(self, method_name: str, ctx: Optional[dict]
               ) -> Optional[float]:
        """Admission gate run before any user code; returns the request
        deadline. Raises the typed pushback/expiry errors."""
        deadline = (ctx or {}).get("deadline_s")
        with self._lock:
            if deadline_expired(deadline):
                self._expired += 1
                self._count_lifecycle("requests_expired", "replica")
                raise RequestDeadlineExceeded(
                    f"request deadline passed before {self.replica_id} "
                    f"started {method_name}")
            if self._max_ongoing and self._ongoing >= self._max_ongoing:
                self._overloaded += 1
                raise ReplicaOverloadedError(
                    f"{self.replica_id} at max_ongoing_requests="
                    f"{self._max_ongoing}")
            self._ongoing += 1
            self._total += 1
        self._observe_queue_wait(ctx)
        return deadline

    def _observe_queue_wait(self, ctx: Optional[dict]):
        """``replica.queue_wait`` stage: submission stamp (router side)
        to admission here — transit plus any actor-mailbox queueing.
        Wall-clock across processes, like the deadline it rides with."""
        submitted_at = (ctx or {}).get(SUBMITTED_AT_KEY)
        if submitted_at is None:
            return
        now = time.time()
        # Cross-machine wall clocks: clamp so skew never yields a
        # negative wait (histogram) or an end-before-start span.
        start = min(submitted_at, now)
        from .._private.metrics import serve_metrics

        serve_metrics()["queue_wait"].observe(
            now - start,
            labels={"deployment": self.deployment_name,
                    "where": "replica"})
        tctx = (ctx or {}).get(TRACE_CTX_KEY)
        if tctx is not None:
            tracing.record_span("replica.queue_wait", start, now,
                                parent_ctx=tctx,
                                deployment=self.deployment_name,
                                replica=self.replica_id)

    def _count_lifecycle(self, name: str, where: str):
        from .._private.metrics import serve_metrics

        serve_metrics()[name].inc(
            labels={"deployment": self.deployment_name, "where": where})

    def _pre_invoke(self, method_name: str, deadline: Optional[float]):
        """Fault-injection hook: log the invocation, then apply the
        configured latency/error. A no-op unless armed."""
        fi = self._fault
        if not fi:
            return
        with self._lock:
            self._invocations.append(
                {"method": method_name, "start": time.time(),
                 "deadline": deadline})
            if len(self._invocations) > _INVOCATION_LOG_CAP:
                del self._invocations[:-_INVOCATION_LOG_CAP]
        if fi.get("latency_s"):
            time.sleep(fi["latency_s"])
        rate = fi.get("error_rate", 0.0)
        if rate:
            import random

            if random.random() < rate:
                raise RuntimeError(
                    f"injected fault on {self.replica_id}.{method_name}")

    def handle_request(self, method_name: str, args: tuple, kwargs: dict,
                       ctx: dict = None):
        deadline = self._admit(method_name, ctx)
        token = None
        if ctx and ctx.get("multiplexed_model_id"):
            from .multiplex import _request_model_id

            token = _request_model_id.set(ctx["multiplexed_model_id"])
        dl_token = _request_deadline.set(deadline)
        dep_token = _request_deployment.set(self.deployment_name)
        try:
            self._pre_invoke(method_name, deadline)
            if inspect.isfunction(self._user) or inspect.isbuiltin(self._user):
                method = self._user
            else:
                method = getattr(self._user, method_name)
            # user_code stage span: the slice of the request actually
            # spent in the deployment's handler (queue waits and
            # transport excluded). Nested spans/handle calls/batch
            # submissions inside the handler parent under it.
            with tracing.span("user_code", kind="stage",
                              deployment=self.deployment_name,
                              method=method_name):
                out = method(*args, **kwargs)
                if inspect.iscoroutine(out):
                    # Per-call loop: our replicas are thread-concurrent,
                    # not loop-concurrent; shared batching state lives
                    # in serve.batching's thread queues instead.
                    out = asyncio.run(out)
            return out
        finally:
            _request_deployment.reset(dep_token)
            _request_deadline.reset(dl_token)
            if token is not None:
                from .multiplex import _request_model_id

                _request_model_id.reset(token)
            with self._lock:
                self._ongoing -= 1

    def handle_request_streaming(self, method_name: str, args: tuple,
                                 kwargs: dict, ctx: dict = None):
        """Generator twin of ``handle_request`` (reference:
        ``serve/_private/replica.py:391-543`` handle_request_streaming):
        items from the user generator stream back to the caller one at a
        time over the core streaming-generator transport instead of
        buffering the whole response.

        Chunked-decode mode: handlers on the fused decode path yield
        per-chunk token SLICES (one list per device dispatch) rather
        than per-token items. Those stream through unchanged — one
        stream item per chunk — unless the caller sets
        ``ctx["flatten_chunks"]``, which re-yields each list/tuple item
        element-wise so per-token consumers keep token granularity
        without a second code path on the replica."""
        deadline = self._admit(method_name, ctx)
        token = None
        if ctx and ctx.get("multiplexed_model_id"):
            from .multiplex import _request_model_id

            token = _request_model_id.set(ctx["multiplexed_model_id"])
        dl_token = _request_deadline.set(deadline)
        dep_token = _request_deployment.set(self.deployment_name)
        try:
            self._pre_invoke(method_name, deadline)
            # user_code stage span covers the ITERATION of the handler
            # (the whole stream), mirroring _traced_gen's contract for
            # generator tasks; per-dispatch chunk spans nest inside it.
            with tracing.span("user_code", kind="stage",
                              deployment=self.deployment_name,
                              method=method_name):
                out = self._invoke_user(method_name, args, kwargs)
                # Continuous-engine streams (@serve.batch(continuous=
                # True)) carry their own per-dispatch decode.chunk spans
                # with real device timing — recording pull-wait spans
                # here too would double-count the stage.
                engine_fed = bool(getattr(out, "__rt_engine_stream__",
                                          False))
                items = self._traced_items(self._normalize_stream(out),
                                           engine_fed=engine_fed)
                if ctx and ctx.get("flatten_chunks"):
                    for item in items:
                        if isinstance(item, (list, tuple)):
                            yield from item
                        elif getattr(item, "ndim", 0):
                            # ndarray chunk slice (e.g. generate_chunked's
                            # [B, j]): row-major flatten to scalars — for
                            # the B == 1 serving case that is exactly
                            # per-token order.
                            yield from item.ravel().tolist()
                        else:
                            yield item
                else:
                    yield from items
        finally:
            _request_deployment.reset(dep_token)
            _request_deadline.reset(dl_token)
            if token is not None:
                from .multiplex import _request_model_id

                _request_model_id.reset(token)
            with self._lock:
                self._ongoing -= 1

    @staticmethod
    def _traced_items(items, engine_fed: bool = False):
        """Pass-through iterator that records one stage span per stream
        item when the request is traced: ``decode.chunk`` for chunk
        slices (list/tuple/array — one fused device dispatch each),
        ``stream.item`` for scalar items. The span covers the time this
        replica spent PRODUCING the item (the pull from the user
        generator), which for chunked decode is exactly one dispatch.
        ``engine_fed`` streams skip span recording entirely: the decode
        engine records one authoritative ``decode.chunk`` span per fused
        dispatch on its driver thread."""
        from ..util.tracing import current_context, record_span

        if engine_fed or current_context() is None:
            yield from items  # untraced / engine-traced: no overhead
            return
        idx = 0
        while True:
            t0 = time.time()
            try:
                item = next(items)
            except StopIteration:
                return
            chunk = isinstance(item, (list, tuple)) or \
                bool(getattr(item, "ndim", 0))
            if isinstance(item, (list, tuple)):
                width = len(item)
            elif getattr(item, "ndim", 0):
                # ndarray chunk slice [B, j]: every element is a token
                # (len() would report B, undercounting by the chunk
                # factor the span exists to record).
                width = int(getattr(item, "size", 1))
            else:
                width = 1
            record_span("decode.chunk" if chunk else "stream.item",
                        t0, index=idx, tokens=width)
            idx += 1
            yield item

    def _invoke_user(self, method_name: str, args: tuple, kwargs: dict):
        """Call the user callable and return its RAW result (generator,
        coroutine, engine stream, plain value) without starting any
        iteration — the caller inspects it before normalization."""
        if inspect.isfunction(self._user) or inspect.isbuiltin(self._user):
            method = self._user
        else:
            method = getattr(self._user, method_name)
        return method(*args, **kwargs)

    def _normalize_stream(self, out):
        """Normalize one raw handler result into a sync iterator."""
        if inspect.isasyncgen(out):
            # Drain the async generator on a private loop; the
            # replica's concurrency model is threads, not one loop.
            loop = asyncio.new_event_loop()
            try:
                while True:
                    try:
                        yield loop.run_until_complete(out.__anext__())
                    except StopAsyncIteration:
                        break
            finally:
                # Abandoned stream: run the handler's cleanup
                # (try/finally, context managers) before the loop
                # goes away — GC would otherwise try to aclose on a
                # closed loop.
                try:
                    loop.run_until_complete(out.aclose())
                except Exception:  # noqa: BLE001 - cleanup best-effort
                    pass
                loop.close()
        elif inspect.isgenerator(out) or hasattr(out, "__next__"):
            yield from out
        else:
            if inspect.iscoroutine(out):
                out = asyncio.run(out)
            # Non-generator handler called in streaming mode: a
            # single-item stream keeps the caller's contract.
            yield out

    # ---------------------------------------------------------- control plane
    def _apply_engine_config(self, engine_config: dict):
        """Push the deployment schema's ``engine:`` block (paged KV
        knobs) into every DecodeEngine the user callable constructed —
        applied right after ``__init__``, before any traffic, which is
        the only window an engine may be repaged in."""
        from .engine import DecodeEngine

        for v in vars(self._user).values() \
                if hasattr(self._user, "__dict__") else []:
            if isinstance(v, DecodeEngine):
                v.ensure_paging(**engine_config)

    def get_metrics(self) -> Dict[str, Any]:
        with self._lock:
            out = {"replica_id": self.replica_id, "ongoing": self._ongoing,
                   "total": self._total,
                   "expired": self._expired,
                   "overloaded": self._overloaded,
                   "uptime": time.time() - self._start_time}
        try:
            from .engine import DecodeEngine

            engines = [v for v in vars(self._user).values()
                       if isinstance(v, DecodeEngine)] \
                if hasattr(self._user, "__dict__") else []
            if engines:
                out["engines"] = [e.stats() for e in engines]
        except Exception:  # noqa: BLE001 - metrics stay useful without it
            pass
        return out

    def set_fault_injection(self, latency_s: float = 0.0,
                            error_rate: float = 0.0) -> bool:
        """Arm the per-request fault-injection hook (testing only): every
        admitted request is logged, then delayed ``latency_s`` and failed
        with probability ``error_rate`` before user code runs."""
        with self._lock:
            self._fault = {"latency_s": float(latency_s),
                           "error_rate": float(error_rate)}
            self._invocations = []
        return True

    def clear_fault_injection(self) -> bool:
        with self._lock:
            self._fault = {}
        return True

    def get_invocation_log(self) -> list:
        """Invocation records ({method, start, deadline}) captured while
        fault injection is armed — the overload tests assert that no
        invocation STARTED after its request deadline."""
        with self._lock:
            return list(self._invocations)

    def get_node_id(self):
        """The node hosting this replica (locality routing hint)."""
        from ..core.worker import CoreWorker

        core = CoreWorker._current
        return getattr(core, "node_id", None) if core is not None else None

    def check_health(self) -> bool:
        fn = getattr(self._user, "check_health", None)
        if fn is not None:
            out = fn()
            if inspect.iscoroutine(out):
                out = asyncio.run(out)
            return bool(out) if out is not None else True
        return True

    def reconfigure(self, user_config: Any):
        fn = getattr(self._user, "reconfigure", None)
        if fn is not None:
            out = fn(user_config)
            if inspect.iscoroutine(out):
                asyncio.run(out)
        return True

    def drain(self, timeout_s: float = 5.0) -> bool:
        """Graceful shutdown: wait for in-flight requests to finish."""
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            with self._lock:
                if self._ongoing == 0:
                    return True
            time.sleep(0.01)
        return False


def _resolve_handles(app_name: str, obj):
    """Replace bound-deployment markers with live handles at init time
    (reference analogue: init-arg DAG resolution in
    ``serve/_private/deployment_graph_build.py``)."""
    from .handle import DeploymentHandle, _HandleMarker

    if isinstance(obj, _HandleMarker):
        return DeploymentHandle(app_name, obj.deployment_name)
    if isinstance(obj, tuple):
        return tuple(_resolve_handles(app_name, x) for x in obj)
    if isinstance(obj, list):
        return [_resolve_handles(app_name, x) for x in obj]
    if isinstance(obj, dict):
        return {k: _resolve_handles(app_name, v) for k, v in obj.items()}
    return obj
