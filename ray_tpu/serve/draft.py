"""Drafters for speculative decoding in the :class:`~.engine.DecodeEngine`
(ISSUE 9).

A drafter proposes ``draft_k`` candidate tokens per active slot at every
chunk boundary; the target model then verifies all of them in ONE
batched forward (:func:`~ray_tpu.models.gpt_decode.verify_chunk_slots`)
and commits the accepted prefix plus its own correction/bonus token.
Because acceptance is exact (greedy match at temperature 0, lossless
rejection sampling above it), a drafter can NEVER change the committed
stream — only how many target forwards it takes to produce it — so the
protocol is deliberately tiny and entirely advisory.

Contract every drafter must keep (the engine's replay machinery leans
on it):

- **Determinism**: proposals must be a pure function of the slot's
  committed history (prompt + delivered tokens). Crash-resume replays
  the stream on another replica by re-running the same deterministic
  generation; a stateful or randomized drafter would change the
  accepted lengths — harmless for token identity, but it would shift
  the temperature>0 PRNG chain and break bit-exact replay.
- **Per-slot isolation**: no state shared across slots (a slot's
  proposals must not depend on which other requests are resident).
- **Driver-thread only**: every method is called from the engine's
  driver thread, between device dispatches — no locking, and device
  drafters may dispatch freely (rtlint RT102 ``owner=driver``).

Two implementations ship:

- :class:`NGramDrafter` — a host-side n-gram table per slot, built from
  the prompt and committed tokens (prompt-lookup decoding). Zero device
  cost and zero compiled programs; wins whenever the output is locally
  repetitive (templated/structured text, code, the loops greedy
  decoding falls into).
- :class:`ModelDrafter` — a small GPT (typically sharing the target's
  embedding, see :func:`tied_drafter_params`) decoding greedily into
  its own flat slot pool that mirrors the engine's slots. Wins when a
  trained/distilled draft model actually approximates the target;
  costs ``len(prompt_buckets) + 2`` extra compiled programs (its own
  prefill per bucket, a k-step draft chunk, and a 1-token ingest).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


class Drafter:
    """Protocol for speculative-decoding proposal sources.

    Lifecycle per slot: :meth:`admit` when the engine prefills a prompt
    into it, :meth:`propose` + :meth:`observe` once per verify round
    while the lane runs, :meth:`free` when the lane ends for any reason
    (EOS, max_new, deadline, abandonment, failure). :meth:`configure`
    is called once by the engine before any traffic (and again after a
    supervisor driver restart, via :meth:`reset`)."""

    name = "base"

    def configure(self, *, slots: int, max_len: int,
                  prompt_buckets: Sequence[int], draft_k: int):
        self.slots = int(slots)
        self.max_len = int(max_len)
        self.prompt_buckets = tuple(prompt_buckets)
        self.draft_k = int(draft_k)

    def admit(self, slot: int, prompt: np.ndarray, first_token: int):
        """A prompt was prefilled into ``slot``; ``first_token`` is the
        target's fused first sample (already delivered)."""

    def propose(self, active: np.ndarray, last: np.ndarray) -> np.ndarray:
        """``[slots, draft_k]`` int32 proposals; rows of inactive slots
        are ignored. ``last`` is each slot's last delivered token."""
        raise NotImplementedError

    def observe(self, slot: int, tokens: np.ndarray, accepted: int):
        """``tokens`` were committed to ``slot`` this round (the
        accepted drafts plus the target's correction/bonus);
        ``accepted`` of this drafter's proposals were accepted, or
        ``-1`` when the round ran the plain chunk path (adaptive
        speculation parked this slot, so nothing was proposed —
        only drafters with an :meth:`estimate` ever see ``-1``).
        Called only for lanes that keep running — ended lanes get
        :meth:`free` instead."""

    def estimate(self, slot: int) -> Optional[float]:
        """Expected accepted proposals for a verify round on ``slot``
        right now, or None for "no self-assessment" — the engine then
        always speculates the slot (``None`` is treated as +inf
        against ``spec_threshold``). Must be a deterministic function
        of the slot's committed history: the engine's per-slot
        speculate-or-chunk decision feeds the PRNG consumption
        pattern, so crash-resume replay depends on it."""
        return None

    def free(self, slot: int):
        """The lane in ``slot`` ended; drop its state."""

    def reset(self):
        """Drop ALL per-slot state (supervisor driver restart: the
        engine pool was rebuilt from scratch and every lane failed)."""


class NGramDrafter(Drafter):
    """Host-side prompt-lookup drafter: per slot, an n-gram table from
    the prompt + committed tokens maps each trailing context of length
    ``min_n..max_n`` to its observed continuations; proposals extend
    the history with the MOST FREQUENT continuation of the longest
    matching context (ties break to the smallest token id — the whole
    proposal is deterministic). With no match the last token repeats
    (self-loops are the most common attractor). Zero device cost: the
    engine's compiled-program set stays ``len(prompt_buckets) + 1 + 1``.
    """

    name = "ngram"

    #: EMA smoothing for the per-slot hit self-assessment.
    EMA_ALPHA = 0.5

    def __init__(self, max_n: int = 4, min_n: int = 1):
        if not 1 <= min_n <= max_n:
            raise ValueError(f"need 1 <= min_n <= max_n, got "
                             f"[{min_n}, {max_n}]")
        self.max_n = int(max_n)
        self.min_n = int(min_n)
        self._hist: Dict[int, List[int]] = {}
        #: slot -> {(n, ctx tuple) -> {token -> count}}
        self._tab: Dict[int, Dict[Tuple, Dict[int, int]]] = {}
        #: slot -> EMA of per-round would-have-hit counts (the
        #: adaptive-speculation signal; deterministic from history).
        self._ema: Dict[int, float] = {}

    def _index(self, slot: int, start: int):
        """Count the continuations introduced by hist[start:]."""
        h = self._hist[slot]
        tab = self._tab[slot]
        for t in range(max(start, self.min_n), len(h)):
            for n in range(self.min_n, min(self.max_n, t) + 1):
                key = (n, tuple(h[t - n:t]))
                bucket = tab.setdefault(key, {})
                bucket[h[t]] = bucket.get(h[t], 0) + 1

    def admit(self, slot: int, prompt: np.ndarray, first_token: int):
        self._hist[slot] = [int(t) for t in prompt] + [int(first_token)]
        self._tab[slot] = {}
        self._ema[slot] = 0.0
        self._index(slot, 0)

    def _propose_one(self, slot: int, k: int) -> List[int]:
        """k deterministic proposals extending slot's history: most
        frequent continuation of the longest matching context, ties to
        the smallest token, last-token self-loop as fallback."""
        out: List[int] = []
        tail = list(self._hist[slot][-self.max_n:])
        extra: Dict[Tuple, Dict[int, int]] = {}
        tab = self._tab[slot]
        empty: Dict[int, int] = {}
        for _ in range(k):
            nxt = None
            for n in range(min(self.max_n, len(tail)),
                           self.min_n - 1, -1):
                key = (n, tuple(tail[-n:]))
                base = tab.get(key, empty)
                ext = extra.get(key, empty)
                if not base and not ext:
                    continue
                # Max by (count, -token) over base+ext WITHOUT copying
                # base (this is the propose hot loop): ext tokens get
                # their combined count, pure-base tokens their own.
                best = None
                for tok, c in base.items():
                    if tok not in ext:
                        cand = (c, -tok)
                        if best is None or cand > best:
                            best = cand
                for tok, c in ext.items():
                    cand = (c + base.get(tok, 0), -tok)
                    if best is None or cand > best:
                        best = cand
                nxt = -best[1]
                break
            if nxt is None:
                nxt = tail[-1]
            out.append(nxt)
            # Count the hypothetical extension too, so a proposal that
            # starts a repeat immediately reinforces itself.
            for n in range(self.min_n, min(self.max_n, len(tail)) + 1):
                key = (n, tuple(tail[-n:]))
                b = extra.setdefault(key, {})
                b[nxt] = b.get(nxt, 0) + 1
            tail.append(nxt)
            tail = tail[-self.max_n:]
        return out

    def observe(self, slot: int, tokens: np.ndarray, accepted: int):
        h = self._hist.get(slot)
        if h is None:
            return
        # Self-assessment BEFORE indexing the new tokens: how many of
        # this round's committed tokens would this table have proposed?
        # Verify rounds already measured it — ``accepted`` IS that
        # count; chunk rounds (accepted == -1, nothing was proposed)
        # replay the proposal against the committed row. Either way the
        # EMA is a pure function of the committed history, which
        # adaptive mode leans on for deterministic replay.
        if accepted >= 0:
            hit = accepted
        else:
            hyp = self._propose_one(slot, min(self.draft_k, len(tokens)))
            hit = 0
            for want, got in zip(tokens, hyp):
                if int(want) != got:
                    break
                hit += 1
        self._ema[slot] = ((1.0 - self.EMA_ALPHA) * self._ema[slot]
                           + self.EMA_ALPHA * hit)
        start = len(h)
        h.extend(int(t) for t in tokens)
        self._index(slot, start)

    def estimate(self, slot: int) -> Optional[float]:
        return self._ema.get(slot, 0.0)

    def propose(self, active: np.ndarray, last: np.ndarray) -> np.ndarray:
        out = np.zeros((self.slots, self.draft_k), np.int32)
        for i in range(self.slots):
            if not active[i] or i not in self._hist:
                continue
            out[i, :] = self._propose_one(i, self.draft_k)
        return out

    def free(self, slot: int):
        self._hist.pop(slot, None)
        self._tab.pop(slot, None)
        self._ema.pop(slot, None)

    def reset(self):
        self._hist.clear()
        self._tab.clear()
        self._ema.clear()


class ModelDrafter(Drafter):
    """Device drafter: a small GPT decoding greedily into its OWN flat
    slot pool whose slots mirror the engine's 1:1 (same ``max_len``,
    same prompt buckets, so positions track the target exactly).

    Per verify round the drafter runs one fused k-step greedy chunk
    (:func:`~ray_tpu.models.gpt_decode.decode_chunk_slots` of its own
    model) to propose, and after the verify it rolls its write cursor
    back past rejected positions — host-authoritative ``pos`` is
    re-uploaded wholesale each round, and garbage K/V beyond it is
    overwritten before it is ever attended (the engine's standard
    exactness argument). A fully-accepted round leaves exactly one
    committed token (``d_k``) without K/V in the drafter cache; it is
    ingested lazily by a 1-token chunk before the next proposal, so the
    drafter's compiled-program set is bounded at
    ``len(prompt_buckets) + 2`` for any traffic."""

    name = "model"

    def __init__(self, params, cfg):
        self.params = params
        self.cfg = cfg

    # The drafter's own program set (rtflow RT109): its prefill per
    # prompt bucket + the k-step draft chunk + the 1-token lazy ingest.
    # rtlint: program-budget: len(prompt_buckets) + 2
    def configure(self, *, slots: int, max_len: int,
                  prompt_buckets: Sequence[int], draft_k: int):
        super().configure(slots=slots, max_len=max_len,
                          prompt_buckets=prompt_buckets, draft_k=draft_k)
        if max_len > self.cfg.max_seq:
            raise ValueError(
                f"drafter max_seq {self.cfg.max_seq} cannot mirror "
                f"engine max_len {max_len}")
        from ..models import gpt_decode

        self._gd = gpt_decode
        self._prefill = gpt_decode.jit_prefill_into_slot(self.cfg, 0.0)
        self._step = gpt_decode.jit_decode_chunk_slots(
            self.cfg, self.draft_k, 0.0, -1)
        self._ingest = gpt_decode.jit_decode_chunk_slots(
            self.cfg, 1, 0.0, -1)
        self.reset()

    def reset(self):
        self._cache = self._gd.init_slot_cache(self.cfg, self.slots,
                                               self.max_len)
        self._pos = np.zeros((self.slots,), np.int32)
        self._pending = np.full((self.slots,), -1, np.int64)
        self._rngs = np.zeros((self.slots, 2), np.uint32)

    # entry=driver: admission is the engine driver's first touch of a
    # slot — rtsan re-registers the drafter's owner thread here, so a
    # supervisor-restarted engine (new driver thread, drafter reset)
    # rebinds on its first admission instead of tripping RS103.
    # rtlint: owner=driver entry=driver
    def admit(self, slot: int, prompt: np.ndarray, first_token: int):
        import jax

        S = int(prompt.shape[0])
        bucket = next(b for b in self.prompt_buckets if b >= S)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :S] = prompt
        # The fused first-token sample is the TARGET's job; the
        # drafter's is discarded — only the prompt K/V matters here.
        _tok, cache, _key = self._prefill(
            self.params, self._cache, padded, np.int32(S),
            np.int32(slot), jax.random.PRNGKey(0))
        self._cache = cache
        self._pos[slot] = S
        self._pending[slot] = -1

    # rtlint: owner=driver
    def propose(self, active: np.ndarray, last: np.ndarray) -> np.ndarray:
        import jax.numpy as jnp

        # Host-authoritative write cursor: rejected draft positions
        # were rolled back in observe(), so upload pos wholesale (tiny
        # [slots] int32 against the draft forward).
        self._cache["pos"] = jnp.asarray(self._pos)
        pend = active & (self._pending >= 0)
        if pend.any():
            ptok = np.where(pend, self._pending, 0).astype(np.int32)
            _t, cache, _d, _r = self._ingest(
                self.params, self._cache, ptok, self._rngs, pend)
            self._cache = cache
            self._pos[pend] += 1
            self._pending[pend] = -1
        toks, cache, _done, _rngs = self._step(
            self.params, self._cache, np.asarray(last, np.int32),
            self._rngs, active)
        self._cache = cache
        self._pos[active] += self.draft_k
        # The drafted tokens must reach the host: the verify dispatch
        # feeds them back as its device inputs.
        # rtlint: sync-ok=proposals proposals feed the verify dispatch
        return np.asarray(toks)

    def observe(self, slot: int, tokens: np.ndarray, accepted: int):
        k = self.draft_k
        if accepted < 0:
            # Chunk-round observe: cannot happen — this drafter has no
            # estimate(), so the engine always speculates its slots.
            raise RuntimeError(
                "ModelDrafter saw a chunk-round observe; its KV cache "
                "cannot ingest unproposed tokens")
        if accepted >= k:
            # Every proposal accepted: the draft chunk wrote K/V for
            # [last, d_1..d_{k-1}] — all committed — but d_k's K/V is
            # missing. Ingest it lazily before the next proposal.
            self._pending[slot] = int(tokens[k - 1])
        else:
            # Roll the cursor back past the rejected positions: valid
            # K/V runs through [last, d_1..d_a] at pos0..pos0+a.
            self._pos[slot] += accepted + 1 - k
            self._pending[slot] = -1

    def free(self, slot: int):
        self._pos[slot] = 0
        self._pending[slot] = -1


def tied_drafter_params(target_params, target_cfg, *, n_layer: int = 1,
                        seed: int = 0):
    """Build ``(params, cfg)`` for a :class:`ModelDrafter` that SHARES
    the target's embedding and position tables (the same arrays — zero
    extra HBM for the dominant parameter block) over a fresh
    ``n_layer``-deep trunk. Deterministic for a given seed, so every
    replica builds the identical drafter — required for bit-exact
    crash-resume replay with ``spec_decode="model"``."""
    import dataclasses

    import jax

    from ..models import gpt

    dcfg = dataclasses.replace(target_cfg, n_layer=int(n_layer))
    params = gpt.init_params(jax.random.PRNGKey(int(seed)), dcfg)
    params["embed"] = target_params["embed"]
    params["pos_embed"] = target_params["pos_embed"]
    return params, dcfg


def make_drafter(spec, params=None, cfg=None) -> Optional[Drafter]:
    """Resolve the engine/config-plane ``spec_decode`` knob:

    - ``None``/``False`` → no drafter (speculative decoding off);
    - ``True`` / ``"ngram"`` → a fresh :class:`NGramDrafter`;
    - ``"model"`` → a :class:`ModelDrafter` over
      :func:`tied_drafter_params` of the engine's own weights;
    - a :class:`Drafter` instance → used as-is.
    """
    if spec is None or spec is False:
        return None
    if isinstance(spec, Drafter):
        return spec
    if spec is True or spec == "ngram":
        return NGramDrafter()
    if spec == "model":
        if params is None or cfg is None:
            raise ValueError(
                "spec_decode='model' needs the engine's params/cfg to "
                "build the tied-embedding drafter")
        return ModelDrafter(*tied_drafter_params(params, cfg))
    raise ValueError(
        f"spec_decode must be False, True, 'ngram', 'model', or a "
        f"Drafter instance, got {spec!r}")
