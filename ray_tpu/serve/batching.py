"""Dynamic request batching with TPU-friendly bucketed padding.

Capability parity with ``@serve.batch`` (reference:
``python/ray/serve/batching.py:530`` — queue per wrapped function, flush on
``max_batch_size`` or ``batch_wait_timeout_s``), rebuilt on threads +
``concurrent.futures`` to match this runtime's threaded replica execution
model instead of the reference's asyncio replica event loop.

The TPU-specific part is **bucketed padding**: a jitted model recompiles for
every distinct batch size, so naively flushing whatever arrived (3 requests,
then 7, then 5 …) would trigger a new XLA compilation per size. With
``pad_to_bucket=True`` the flusher pads each batch up to the next bucket
(powers of two by default) by repeating the final item, runs the handler on
the static-shaped batch, and truncates the results — so the jitted callee
only ever sees ``len(buckets)`` distinct shapes (SURVEY.md §7: "dynamic
batching vs static XLA shapes via bucketed padding").
"""
from __future__ import annotations

import concurrent.futures
import functools
import queue
import threading
import time
from typing import Any, Callable, List, Optional, Sequence


def default_buckets(max_batch_size: int) -> List[int]:
    """Powers of two up to (and including) max_batch_size."""
    out, b = [], 1
    while b < max_batch_size:
        out.append(b)
        b *= 2
    out.append(max_batch_size)
    return sorted(set(out))


def pad_to_bucket(n: int, buckets: Sequence[int]) -> int:
    for b in buckets:
        if b >= n:
            return b
    return buckets[-1]


class _BatchQueue:
    """One pending-request queue + flusher thread per wrapped function."""

    def __init__(self, fn: Callable, max_batch_size: int,
                 batch_wait_timeout_s: float,
                 pad: bool, buckets: Optional[Sequence[int]]):
        self.fn = fn
        self.max_batch_size = max_batch_size
        self.timeout_s = batch_wait_timeout_s
        self.pad = pad
        self.buckets = sorted(buckets) if buckets else \
            default_buckets(max_batch_size)
        self.q: "queue.Queue" = queue.Queue()
        self.batch_sizes: List[int] = []  # observed (pre-pad) for tests/metrics
        self._thread = threading.Thread(
            target=self._flusher, daemon=True, name="rt-serve-batch")
        self._thread.start()

    def submit(self, item) -> "concurrent.futures.Future":
        fut: "concurrent.futures.Future" = concurrent.futures.Future()
        self.q.put((item, fut))
        return fut

    def _flusher(self):
        while True:
            item, fut = self.q.get()
            batch = [(item, fut)]
            deadline = time.monotonic() + self.timeout_s
            while len(batch) < self.max_batch_size:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    batch.append(self.q.get(timeout=remaining))
                except queue.Empty:
                    break
            self._run_batch(batch)

    def _run_batch(self, batch):
        items = [b[0] for b in batch]
        futs = [b[1] for b in batch]
        self.batch_sizes.append(len(items))
        n = len(items)
        if self.pad:
            target = pad_to_bucket(n, self.buckets)
            items = items + [items[-1]] * (target - n)
        try:
            results = self.fn(items)
            if results is None or len(results) < n:
                raise ValueError(
                    f"batch handler returned {0 if results is None else len(results)} "
                    f"results for {n} requests")
            for fut, r in zip(futs, results[:n]):
                fut.set_result(r)
        except Exception as e:  # noqa: BLE001 - fan the error out per caller
            for fut in futs:
                if not fut.done():
                    fut.set_exception(e)


# Runtime state (queues, locks) lives here — NOT in decorator closures —
# because deployment classes are cloudpickled at ``bind()`` time and
# thread locks / running flusher threads don't pickle.
_REGISTRY: dict = {}
_REG_LOCK = threading.Lock()


def _queue_for(self_obj, key, fn, cfg) -> _BatchQueue:
    max_bs, wait_s, pad, buckets = cfg
    if self_obj is not None:
        attr = f"__rt_batch_queue_{fn.__name__}"
        bq = self_obj.__dict__.get(attr)
        if bq is None:
            with _REG_LOCK:
                bq = self_obj.__dict__.get(attr)
                if bq is None:
                    bq = _BatchQueue(lambda items: fn(self_obj, items),
                                     max_bs, wait_s, pad, buckets)
                    object.__setattr__(self_obj, attr, bq)
        return bq
    with _REG_LOCK:
        bq = _REGISTRY.get(key)
        if bq is None:
            bq = _REGISTRY[key] = _BatchQueue(fn, max_bs, wait_s, pad,
                                              buckets)
    return bq


def batch(_fn: Optional[Callable] = None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01, pad_to_bucket: bool = False,
          buckets: Optional[Sequence[int]] = None):
    """Decorator: turn a ``List[T] -> List[R]`` handler into a ``T -> R``
    callable that transparently batches concurrent callers.

    Usage (on a replica method)::

        @serve.batch(max_batch_size=32, batch_wait_timeout_s=0.005,
                     pad_to_bucket=True)
        def predict_batch(self, inputs):      # inputs: List[np.ndarray]
            return self._jitted(np.stack(inputs))  # static bucket shapes

        def __call__(self, request):
            return self.predict_batch(request)
    """

    def decorate(fn):
        is_method = _looks_like_method(fn)
        cfg = (max_batch_size, batch_wait_timeout_s, pad_to_bucket,
               tuple(buckets) if buckets else None)
        key = (getattr(fn, "__module__", ""), getattr(fn, "__qualname__", ""))

        @functools.wraps(fn)
        def wrapper(*args):
            import ray_tpu.serve.batching as _mod

            if is_method:
                self_obj, item = args
            else:
                self_obj, (item,) = None, args
            return _mod._queue_for(self_obj, key, fn, cfg).submit(
                item).result()

        wrapper.__rt_is_batched__ = True
        return wrapper

    if _fn is not None and callable(_fn):
        return decorate(_fn)
    return decorate


def _looks_like_method(fn) -> bool:
    import inspect

    params = list(inspect.signature(fn).parameters)
    return bool(params) and params[0] == "self"
