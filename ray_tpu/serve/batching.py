"""Dynamic request batching with TPU-friendly bucketed padding.

Capability parity with ``@serve.batch`` (reference:
``python/ray/serve/batching.py:530`` — queue per wrapped function, flush on
``max_batch_size`` or ``batch_wait_timeout_s``), rebuilt on threads +
``concurrent.futures`` to match this runtime's threaded replica execution
model instead of the reference's asyncio replica event loop.

The TPU-specific part is **bucketed padding**: a jitted model recompiles for
every distinct batch size, so naively flushing whatever arrived (3 requests,
then 7, then 5 …) would trigger a new XLA compilation per size. With
``pad_to_bucket=True`` the flusher pads each batch up to the next bucket
(powers of two by default) by repeating the final item, runs the handler on
the static-shaped batch, and truncates the results — so the jitted callee
only ever sees ``len(buckets)`` distinct shapes (SURVEY.md §7: "dynamic
batching vs static XLA shapes via bucketed padding").

**Streaming batches** (``stream=True``): the handler is a GENERATOR taking
``List[T]`` and yielding per-batch slices — each yielded value is a list
with one element per batched caller — and each caller's wrapped call
returns an iterator of its own elements. This is how fused chunked decode
batches concurrent streams: one ``lax.scan`` dispatch serves the whole
batch, and every caller still streams its per-chunk token slices
incrementally (the serve replica forwards them straight into the chunked
HTTP path)::

    @serve.batch(max_batch_size=4, stream=True)
    def decode_batch(self, requests):        # one fused decode loop
        for chunk in self._decode_chunks(requests):
            yield chunk                       # List[per-caller slice]

    def __call__(self, request):
        for slice_ in self.decode_batch(request):
            yield slice_                      # caller's own stream
"""
from __future__ import annotations

import concurrent.futures
import functools
import queue
import threading
import time
from typing import Any, Callable, List, Optional, Sequence

from ..util import tracing
from .request import (RequestDeadlineExceeded, deadline_expired,
                      get_request_deadline, get_request_deployment,
                      get_request_handoff, get_request_resume_from)


def default_buckets(max_batch_size: int) -> List[int]:
    """Powers of two up to (and including) max_batch_size."""
    out, b = [], 1
    while b < max_batch_size:
        out.append(b)
        b *= 2
    out.append(max_batch_size)
    return sorted(set(out))


def pad_to_bucket(n: int, buckets: Sequence[int]) -> int:
    for b in buckets:
        if b >= n:
            return b
    return buckets[-1]


#: end-of-stream marker on the per-caller queues of a streaming batch
_STREAM_END = object()


class _StreamLane:
    """One caller's lane of a streaming batch: an unbounded queue plus a
    closed flag the consumer sets on abandonment, so the flusher stops
    feeding (and, once every lane closes, stops computing) chunks nobody
    will read."""

    __slots__ = ("q", "closed")

    def __init__(self):
        self.q = queue.SimpleQueue()
        self.closed = False


class _BatchQueue:
    """One pending-request queue + flusher thread per wrapped function."""

    def __init__(self, fn: Callable, max_batch_size: int,
                 batch_wait_timeout_s: float,
                 pad: bool, buckets: Optional[Sequence[int]],
                 stream: bool = False):
        self.fn = fn
        self.max_batch_size = max_batch_size
        self.timeout_s = batch_wait_timeout_s
        self.pad = pad
        self.stream = stream
        self.buckets = sorted(buckets) if buckets else \
            default_buckets(max_batch_size)
        self.q: "queue.Queue" = queue.Queue()
        self.batch_sizes: List[int] = []  # observed (pre-pad) for tests/metrics
        self._thread = threading.Thread(
            target=self._flusher, daemon=True, name="rt-serve-batch")
        self._thread.start()

    def submit(self, item,
               deadline_s: Optional[float] = None,
               trace_ctx: Optional[dict] = None,
               deployment: str = "") -> "concurrent.futures.Future":
        """Enqueue one caller's item. ``trace_ctx``/``deployment`` are
        the caller's request identity, captured at the wrapper (the
        flusher thread has no request context of its own): the flush
        records a ``batch.wait`` span per entry and labels the batch
        histograms by deployment."""
        fut: "concurrent.futures.Future" = concurrent.futures.Future()
        self.q.put((item, fut, deadline_s, trace_ctx, time.time(),
                    deployment))
        return fut

    def _flusher(self):
        while True:
            entry = self.q.get()
            batch = [entry]
            deadline = time.monotonic() + self.timeout_s
            while len(batch) < self.max_batch_size:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    batch.append(self.q.get(timeout=remaining))
                except queue.Empty:
                    break
            self._run_batch(batch)

    def _drop_expired(self, batch):
        """Flush-time expiry sweep: entries whose request deadline passed
        while queued are failed out of the batch instead of padding it —
        the device dispatch never spends cycles on answers whose callers
        already gave up. Returns the still-live entries."""
        from .._private.metrics import serve_metrics

        live = []
        for entry in batch:
            item, fut, dl = entry[0], entry[1], entry[2]
            if deadline_expired(dl):
                if not fut.done():
                    fut.set_exception(RequestDeadlineExceeded(
                        "request expired while queued for batching"))
                serve_metrics()["requests_expired"].inc(
                    labels={"where": "batcher",
                            "deployment": entry[5] or ""})
            else:
                live.append(entry)
        return live

    def _observe_flush(self, batch):
        """Batch-shape histograms + one ``batch.wait`` stage span per
        traced entry, recorded at flush time (the stage ends when the
        batch leaves the queue for the handler)."""
        from .._private.metrics import serve_metrics

        sm = serve_metrics()
        flush_t = time.time()
        n = len(batch)
        labels = {"deployment": batch[0][5] or ""}
        sm["batch_size"].observe(n, labels=labels)
        sm["batch_fill_ratio"].observe(n / max(self.max_batch_size, 1),
                                       labels=labels)
        for _item, _fut, _dl, tctx, enq_t, dep in batch:
            sm["batch_wait"].observe(max(flush_t - enq_t, 0.0),
                                     labels={"deployment": dep or ""})
            if tctx is not None:
                tracing.record_span("batch.wait", enq_t, flush_t,
                                    parent_ctx=tctx, batch_size=n,
                                    deployment=dep or "")

    def _run_batch(self, batch):
        batch = self._drop_expired(batch)
        if not batch:
            return  # every caller's deadline passed: skip the dispatch
        self._observe_flush(batch)
        items = [b[0] for b in batch]
        futs = [b[1] for b in batch]
        # First traced caller's context parents the handler invocation's
        # spans/submissions (the flusher thread has no context).
        lead_ctx = next((b[3] for b in batch if b[3] is not None), None)
        self.batch_sizes.append(len(items))
        n = len(items)
        if self.pad:
            target = pad_to_bucket(n, self.buckets)
            items = items + [items[-1]] * (target - n)
        if self.stream:
            # Own thread per streaming batch: the flusher goes straight
            # back to collecting the NEXT batch, so back-to-back batches
            # of streams overlap instead of serializing behind one
            # multi-second generation (head-of-line blocking). The
            # handler must therefore tolerate concurrent invocations —
            # the same contract this runtime's thread-concurrent
            # replicas already impose.
            threading.Thread(
                target=self._run_batch_stream,
                args=(items, futs, n, lead_ctx),
                daemon=True, name="rt-serve-batch-stream").start()
            return
        try:
            with tracing.activate_context(lead_ctx):
                results = self.fn(items)
            if results is None or len(results) < n:
                raise ValueError(
                    f"batch handler returned {0 if results is None else len(results)} "
                    f"results for {n} requests")
            for fut, r in zip(futs, results[:n]):
                fut.set_result(r)
        except Exception as e:  # noqa: BLE001 - fan the error out per caller
            for fut in futs:
                if not fut.done():
                    fut.set_exception(e)

    def _run_batch_stream(self, items, futs, n, lead_ctx=None):
        """Streaming flush (runs on its own thread, one per batch): the
        handler yields per-batch slices; element i of every slice is
        routed to caller i's lane, so all callers stream concurrently
        off ONE handler invocation, driven until exhaustion OR every
        lane is abandoned. Closed lanes stop receiving chunks, so a
        departed caller's queue can't grow."""
        lanes = [_StreamLane() for _ in range(n)]
        for fut, lane in zip(futs, lanes):
            fut.set_result(lane)
        try:
            # The lead caller's trace context stays active for the WHOLE
            # drive loop: every resume of the handler generator (each
            # fused dispatch) runs on this thread, and its spans/nested
            # submissions must join the request's trace.
            with tracing.activate_context(lead_ctx):
                gen = self.fn(items)
                try:
                    for slice_ in gen:
                        if all(lane.closed for lane in lanes):
                            break  # every consumer left; stop computing
                        if slice_ is None or len(slice_) < n:
                            raise ValueError(
                                f"streaming batch handler yielded "
                                f"{0 if slice_ is None else len(slice_)} "
                                f"results for {n} requests")
                        for lane, r in zip(lanes, list(slice_)[:n]):
                            if not lane.closed:
                                lane.q.put(("item", r))
                finally:
                    if hasattr(gen, "close"):
                        gen.close()  # run the handler's cleanup
            for lane in lanes:
                lane.q.put((_STREAM_END, None))
        except Exception as e:  # noqa: BLE001 - fan out per caller
            for lane in lanes:
                lane.q.put(("err", e))


# Runtime state (queues, locks) lives here — NOT in decorator closures —
# because deployment classes are cloudpickled at ``bind()`` time and
# thread locks / running flusher threads don't pickle.
_REGISTRY: dict = {}
_REG_LOCK = threading.Lock()


def _queue_for(self_obj, key, fn, cfg) -> _BatchQueue:
    max_bs, wait_s, pad, buckets, stream = cfg
    if self_obj is not None:
        attr = f"__rt_batch_queue_{fn.__name__}"
        bq = self_obj.__dict__.get(attr)
        if bq is None:
            with _REG_LOCK:
                bq = self_obj.__dict__.get(attr)
                if bq is None:
                    bq = _BatchQueue(lambda items: fn(self_obj, items),
                                     max_bs, wait_s, pad, buckets, stream)
                    object.__setattr__(self_obj, attr, bq)
        return bq
    with _REG_LOCK:
        bq = _REGISTRY.get(key)
        if bq is None:
            bq = _REGISTRY[key] = _BatchQueue(fn, max_bs, wait_s, pad,
                                              buckets, stream)
    return bq


def _drain_stream(lane: _StreamLane):
    """Caller-side iterator over one streaming-batch lane. Marks the
    lane closed on exit — normal exhaustion, error, or abandonment
    (GeneratorExit) — so the flusher stops feeding it."""
    try:
        while True:
            kind, val = lane.q.get()
            if kind is _STREAM_END:
                return
            if kind == "err":
                raise val
            yield val
    finally:
        lane.closed = True


class _EngineStream:
    """Iterator over one continuous-engine lane. A real class (not a
    generator) so it can carry ``__rt_engine_stream__`` — the replica's
    tracing reads that marker to skip recording its own per-item
    ``decode.chunk`` spans, deferring to the engine's per-dispatch spans
    (which carry real device timing instead of pull-wait timing) — and
    so ``close()`` marks the lane abandoned even before the first pull
    (closing an UNSTARTED generator skips its ``finally``, so
    ``_drain_stream`` alone would never flag a consumer that walked
    away while still queued for admission)."""

    __rt_engine_stream__ = True

    def __init__(self, lane: _StreamLane):
        self._lane = lane
        self._it = _drain_stream(lane)

    def __iter__(self):
        return self

    def __next__(self):
        return next(self._it)

    def poll(self):
        """Non-blocking pull of one event: ``("item", chunk)``,
        ``("end", None)``, or None when nothing is queued; a failed
        stream raises its error. Keeps the lane wire protocol private
        to this module — the offline batch pipeline (``data/llm.py``)
        drains many streams from ONE driver thread and cannot block on
        any single one. Do not interleave with iteration: one consumer,
        one access mode."""
        try:
            kind, val = self._lane.q.get_nowait()
        except queue.Empty:
            return None
        if kind is _STREAM_END:
            return ("end", None)
        if kind == "err":
            raise val
        return ("item", val)

    def close(self):
        self._lane.closed = True
        self._it.close()


def batch(_fn: Optional[Callable] = None, *, max_batch_size: int = 8,
          batch_wait_timeout_s: float = 0.01, pad_to_bucket: bool = False,
          buckets: Optional[Sequence[int]] = None, stream: bool = False,
          continuous: bool = False, page_size: Optional[int] = None,
          prefix_cache: Optional[bool] = None, spec_decode=None,
          draft_k: Optional[int] = None,
          spec_threshold: Optional[float] = None,
          attn_kernel: Optional[str] = None,
          kv_dtype: Optional[str] = None):
    """Decorator: turn a ``List[T] -> List[R]`` handler into a ``T -> R``
    callable that transparently batches concurrent callers.

    Usage (on a replica method)::

        @serve.batch(max_batch_size=32, batch_wait_timeout_s=0.005,
                     pad_to_bucket=True)
        def predict_batch(self, inputs):      # inputs: List[np.ndarray]
            return self._jitted(np.stack(inputs))  # static bucket shapes

        def __call__(self, request):
            return self.predict_batch(request)

    With ``stream=True`` the handler is a generator yielding per-batch
    slices (one element per batched caller) and each call returns an
    iterator of that caller's elements — see the module docstring for
    the fused-decode shape.

    With ``continuous=True`` the batching moves OFF the flusher entirely
    and into a :class:`~.engine.DecodeEngine` slot pool: the handler is
    called once per request and returns ``(engine, submit_kwargs)`` —
    the wrapper forwards the request's deadline and trace context into
    ``engine.submit`` and hands back the request's own chunk-slice
    stream. No batch queue forms; admission happens at the engine's
    chunk boundaries, so a request arriving mid-generation joins the
    running pool as soon as a slot frees instead of waiting for the
    next gang batch::

        @serve.batch(continuous=True)
        def decode(self, request):
            return self.engine, {"prompt": request["prompt"],
                                 "max_new": request["max_new"]}

        def __call__(self, request):
            return self.decode(request)       # iterator of [j] slices

    ``page_size=`` / ``prefix_cache=`` / ``attn_kernel=`` /
    ``kv_dtype=`` (continuous only) are the paged KV-cache knobs, and
    ``spec_decode=`` / ``draft_k=`` the speculative
    decoding knobs, applied to the handler's engine via
    :meth:`~.engine.DecodeEngine.apply_config` on first use: a
    flat-constructed engine is repaged / given a drafter before traffic
    (a matching engine just validates), so deployments can opt in
    declaratively without touching their ``__init__``.
    """
    if continuous and (stream or pad_to_bucket or buckets is not None):
        raise ValueError(
            "continuous=True replaces the flusher with an engine slot "
            "pool; stream/pad_to_bucket/buckets do not apply")
    if not continuous and (page_size is not None
                           or prefix_cache is not None
                           or spec_decode is not None
                           or draft_k is not None
                           or spec_threshold is not None
                           or attn_kernel is not None
                           or kv_dtype is not None):
        raise ValueError(
            "page_size/prefix_cache/spec_decode/draft_k/spec_threshold/"
            "attn_kernel/kv_dtype are decode-engine knobs; they "
            "require continuous=True")
    if buckets is not None:
        bs = sorted(int(b) for b in buckets)
        if not bs or bs[0] < 1:
            raise ValueError(f"buckets must be positive ints, got "
                             f"{list(buckets)}")
        if bs[-1] < max_batch_size:
            # Without this, pad_to_bucket silently returns buckets[-1]
            # for a full batch and the "pad" becomes a negative-count
            # no-op — the jitted callee then sees unpadded sizes.
            raise ValueError(
                f"buckets {list(buckets)} do not cover "
                f"max_batch_size={max_batch_size}; add a bucket >= "
                f"{max_batch_size} (a full batch cannot be padded DOWN "
                f"to {bs[-1]})")

    def decorate(fn):
        is_method = _looks_like_method(fn)
        if continuous:
            return _decorate_continuous(fn, page_size, prefix_cache,
                                        spec_decode, draft_k,
                                        spec_threshold, attn_kernel,
                                        kv_dtype)
        cfg = (max_batch_size, batch_wait_timeout_s, pad_to_bucket,
               tuple(buckets) if buckets else None, stream)
        key = (getattr(fn, "__module__", ""), getattr(fn, "__qualname__", ""))

        @functools.wraps(fn)
        def wrapper(*args):
            import ray_tpu.serve.batching as _mod

            if is_method:
                self_obj, item = args
            else:
                self_obj, (item,) = None, args
            # Inherit the caller's request deadline (set by the replica
            # around user code) so queued entries can be dropped at
            # flush time once nobody is waiting for them — plus its
            # trace context and deployment name, captured HERE because
            # the flusher thread that records the batch.wait stage has
            # no request context of its own.
            out = _mod._queue_for(self_obj, key, fn, cfg).submit(
                item, deadline_s=get_request_deadline(),
                trace_ctx=tracing.current_context(),
                deployment=get_request_deployment() or "").result()
            return _drain_stream(out) if stream else out

        wrapper.__rt_is_batched__ = True
        return wrapper

    if _fn is not None and callable(_fn):
        return decorate(_fn)
    return decorate


def _decorate_continuous(fn, page_size: Optional[int] = None,
                         prefix_cache: Optional[bool] = None,
                         spec_decode=None, draft_k: Optional[int] = None,
                         spec_threshold: Optional[float] = None,
                         attn_kernel: Optional[str] = None,
                         kv_dtype: Optional[str] = None):
    """Engine-backed admission path: per request, the handler maps the
    item to ``(engine, submit_kwargs)`` and the wrapper feeds the
    engine's admission queue, inheriting the request's deadline (so the
    engine can drop it unstarted or free its slot mid-generation) and
    trace context (so ``engine.admission`` / per-dispatch
    ``decode.chunk`` spans join the request's trace). Decorator-level
    ``page_size``/``prefix_cache``/``spec_decode``/``draft_k`` are
    pushed into the engine via ``apply_config`` the first time each
    engine instance passes through (a cheap identity check
    afterwards)."""

    import weakref

    configured: "weakref.WeakSet" = weakref.WeakSet()

    @functools.wraps(fn)
    def wrapper(*args):
        out = fn(*args)
        try:
            engine, kw = out
            kw = dict(kw)
        except (TypeError, ValueError):
            raise TypeError(
                f"@serve.batch(continuous=True) handler "
                f"{fn.__qualname__} must return (engine, submit_kwargs),"
                f" got {type(out).__name__}") from None
        if (page_size is not None or prefix_cache is not None
                or spec_decode is not None or draft_k is not None
                or spec_threshold is not None
                or attn_kernel is not None or kv_dtype is not None) \
                and engine not in configured:
            engine.apply_config(page_size=page_size,
                                prefix_cache=prefix_cache,
                                spec_decode=spec_decode,
                                draft_k=draft_k,
                                spec_threshold=spec_threshold,
                                attn_kernel=attn_kernel,
                                kv_dtype=kv_dtype)
            configured.add(engine)
        # Disaggregated dispatch (ISSUE 14), stamped by the router's
        # two-hop routing: the prefill hop answers with a leased
        # handoff descriptor (unary), the decode hop imports one
        # instead of prefilling locally. The handler's submit kwargs
        # stay authoritative for WHAT to generate; the hop marker only
        # picks the engine entry point.
        hop = get_request_handoff()
        if hop == "export":
            return engine.handoff(
                kw["prompt"], kw["max_new"],
                seed=int(kw.get("seed", 0)),
                deadline_s=get_request_deadline(),
                trace_ctx=tracing.current_context())
        if isinstance(hop, dict):
            lane = engine.admit_prefilled(
                hop, deadline_s=get_request_deadline(),
                trace_ctx=tracing.current_context(),
                resume_from=get_request_resume_from())
            return _EngineStream(lane)
        # Mid-stream failover replay token: a resumed request (its first
        # replica died after delivering n tokens) replays the SAME
        # deterministic generation here with the delivered prefix
        # suppressed — stamped by the router, carried by the replica's
        # request context.
        kw.setdefault("resume_from", get_request_resume_from())
        lane = engine.submit(deadline_s=get_request_deadline(),
                             trace_ctx=tracing.current_context(), **kw)
        return _EngineStream(lane)

    wrapper.__rt_is_batched__ = True
    wrapper.__rt_continuous__ = True
    return wrapper


def _looks_like_method(fn) -> bool:
    import inspect

    params = list(inspect.signature(fn).parameters)
    return bool(params) and params[0] == "self"
