"""HTTP proxy actor: routes requests to app ingress deployments.

Capability parity with the reference proxy
(reference: ``python/ray/serve/_private/proxy.py:752`` — route-prefix
matching, per-request handle dispatch, draining), rebuilt as a minimal
asyncio HTTP/1.1 server on a dedicated thread instead of uvicorn/ASGI
(no server framework in this image; requests hop processes anyway).

Blocking handle calls are pushed to a thread pool so the accept loop never
stalls on a slow replica.
"""
from __future__ import annotations

import asyncio
import concurrent.futures
import json
import threading
import time
import traceback
from typing import Dict, Optional

from ..exceptions import GetTimeoutError, TaskError
from .config import SERVE_CONTROLLER_NAME
from .handle import DeploymentHandle
from .request import (BackPressureError, Request, RequestDeadlineExceeded,
                      Response, encode_body)

_MAX_BODY = 256 * 1024 * 1024


def _is_backpressure(e: Exception) -> bool:
    """Shed signal, raised locally by this proxy's router or re-raised
    TaskError-wrapped from a composed deployment's nested handle call."""
    return isinstance(e, BackPressureError) or (
        isinstance(e, TaskError)
        and getattr(e, "cause_type", "") == "BackPressureError")


def _is_deadline(e: Exception) -> bool:
    return isinstance(e, (RequestDeadlineExceeded, GetTimeoutError,
                          TimeoutError)) or (
        isinstance(e, TaskError)
        and getattr(e, "cause_type", "") == "RequestDeadlineExceeded")


class ProxyActor:
    ROUTES_TTL_S = 1.0

    def __init__(self):
        self._routes: Dict[str, dict] = {}
        self._routes_at = 0.0
        self._miss_refresh_at = 0.0
        self._routes_lock = threading.Lock()
        self._pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=64, thread_name_prefix="rt-serve-proxy")
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._port: Optional[int] = None
        self._started = threading.Event()
        self._request_timeout_s = 60.0
        # Lifecycle accounting (pulled by the controller for status()).
        self._stats_lock = threading.Lock()
        self._shed_total = 0
        self._expired_total = 0

    def start(self, host: str, port: int, request_timeout_s: float = 60.0
              ) -> dict:
        """Bind and serve on a dedicated event-loop thread; returns the
        actual bound port (``port=0`` picks a free one)."""
        self._request_timeout_s = request_timeout_s
        t = threading.Thread(target=self._serve_thread, args=(host, port),
                             daemon=True, name="rt-serve-http")
        t.start()
        if not self._started.wait(timeout=10):
            raise RuntimeError("proxy failed to bind")
        return {"host": host, "port": self._port}

    def _serve_thread(self, host: str, port: int):
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        self._loop = loop

        async def _main():
            server = await asyncio.start_server(self._handle_conn, host, port)
            self._port = server.sockets[0].getsockname()[1]
            self._started.set()
            async with server:
                await server.serve_forever()

        try:
            loop.run_until_complete(_main())
        except Exception:  # noqa: BLE001
            traceback.print_exc()

    def ping(self) -> bool:
        return True

    def set_tracing(self, enabled: bool) -> bool:
        """Mirror the driver's tracing state into this proxy process so
        per-request server spans record exactly when the driver traces
        (serve.start propagates it on every call, both directions)."""
        from ..util import tracing

        tracing.enable() if enabled else tracing.disable()
        return enabled

    def get_port(self) -> Optional[int]:
        return self._port

    # ------------------------------------------------------------- routing
    def _get_routes(self, force: bool = False) -> Dict[str, dict]:
        now = time.monotonic()
        with self._routes_lock:
            if not force and now - self._routes_at < self.ROUTES_TTL_S:
                return self._routes
        from .. import api as rt

        try:
            ctrl = rt.get_actor(SERVE_CONTROLLER_NAME, timeout=5)
            routes = rt.get(ctrl.get_routes.remote(), timeout=10)
            with self._routes_lock:
                self._routes = routes
                self._routes_at = now
        except Exception:  # noqa: BLE001 - keep stale routes
            pass
        return self._routes

    def _refresh_on_miss(self) -> bool:
        """A just-deployed app can miss the (≤TTL-old) cached table —
        ``serve.run`` returns when the CONTROLLER is ready, and proxies
        learn asynchronously. One forced refresh before answering 404
        makes fresh routes visible immediately; rate-limited so a 404
        flood cannot hammer the controller. Returns whether a refresh
        actually ran (False = rate-limited, a re-match is pointless).
        Blocking — callers on the accept loop must run it in the pool."""
        now = time.monotonic()
        with self._routes_lock:
            if now - self._miss_refresh_at < 0.05:
                return False
            self._miss_refresh_at = now
        self._get_routes(force=True)
        return True

    def _match(self, path: str) -> Optional[dict]:
        routes = self._get_routes()
        best, best_len = None, -1
        for prefix, target in routes.items():
            p = prefix.rstrip("/") or "/"
            if (path == p or path.startswith(p if p == "/" else p + "/")
                    or (p != "/" and path == p)):
                if len(p) > best_len:
                    best, best_len = {**target, "prefix": p}, len(p)
        return best

    # --------------------------------------------------------- HTTP server
    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter):
        try:
            while True:
                req = await self._read_request(reader)
                if req is None:
                    break
                status, ctype, body, *rest = await self._dispatch(req)
                extra = rest[0] if rest else {}
                hdr_extra = "".join(f"{k}: {v}\r\n"
                                    for k, v in (extra or {}).items()
                                    ).encode()
                keep = req.headers.get("connection", "").lower() != "close"
                if callable(body):
                    # Streaming response: chunked transfer encoding, one
                    # chunk per item the replica generator yields
                    # (reference: proxy.py streaming + http_util.py).
                    writer.write(
                        b"HTTP/1.1 %d %s\r\n" % (status, _reason(status)) +
                        b"Content-Type: %s\r\n" % ctype.encode() +
                        hdr_extra +
                        b"Transfer-Encoding: chunked\r\n" +
                        (b"Connection: keep-alive\r\n" if keep
                         else b"Connection: close\r\n") + b"\r\n")
                    loop = asyncio.get_running_loop()
                    while True:
                        chunk = await loop.run_in_executor(self._pool, body)
                        if chunk is None:
                            break
                        if not chunk:
                            # A zero-length chunk IS the chunked-encoding
                            # terminator — writing it would end the
                            # response mid-stream.
                            continue
                        writer.write(b"%x\r\n" % len(chunk) + chunk + b"\r\n")
                        await writer.drain()
                    writer.write(b"0\r\n\r\n")
                    await writer.drain()
                    if not keep:
                        break
                    continue
                writer.write(
                    b"HTTP/1.1 %d %s\r\n" % (status, _reason(status)) +
                    b"Content-Type: %s\r\n" % ctype.encode() +
                    hdr_extra +
                    b"Content-Length: %d\r\n" % len(body) +
                    (b"Connection: keep-alive\r\n" if keep
                     else b"Connection: close\r\n") +
                    b"\r\n" + body)
                await writer.drain()
                if not keep:
                    break
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        except Exception:  # noqa: BLE001
            traceback.print_exc()
        finally:
            try:
                writer.close()
            except Exception:  # noqa: BLE001
                pass

    @staticmethod
    async def _read_request(reader: asyncio.StreamReader) -> Optional[Request]:
        try:
            line = await reader.readline()
        except (ConnectionError, asyncio.LimitOverrunError):
            return None
        if not line or line in (b"\r\n", b"\n"):
            return None
        try:
            method, target, _version = line.decode().split(None, 2)
        except ValueError:
            return None
        headers: Dict[str, str] = {}
        while True:
            h = await reader.readline()
            if not h or h in (b"\r\n", b"\n"):
                break
            k, _, v = h.decode().partition(":")
            headers[k.strip().lower()] = v.strip()
        length = int(headers.get("content-length", "0") or "0")
        body = b""
        if 0 < length <= _MAX_BODY:
            body = await reader.readexactly(length)
        return Request.from_target(method, target, headers, body)

    async def _dispatch(self, req: Request):
        if req.path == "/-/routes":
            return 200, "application/json", json.dumps(
                {p: f"{t['app']}:{t['ingress']}"
                 for p, t in self._get_routes().items()}).encode()
        if req.path == "/-/healthz":
            return 200, "text/plain", b"ok"
        loop = asyncio.get_running_loop()
        target = self._match(req.path)
        if target is None:
            # Off-loop: the forced refresh blocks on a controller RPC
            # and must not stall the accept loop (or /-/healthz).
            if await loop.run_in_executor(self._pool,
                                          self._refresh_on_miss):
                target = self._match(req.path)
        if target is None:
            return 404, "text/plain", b"no application at this route"
        if target.get("stream"):
            try:
                gen, span = await asyncio.wait_for(
                    loop.run_in_executor(
                        self._pool, self._call_app_stream, target, req),
                    timeout=self._request_timeout_s)
            except asyncio.TimeoutError:
                return self._timeout_response()
            except Exception as e:  # noqa: BLE001
                return self._error_response(e)

            def next_chunk():
                """Blocking puller run on the proxy pool; None ends the
                stream (sentinel keeps the executor round-trip single)."""
                try:
                    item = next(gen)
                except StopIteration:
                    if span is not None:
                        span.finish()
                    return None
                except BaseException:
                    if span is not None:
                        span.finish("error")
                    raise
                if isinstance(item, bytes):
                    return item
                if isinstance(item, str):
                    return item.encode()
                return json.dumps(item).encode() + b"\n"

            return 200, "application/octet-stream", next_chunk
        try:
            result = await asyncio.wait_for(
                loop.run_in_executor(
                    self._pool, self._call_app, target, req),
                timeout=self._request_timeout_s)
        except asyncio.TimeoutError:
            return self._timeout_response()
        except Exception as e:  # noqa: BLE001
            return self._error_response(e)
        if isinstance(result, Response):
            status, ctype, body = result.encode()
            return status, ctype, body
        ctype, body = encode_body(result)
        return 200, ctype, body

    # --------------------------------------------------- lifecycle mapping
    def _timeout_response(self):
        with self._stats_lock:
            self._expired_total += 1
        return 504, "text/plain", b"request timed out"

    def _error_response(self, e: Exception):
        """Map request-lifecycle errors onto HTTP semantics: shed →
        ``503`` + ``Retry-After`` (the client contract: back off at
        least that many seconds before resubmitting — the deployment is
        saturated, not broken); expired → ``504``; anything else →
        ``500``."""
        if _is_backpressure(e):
            retry_after = max(1, int(round(
                getattr(e, "retry_after_s", 1.0) or 1.0)))
            with self._stats_lock:
                self._shed_total += 1
            from .._private.metrics import serve_metrics

            serve_metrics()["requests_shed"].inc(labels={"where": "proxy"})
            return (503, "text/plain",
                    b"deployment overloaded; request shed",
                    {"Retry-After": str(retry_after)})
        if _is_deadline(e):
            return self._timeout_response()
        return 500, "text/plain", f"{type(e).__name__}: {e}".encode()

    def get_lifecycle_stats(self) -> dict:
        """Shed/expired totals since proxy start (controller status)."""
        with self._stats_lock:
            return {"shed_total": self._shed_total,
                    "expired_total": self._expired_total}

    def _call_app(self, target: dict, req: Request):
        # Server span per request (recorded only when tracing is on in
        # this proxy process, e.g. RT_TRACING_ENABLED=1 cluster-wide):
        # the replica call inside becomes its child, so one trace reads
        # proxy → handle submit → replica execute (reference: serve
        # requests traced through the core task spans).
        from ..util import tracing

        with tracing.span(f"http {req.method} {req.path}", kind="server",
                          route=target.get("prefix", "")):
            # The handle stamps the absolute deadline at submission from
            # timeout_s; result() inherits it, so the replica, batcher,
            # and any retry all share ONE request-scoped window.
            handle = DeploymentHandle(target["app"], target["ingress"],
                                      timeout_s=self._request_timeout_s)
            # proxy.admission stage: ingress overhead through submission
            # (router admission inside nests as router.queue_wait). The
            # wait for the RESULT is deliberately outside — that time
            # belongs to the replica-side stages.
            with tracing.span("proxy.admission", kind="stage",
                              deployment=target["ingress"]):
                resp = handle.remote(req)
            return resp.result()

    def _call_app_stream(self, target: dict, req: Request):
        """Returns (generator, ManualSpan-or-None). The server span must
        cover the whole STREAM, not the submission — the caller finishes
        it when the last chunk is pulled (or the stream errors), which
        happens on a different pool thread."""
        from ..util import tracing

        ms = tracing.manual_span(
            f"http {req.method} {req.path} [stream]", "server",
            route=target.get("prefix", ""))
        handle = DeploymentHandle(target["app"], target["ingress"],
                                  stream=True,
                                  timeout_s=self._request_timeout_s)
        if ms is None:
            return handle.remote(req), None
        with ms.activate():
            with tracing.span("proxy.admission", kind="stage",
                              deployment=target["ingress"]):
                return handle.remote(req), ms

    # ---------------------------------------------------------- gRPC ingress
    def start_grpc(self, host: str, port: int) -> dict:
        """gRPC ingress next to HTTP (reference: ``proxy.py:534``
        ``gRPCProxy`` — one proxy actor serves both protocols).

        Generic-handler server: ANY method path is accepted and routed
        by the ``application`` request metadata (reference behavior) or,
        absent that, the first path segment (``/<app>/Method``). Request
        and response messages are raw bytes — schema belongs to the
        application (a deployment returning bytes passes through
        verbatim; other values use the same ``encode_body`` rules as
        HTTP). Streaming deployments answer server-streaming calls with
        one message per yielded item.
        """
        import grpc

        proxy = self

        class _Generic(grpc.GenericRpcHandler):
            def service(self, call_details):
                md = dict(call_details.invocation_metadata or ())
                method = call_details.method or ""
                target = proxy._grpc_target(md.get("application"), method)
                if target is None:
                    return None  # grpc answers UNIMPLEMENTED
                if target.get("stream"):
                    return grpc.unary_stream_rpc_method_handler(
                        proxy._grpc_stream_call(target, method))
                return grpc.unary_unary_rpc_method_handler(
                    proxy._grpc_unary_call(target, method))

        server = grpc.server(self._pool, handlers=(_Generic(),))
        bound = server.add_insecure_port(f"{host}:{port}")
        if not bound:
            raise RuntimeError(f"grpc ingress failed to bind {host}:{port}")
        server.start()
        self._grpc_server = server
        return {"host": host, "grpc_port": bound}

    def _grpc_target(self, app_name: Optional[str],
                     method: str) -> Optional[dict]:
        for attempt in range(2):
            routes = self._get_routes()
            if app_name:
                for prefix, t in routes.items():
                    if t["app"] == app_name:
                        return {**t, "prefix": prefix}
            else:
                seg = method.strip("/").split("/", 1)[0].split(".")[0]
                for prefix, t in routes.items():
                    if t["app"] == seg or prefix.strip("/") == seg:
                        return {**t, "prefix": prefix}
            # gRPC handlers run on worker threads, so the blocking
            # refresh is safe here; skip the re-scan if rate-limited.
            if attempt == 0 and not self._refresh_on_miss():
                break
        return None

    def _grpc_request(self, method: str, data: bytes, context) -> Request:
        headers = {k: v for k, v in (context.invocation_metadata() or ())
                   if isinstance(v, str)}
        headers["grpc-method"] = method
        return Request(method="GRPC", path=method, headers=headers,
                       body=bytes(data))

    def _grpc_status(self, e: Exception):
        """gRPC twin of ``_error_response``: shed → RESOURCE_EXHAUSTED
        (with the same retry-after contract in the detail string),
        expired → DEADLINE_EXCEEDED."""
        import grpc

        if _is_backpressure(e):
            with self._stats_lock:
                self._shed_total += 1
            from .._private.metrics import serve_metrics

            serve_metrics()["requests_shed"].inc(labels={"where": "proxy"})
            retry_after = max(1, int(round(
                getattr(e, "retry_after_s", 1.0) or 1.0)))
            return (grpc.StatusCode.RESOURCE_EXHAUSTED,
                    f"deployment overloaded; retry after {retry_after}s")
        if _is_deadline(e):
            with self._stats_lock:
                self._expired_total += 1
            return grpc.StatusCode.DEADLINE_EXCEEDED, "request timed out"
        return grpc.StatusCode.INTERNAL, f"{type(e).__name__}: {e}"

    def _grpc_unary_call(self, target: dict, method: str):
        def call(data, context):
            try:
                result = self._call_app(
                    target, self._grpc_request(method, data, context))
            except Exception as e:  # noqa: BLE001
                code, detail = self._grpc_status(e)
                context.abort(code, detail)
                return b""
            if isinstance(result, Response):
                _, _, body = result.encode()
                return body
            return encode_body(result)[1]

        return call

    def _grpc_stream_call(self, target: dict, method: str):
        def call(data, context):
            span = None
            try:
                gen, span = self._call_app_stream(
                    target, self._grpc_request(method, data, context))
                for item in gen:
                    yield encode_body(item)[1]
                if span is not None:
                    span.finish()
            except Exception as e:  # noqa: BLE001
                if span is not None:
                    span.finish("error")
                code, detail = self._grpc_status(e)
                context.abort(code, detail)

        return call


def _reason(status: int) -> bytes:
    return {200: b"OK", 404: b"Not Found", 500: b"Internal Server Error",
            503: b"Service Unavailable",
            504: b"Gateway Timeout"}.get(status, b"Unknown")
