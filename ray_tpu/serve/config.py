"""Serve configuration objects.

Capability parity with the reference's ``ray.serve.config``
(reference: ``python/ray/serve/config.py`` — ``AutoscalingConfig``,
``HTTPOptions``; ``python/ray/serve/_private/config.py`` —
``DeploymentConfig``), redesigned as plain dataclasses for this runtime.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class AutoscalingConfig:
    """Target-driven replica autoscaling.

    The controller computes a desired replica count from replica-reported
    signals and applies it after the decision has been stable for
    ``upscale_delay_s`` / ``downscale_delay_s`` (reference:
    ``serve/_private/autoscaling_state.py:262`` and
    ``serve/autoscaling_policy.py``).

    Signal selection (ISSUE 17, SLO-driven loop in
    ``serve/autoscaler.py``): ``target_occupancy`` scales on the
    engine's active-slot fraction (decode groups), ``target_queue_depth``
    on per-replica admission backlog (prefill groups / bursty arrivals),
    and with neither set the loop falls back to the classic
    ``target_ongoing_requests`` ratio. ``tpot_slo_s`` layers a latency
    SLO on top: a p95 TPOT above it forces upscale pressure regardless
    of occupancy. Decisions are bounded — ``hysteresis`` dead-band,
    ``upscale_step``/``downscale_step`` caps, per-direction cooldowns —
    and degrade to a conservative hold when signals are missing or older
    than ``signal_staleness_s``. ``scale_to_zero_idle_s`` (with
    ``min_replicas=0``) opts a group into scale-to-zero after that much
    idle; a scale-from-zero stamps a ``cold_start_grace_s`` window
    during which further upscale is suppressed (the first burst after
    idle queues behind a compiling replica and must not panic-scale).
    Disaggregated deployments autoscale per role group via the
    ``roles:`` override map (``{"decode": {"max_replicas": 4}}``);
    without it a ``roles:`` engine block keeps its declarative targets.
    """

    min_replicas: int = 1
    max_replicas: int = 1
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 2.0
    downscale_delay_s: float = 10.0
    metrics_interval_s: float = 0.25
    initial_replicas: Optional[int] = None
    # ---- SLO-driven signals (ISSUE 17) --------------------------------
    target_occupancy: Optional[float] = None
    target_queue_depth: Optional[float] = None
    tpot_slo_s: Optional[float] = None
    scale_to_zero_idle_s: Optional[float] = None
    hysteresis: float = 0.1
    upscale_step: int = 2
    downscale_step: int = 1
    upscale_cooldown_s: float = 0.0
    downscale_cooldown_s: float = 0.0
    signal_staleness_s: float = 10.0
    cold_start_grace_s: float = 10.0
    ema_tau_s: float = 2.0
    #: Per-role-group overrides for disaggregated deployments:
    #: role name ("prefill" | "decode" | "both") -> field overrides.
    #: Presence of this map is ALSO the opt-in that lets the autoscaler
    #: move a ``roles:`` block's targets at all.
    roles: Optional[Dict[str, Dict[str, Any]]] = None

    _ROLE_NAMES = ("prefill", "decode", "both")

    def __post_init__(self):
        if self.min_replicas < 0 or self.max_replicas < self.min_replicas:
            raise ValueError("need 0 <= min_replicas <= max_replicas")
        if self.hysteresis < 0:
            raise ValueError("hysteresis must be >= 0")
        if self.upscale_step < 1 or self.downscale_step < 1:
            raise ValueError("scale steps must be >= 1")
        if self.signal_staleness_s <= 0:
            raise ValueError("signal_staleness_s must be > 0")
        if self.target_occupancy is not None and \
                not 0 < self.target_occupancy <= 1:
            raise ValueError("target_occupancy must be in (0, 1]")
        if self.roles:
            fields = set(self.__dataclass_fields__) - {"roles"}
            for role, over in self.roles.items():
                if role not in self._ROLE_NAMES:
                    raise ValueError(
                        f"unknown role {role!r} in autoscaling roles "
                        f"block; known: {list(self._ROLE_NAMES)}")
                bad = set(over or {}) - fields
                if bad:
                    raise ValueError(
                        f"unknown autoscaling keys {sorted(bad)} in "
                        f"roles[{role!r}] override")

    def for_role(self, role: Optional[str]) -> "AutoscalingConfig":
        """This config with the ``roles[role]`` overrides applied (the
        per-group view the autoscaler decides with)."""
        if not role or not self.roles or role not in self.roles:
            return self
        from dataclasses import replace

        over = dict(self.roles[role] or {})
        return replace(self, roles=None, **over)


@dataclass
class DeploymentConfig:
    """Per-deployment behavior knobs (reference:
    ``serve/_private/config.py`` ``DeploymentConfig``)."""

    num_replicas: int = 1
    max_ongoing_requests: int = 16
    #: Pending-queue bound per router: once every replica is saturated
    #: AND this many callers are already waiting for admission, further
    #: submissions are shed with ``BackPressureError`` (HTTP 503 +
    #: ``Retry-After`` at the proxy) instead of queuing without bound.
    #: Bounded queues are what keep accepted-request tail latency flat
    #: under overload — see the request-lifecycle notes in ``api.py``.
    max_queued_requests: int = 64
    autoscaling_config: Optional[AutoscalingConfig] = None
    user_config: Any = None
    #: Paged-KV engine knobs (``page_size``, ``prefix_cache``,
    #: ``n_pages``), applied by the replica to every
    #: :class:`~ray_tpu.serve.engine.DecodeEngine` the user callable
    #: constructs — the declarative twin of
    #: ``@serve.batch(continuous=True, page_size=...)``.
    engine_config: Optional[Dict[str, Any]] = None
    health_check_period_s: float = 2.0
    graceful_shutdown_timeout_s: float = 5.0
    ray_actor_options: Dict[str, Any] = field(default_factory=dict)

    def initial_target(self) -> int:
        ac = self.autoscaling_config
        if ac is None:
            return self.num_replicas
        if ac.initial_replicas is not None:
            return max(ac.min_replicas,
                       min(ac.max_replicas, ac.initial_replicas))
        return ac.min_replicas


@dataclass
class HTTPOptions:
    """Proxy bind options (reference: ``serve/config.py`` ``HTTPOptions``)."""

    host: str = "127.0.0.1"
    port: int = 8000
    request_timeout_s: float = 60.0


@dataclass
class gRPCOptions:  # noqa: N801 - reference-parity name
    """gRPC ingress bind options (reference: ``serve/config.py``
    ``gRPCOptions`` — served by the same proxy actor as HTTP)."""

    host: str = "127.0.0.1"
    port: int = 0  # 0 = pick a free port


SERVE_CONTROLLER_NAME = "SERVE_CONTROLLER"
DEFAULT_APP_NAME = "default"
