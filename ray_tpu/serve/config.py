"""Serve configuration objects.

Capability parity with the reference's ``ray.serve.config``
(reference: ``python/ray/serve/config.py`` — ``AutoscalingConfig``,
``HTTPOptions``; ``python/ray/serve/_private/config.py`` —
``DeploymentConfig``), redesigned as plain dataclasses for this runtime.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class AutoscalingConfig:
    """Target-driven replica autoscaling.

    The controller computes ``desired = ceil(total_ongoing /
    target_ongoing_requests)`` from replica-reported metrics and applies it
    after the decision has been stable for ``upscale_delay_s`` /
    ``downscale_delay_s`` (reference:
    ``serve/_private/autoscaling_state.py:262`` and
    ``serve/autoscaling_policy.py``).
    """

    min_replicas: int = 1
    max_replicas: int = 1
    target_ongoing_requests: float = 2.0
    upscale_delay_s: float = 2.0
    downscale_delay_s: float = 10.0
    metrics_interval_s: float = 0.25
    initial_replicas: Optional[int] = None

    def __post_init__(self):
        if self.min_replicas < 0 or self.max_replicas < self.min_replicas:
            raise ValueError("need 0 <= min_replicas <= max_replicas")


@dataclass
class DeploymentConfig:
    """Per-deployment behavior knobs (reference:
    ``serve/_private/config.py`` ``DeploymentConfig``)."""

    num_replicas: int = 1
    max_ongoing_requests: int = 16
    #: Pending-queue bound per router: once every replica is saturated
    #: AND this many callers are already waiting for admission, further
    #: submissions are shed with ``BackPressureError`` (HTTP 503 +
    #: ``Retry-After`` at the proxy) instead of queuing without bound.
    #: Bounded queues are what keep accepted-request tail latency flat
    #: under overload — see the request-lifecycle notes in ``api.py``.
    max_queued_requests: int = 64
    autoscaling_config: Optional[AutoscalingConfig] = None
    user_config: Any = None
    #: Paged-KV engine knobs (``page_size``, ``prefix_cache``,
    #: ``n_pages``), applied by the replica to every
    #: :class:`~ray_tpu.serve.engine.DecodeEngine` the user callable
    #: constructs — the declarative twin of
    #: ``@serve.batch(continuous=True, page_size=...)``.
    engine_config: Optional[Dict[str, Any]] = None
    health_check_period_s: float = 2.0
    graceful_shutdown_timeout_s: float = 5.0
    ray_actor_options: Dict[str, Any] = field(default_factory=dict)

    def initial_target(self) -> int:
        ac = self.autoscaling_config
        if ac is None:
            return self.num_replicas
        if ac.initial_replicas is not None:
            return max(ac.min_replicas,
                       min(ac.max_replicas, ac.initial_replicas))
        return ac.min_replicas


@dataclass
class HTTPOptions:
    """Proxy bind options (reference: ``serve/config.py`` ``HTTPOptions``)."""

    host: str = "127.0.0.1"
    port: int = 8000
    request_timeout_s: float = 60.0


@dataclass
class gRPCOptions:  # noqa: N801 - reference-parity name
    """gRPC ingress bind options (reference: ``serve/config.py``
    ``gRPCOptions`` — served by the same proxy actor as HTTP)."""

    host: str = "127.0.0.1"
    port: int = 0  # 0 = pick a free port


SERVE_CONTROLLER_NAME = "SERVE_CONTROLLER"
DEFAULT_APP_NAME = "default"
