"""Continuous-batching decode engine: one driver thread, one persistent
slot pool (ISSUE 5 tentpole).

``@serve.batch(stream=True)`` gang-schedules: a batch forms once, runs
its whole generation off a freshly allocated KV cache, and a request
arriving mid-generation waits for the NEXT batch (or spawns a competing
per-batch stream thread that contends for the one device). The engine
replaces gang scheduling with **slot scheduling** — the standard
continuous-batching design of production inference stacks, mapped onto
TPU-friendly static shapes:

- ONE long-lived pooled KV cache (``[L, B_slots, max_len, H, hd]``,
  :func:`~ray_tpu.models.gpt_decode.init_slot_cache`) allocated at
  construction. No per-request ``init_cache``; slots are recycled by
  re-prefilling in place.
- A single driver thread owns every device dispatch, so concurrent
  requests never contend for the device — request threads only enqueue
  (device-concurrency discipline per the TPU concurrency study in
  PAPERS.md).
- Admission happens at **chunk boundaries**:
  :func:`~ray_tpu.models.gpt_decode.prefill_into_slot` writes the
  prompt's K/V into a free slot (one compiled program per prompt
  bucket; the TRUE length is traced, so any length within a bucket
  shares the program) and the first sampled token streams out
  immediately — TTFT is one prefill dispatch away from admission, not
  one full gang generation.
- :func:`~ray_tpu.models.gpt_decode.decode_chunk_slots` then decodes
  ALL active slots in one fused k-step dispatch; a slot frees the
  moment its lane samples EOS, exhausts ``max_new``, passes its
  deadline, or its consumer walks away — instead of riding out the
  batch.

Static-shape discipline: the compiled-program set is exactly
``len(prompt_buckets)`` prefill programs + 1 chunk program, bounded for
ANY admission pattern (see the recompile guard in
``tests/test_serve_engine.py``).

Results stream back through the same :class:`~.batching._StreamLane`
queues the batched streaming path uses, so replicas, handles, and the
HTTP proxy need no new transport: ``engine.submit(...)`` returns a lane,
``engine.stream(...)`` an iterator of per-chunk ``np.int32[j]`` slices.

**Paged KV cache + shared-prefix reuse** (ISSUE 6 tentpole,
``paged=True``): the flat pool reserves ``max_len`` KV per slot up
front, so concurrency is capped by the WORST-CASE sequence even when
every live request is short. Paged mode splits the same byte budget
into fixed-size pages (``[L, n_pages, page_size, H, hd]``) handed out
by a host-side allocator:

- Each slot carries a page-table row (``[max_pages]`` int32, sentinel
  padded) that the device programs gather/scatter through — the table
  is traced DATA, so any mapping runs the same compiled programs.
- Pages are allocated **on advance**: a slot takes its next page only
  when ``pos`` is about to cross a page boundary (checked at chunk
  boundaries, where admission already happens). Out of pages is a
  *defined* backpressure path: admission defers (FIFO kept, and freed
  pages flow to parked lanes BEFORE new admissions) and a running slot
  parks out of the dispatch mask until a page frees — never a silent
  clamped write into someone else's page. If EVERY occupied slot is
  parked (allocation deadlock), the youngest lane is preempted **by
  recompute**: its pages free, its request requeues at the head, and on
  re-admission the deterministic per-request PRNG lane replays the
  exact same tokens with the already-delivered prefix suppressed — the
  consumer sees a stall, never an error or a duplicate token.
- A **prefix cache** (``prefix_cache=True``) hashes prompt prefixes at
  page granularity: a request whose prompt prefix is already resident
  maps the cached pages into its table (refcounted), prefills only the
  suffix, and — when the cached prefix ends mid-page — forks that one
  page copy-on-write inside the same prefill program. TTFT for a
  cached system prompt becomes a page-table copy plus a short-suffix
  prefill. Cache entries are evicted LRU when the allocator runs dry.

Flat slots remain the default; paged engines are asserted
token-identical to flat (temp 0 AND seeded temp > 0) in
``tests/test_serve_engine_paged.py``.

**Crash-safe streaming** (ISSUE 7 tentpole): the recompute-preemption
replay above generalizes ACROSS engines — a stream is fully determined
by (prompt, sampling knobs, seed, delivered-token count), so any engine
holding the same weights can reconstruct a lane killed elsewhere:
``submit(resume_from=n)`` replays the generation and suppresses the
first ``n`` tokens (on a paged engine whose prefix cache holds the
prompt, the replay prefill is near-free). The serve layers lean on it
three ways:

- the driver thread stamps a **heartbeat** per dispatch loop;
  :meth:`supervise` (called from the replica's ``check_health``)
  detects a dead or wedged driver, fails current lanes with the
  *retryable* :class:`EngineRestartError` (clients resume on another
  replica via ``resume_from``), and restarts the driver ONCE before
  reporting unhealthy — replica replacement is the escalation, not the
  first response;
- :meth:`drain` winds an engine down gracefully: admissions stop
  (``submit`` raises the retryable :class:`EngineShutdownError`, so the
  router re-picks), running lanes finish, stragglers fail retryably at
  the deadline;
- :meth:`inject_fault` arms the chaos harness (driver death / wedge /
  process kill at token N) driven by ``tests/test_serve_chaos.py`` and
  ``benchmarks/serve_gpt.py --chaos``.

**Speculative decoding** (ISSUE 9 tentpole, ``spec_decode=..``): the
chunk path above pays one full target forward per generated token —
decode stays memory-bandwidth-bound on weights/KV per token. With a
drafter configured (``spec_decode="ngram"`` / ``"model"`` / a
:class:`~.draft.Drafter` instance; ``draft_k`` proposals per round),
the driver interleaves **draft → verify** per chunk boundary instead:

- the drafter proposes ``draft_k`` tokens per active slot (host-side
  n-gram table, or a small GPT on its own slot pool — see
  :mod:`~.draft`);
- ONE batched target forward
  (:func:`~ray_tpu.models.gpt_decode.verify_chunk_slots`, paged twin
  included) scores all ``draft_k + 1`` logit rows, computes each
  slot's accepted length with exact rejection sampling (greedy match
  at temperature 0; point-mass residual resampling above it — the
  committed stream is the target's own distribution for ANY drafter,
  and bitwise the greedy stream at temperature 0), samples the
  bonus/correction token, and rolls each slot's KV write cursor back
  past its rejected positions in-program;
- each slot advances by its OWN ``accepted + 1`` — the variable
  per-slot advance rides the same EOS/deadline/freeing/``resume_from``
  replay logic as the fixed-k path (replay tokens count DELIVERED
  tokens, so crash-resume stays token-identical through any acceptance
  pattern).

The compiled-program set grows by exactly ONE verify program per
``draft_k`` (``len(prompt_buckets) + 1 + 1`` with the n-gram drafter);
accepted-token throughput multiplies by the mean committed tokens per
verify forward (``1 + mean_accept_len``) while the per-forward cost
stays one weight sweep. Wired through the config plane as
``@serve.batch(continuous=True, spec_decode=.., draft_k=..)`` and the
deployment schema's ``engine:`` block; A/B'd in
``benchmarks/serve_gpt.py --spec``.

**Disaggregated prefill/decode** (ISSUE 14 tentpole, ``role=..``):
prefill is compute-bound and bursty, decode bandwidth-bound and steady
— colocated they fight for the one driver dispatch slot and prefill
bursts inflate decode TPOT. ``role="prefill"`` turns an engine into a
prefill-only front: :meth:`handoff` runs the prompt into a transient
slot, samples the first token, EXPORTS the slot's K/V into a contiguous
ship buffer (:func:`~ray_tpu.models.gpt_decode.export_slot_kv`, paged
twin included; trimmed to the true prompt length so the bytes are
identical whichever pool mode produced them), frees the slot
immediately — no slot-pool steady state — and returns a descriptor
under an epoch-stamped **lease** (:mod:`~.handoff`). ``role="decode"``
engines own the slot pools: :meth:`admit_prefilled` resolves the
descriptor (inline or an object-plane chunked pull), BYTE-VERIFIES the
shipped pages against the stamped digest, and imports them into a free
slot/pages (:func:`~ray_tpu.models.gpt_decode.import_slot_kv`), so the
first decode chunk continues bit-exactly where the prefill engine
stopped. Every failure mode degrades to a cheap re-prefill, never a
broken stream: a missing/corrupt payload falls back to a local prefill
from the descriptor's prompt+seed (token-identical by determinism); a
decode side that never claims lets the lease expire, and the prefill
driver's sweep reclaims the shipped pages — a crash can never pin the
pool. The handoff plane adds exactly TWO compiled programs per engine
(export + import); ``role="both"`` (the default) serves all paths.
"""
from __future__ import annotations

import collections
import hashlib
import os
import queue
import threading
import time
from dataclasses import dataclass, field
from typing import List, Optional, Sequence, Tuple

import numpy as np

from .._private import events as _events
from .._private.events import driver_emit as _driver_emit
from ..util import tracing
from .batching import (_STREAM_END, _EngineStream, _StreamLane,
                       default_buckets)
from .request import (RequestDeadlineExceeded, deadline_expired,
                      get_request_id)


def default_prompt_buckets(max_len: int) -> List[int]:
    """Powers of two from 8 up to (and including) max_len."""
    return sorted(b for b in default_buckets(max_len) if b >= 8) \
        or [max_len]


def _node_id():
    """This process's node id (handoff locality hint), or None outside
    a running runtime."""
    try:
        from ..core.worker import CoreWorker

        core = CoreWorker._current
        return getattr(core, "node_id", None) if core is not None \
            else None
    except Exception:  # noqa: BLE001 - no runtime in this process
        return None


@dataclass
class _EngineRequest:
    """One queued admission: everything the driver needs to prefill a
    slot and route its stream."""

    prompt: np.ndarray            # [S] int32
    bucket: int                   # padded prompt length (compile shape)
    max_new: int
    lane: _StreamLane
    deadline_s: Optional[float]
    trace_ctx: Optional[dict]
    seed: int
    enq_t: float
    #: Tokens already delivered before a recompute preemption: the
    #: replay regenerates them (identical — the per-request PRNG lane
    #: is deterministic) and suppresses this many from the stream.
    skip: int = 0
    #: Prefill-role handoff export: admit = prefill + export + free,
    #: the lane receives ONE item (the handoff descriptor), no slot
    #: steady state.
    export: bool = False
    #: Decode-role import: a verified handoff payload whose K/V is
    #: scattered into the slot instead of prefilling. Kept on the
    #: request so a recompute preemption re-imports (cheaper than a
    #: re-prefill, identical by construction).
    handoff: Optional[dict] = None
    #: Export-side lease TTL override (0 = the engine's default).
    ttl_s: float = 0.0
    #: Flight-recorder correlation id: the router-stamped request id
    #: read from the replica's contextvar at submit time (falls back to
    #: a local ``eng-<n>`` id for bare in-process engine use), stamped
    #: on every event this request's slot produces.
    req_id: str = ""


@dataclass
class _Slot:
    """Host-side state of one occupied slot between chunk boundaries."""

    lane: _StreamLane
    remaining: int                # tokens still owed to the caller
    deadline_s: Optional[float]
    trace_ctx: Optional[dict]
    req: Optional[_EngineRequest] = None   # for recompute preemption
    emitted: int = 1              # tokens DELIVERED to the lane
    admitted_t: float = field(default_factory=time.time)
    # -------- paged-mode bookkeeping (empty/ignored for flat pools)
    pos: int = 0                  # virtual write position (mirrors device)
    pages: List[int] = field(default_factory=list)
    parked: bool = False          # out of pages: excluded from dispatch
    skip: int = 0                 # replay tokens left to suppress


class EngineShutdownError(RuntimeError):
    """The engine stopped while this request was queued or decoding.

    Retryable: the request state (prompt, knobs, seed, delivered count)
    fully determines the stream, so the router re-picks another replica
    — mid-stream via ``resume_from`` replay — instead of surfacing a
    hard failure or marking the replica dead."""

    retryable = True


class EngineRestartError(EngineShutdownError):
    """The engine's driver thread died or wedged; its lanes were failed
    and the driver restarted (or is awaiting replica replacement).
    Retryable like :class:`EngineShutdownError` — resumed streams replay
    deterministically on whichever replica admits them next."""


class _PagePool:
    """Host-side page allocator: a free list plus per-page refcounts.
    Shared-prefix pages are mapped into several page tables at once (and
    pinned by prefix-cache entries); a page returns to the free list
    when its LAST reference drops. Driver-thread only — no locking."""

    def __init__(self, n_pages: int):
        self.n_pages = n_pages
        self.refs = [0] * n_pages
        # Pop from the end → low page indices hand out first (stable
        # layouts in tests/benchmarks).
        self.free = list(range(n_pages - 1, -1, -1))

    def available(self) -> int:
        return len(self.free)

    def alloc(self, n: int) -> Optional[List[int]]:
        """n fresh pages at refcount 1, or None (caller defers/parks)."""
        if n > len(self.free):
            return None
        out = [self.free.pop() for _ in range(n)]
        for p in out:
            self.refs[p] = 1
        return out

    def ref(self, pages: Sequence[int]):
        for p in pages:
            self.refs[p] += 1

    def unref(self, pages: Sequence[int]):
        for p in pages:
            self.refs[p] -= 1
            assert self.refs[p] >= 0, f"page {p} over-freed"
            if self.refs[p] == 0:
                self.free.append(p)


class _PrefixCache:
    """Prompt-prefix → resident-pages map, page-granular with an
    exact-length tail entry.

    Keys are content hashes of the token prefix at every page boundary
    plus the full prompt length; entries pin their pages with a pool
    reference so a cached prefix survives the lane that produced it.
    Lookup probes the query's page boundaries longest-first (plus its
    exact length), verifies tokens byte-for-byte (hashes only index),
    and returns ``(hist_len, pages)`` — ``hist_len`` capped one token
    short of the query so the suffix prefill always has a token to
    sample from. Page-aligned hits share pages directly; an exact-length
    hit ends mid-page and the engine forks that page copy-on-write.
    LRU: entries are evicted (unpinning their pages) when the allocator
    runs dry."""

    def __init__(self, pool: _PagePool, page_size: int):
        self._pool = pool
        self._ps = page_size
        # key -> (n_tokens, prefix_bytes, pages tuple)
        self._entries: "collections.OrderedDict[bytes, Tuple[int, bytes, Tuple[int, ...]]]" = \
            collections.OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def __len__(self):
        return len(self._entries)

    @staticmethod
    def _key(tokens: np.ndarray, n: int) -> bytes:
        return hashlib.sha1(
            np.ascontiguousarray(tokens[:n]).tobytes()).digest()

    def lookup(self, tokens: np.ndarray) -> Tuple[int, List[int]]:
        """Longest cached prefix of ``tokens`` (< len(tokens)); returns
        ``(hist_len, pages_covering_hist)`` or ``(0, [])``."""
        P = len(tokens)
        probes = sorted({n for n in
                         list(range(self._ps, P + 1, self._ps)) + [P]},
                        reverse=True)
        for n in probes:
            ent = self._entries.get(self._key(tokens, n))
            if ent is None:
                continue
            n_cached, raw, pages = ent
            if n_cached != n or raw != tokens[:n].tobytes():
                continue                     # hash collision: skip
            hist = min(n, P - 1)
            if hist <= 0:
                continue
            self._entries.move_to_end(self._key(tokens, n))
            self.hits += 1
            n_cover = -(-hist // self._ps)   # ceil
            return hist, list(pages[:n_cover])
        self.misses += 1
        return 0, []

    def insert(self, tokens: np.ndarray, pages: Sequence[int]):
        """Register a freshly prefilled prompt's pages: one entry per
        covered page boundary plus the exact prompt length. Existing
        keys just refresh their LRU position."""
        P = len(tokens)
        bounds = list(range(self._ps, P + 1, self._ps))
        if P not in bounds:
            bounds.append(P)
        for n in bounds:
            key = self._key(tokens, n)
            if key in self._entries:
                self._entries.move_to_end(key)
                continue
            n_cover = -(-n // self._ps)
            ent_pages = tuple(pages[:n_cover])
            self._pool.ref(ent_pages)
            self._entries[key] = (n, tokens[:n].tobytes(), ent_pages)

    def evict_lru(self) -> bool:
        """Drop the oldest entry whose eviction actually FREES a page
        (some page at refcount 1 — held by the cache alone). False when
        no eviction can free anything: entries pinned by live lanes stay
        resident and keep serving hits rather than being wiped for an
        allocation that would fail anyway. Liveness: with no lane pins,
        a prompt's maximal entry holds its tail page exclusively, so a
        non-empty cache always has an evictable entry."""
        for key, (_n, _raw, pages) in self._entries.items():
            if any(self._pool.refs[p] == 1 for p in pages):
                del self._entries[key]
                self._pool.unref(pages)
                self.evictions += 1
                return True
        return False

    def clear(self):
        """Unpin and drop EVERY entry, shared or not (cache teardown —
        eviction's frees-a-page filter does not apply)."""
        while self._entries:
            _, (_n, _raw, pages) = self._entries.popitem(last=False)
            self._pool.unref(pages)
            self.evictions += 1


class DecodeEngine:
    """Slot-based continuous-batching engine for the chunked GPT decode
    path.

    Usage (inside a deployment; or see ``@serve.batch(continuous=True)``
    for the decorator form)::

        engine = DecodeEngine(params, cfg, slots=8, chunk=8,
                              max_len=256, eos_token=eos)
        for slice_ in engine.stream(prompt_ids, max_new=64):
            ...                       # np.int32 [j] per chunk, first j=1

    All device work runs on the engine's single driver thread;
    ``submit``/``stream`` only enqueue and are safe from any thread.
    At ``temperature == 0`` each stream is token-identical to
    :func:`~ray_tpu.models.gpt_decode.generate_chunked` for the same
    prompt (asserted in ``tests/test_serve_engine.py``).
    """

    def __init__(self, params, cfg, *, slots: int = 4, chunk: int = 8,
                 max_len: int = 0, temperature: float = 0.0,
                 eos_token: int = -1,
                 prompt_buckets: Optional[Sequence[int]] = None,
                 deployment: str = "", auto_start: bool = True,
                 paged: bool = False, page_size: int = 16,
                 n_pages: int = 0, prefix_cache: bool = True,
                 wedge_timeout_s: float = 30.0,
                 max_driver_restarts: int = 1,
                 spec_decode=None, draft_k: int = 4,
                 spec_threshold: float = 0.0,
                 role: str = "both", handoff_ttl_s: float = 30.0,
                 attn_kernel: str = "gather", kv_dtype: str = "fp",
                 tp: int = 1):
        from ..models import gpt_decode
        from .draft import make_drafter
        from .handoff import LeaseTable

        self.params = params
        self.cfg = cfg
        self.slots = int(slots)
        self.chunk = int(chunk)
        self.max_len = int(max_len or cfg.max_seq)
        self.temperature = float(temperature)
        self.eos_token = int(eos_token)
        self.deployment = deployment or "engine"
        if self.slots < 1 or self.chunk < 1:
            raise ValueError("slots and chunk must be >= 1")
        if self.max_len > cfg.max_seq:
            raise ValueError(f"max_len {self.max_len} exceeds model "
                             f"max_seq {cfg.max_seq}")
        buckets = sorted(set(int(b) for b in (
            prompt_buckets or default_prompt_buckets(self.max_len))))
        if not buckets or buckets[0] < 1:
            raise ValueError(f"invalid prompt_buckets {buckets}")
        if buckets[-1] > self.max_len:
            raise ValueError(
                f"largest prompt bucket {buckets[-1]} exceeds cache "
                f"length {self.max_len}")
        self.prompt_buckets = buckets
        self._gd = gpt_decode
        # ---- disaggregation role (ISSUE 14): "prefill" engines only
        # export handoffs (no slot-pool steady state), "decode" engines
        # additionally import them; "both" serves every path. The lease
        # table exists for every role — ensure_role may flip a fresh
        # engine before traffic, and an empty table costs nothing.
        if role not in ("both", "prefill", "decode"):
            raise ValueError(f"unknown engine role {role!r}; expected "
                             f"'prefill', 'decode', or 'both'")
        self.role = role
        self._leases = LeaseTable(ttl_s=float(handoff_ttl_s))
        # ---- speculative decoding (ISSUE 9): an optional drafter turns
        # the dispatch loop into draft -> verify; draft_k is the
        # chunk-static proposal width (one verify program per value).
        # spec_threshold > 0 enables POOL-WIDE adaptive speculation: a
        # boundary verifies only while the drafters' self-assessed mean
        # expected acceptance clears the threshold, else it runs ONE
        # plain chunk dispatch — all-or-nothing, because the chunk
        # program's cost is paid once for the whole pool, so a mixed
        # boundary would pay both programs and always lose. Pool-wide
        # decisions depend on pool COMPOSITION, which is only
        # replay-safe when sampling consumes no randomness — hence
        # greedy engines only (enforced below); temperature > 0 keeps
        # threshold 0 (always verify), whose per-slot PRNG chains are
        # independent of pool-mates.
        self.draft_k = int(draft_k)
        self.spec_threshold = float(spec_threshold)
        if self.draft_k < 1:
            raise ValueError("draft_k must be >= 1")
        if self.spec_threshold > 0.0 and self.temperature > 0.0:
            raise ValueError(
                "spec_threshold > 0 (adaptive speculation) requires "
                "temperature 0: the pool-wide verify-or-chunk decision "
                "depends on which lanes share the pool, and a sampled "
                "stream replayed on another pool would consume a "
                "different PRNG chain — breaking crash-resume replay")
        self._drafter = make_drafter(spec_decode, params, cfg)
        if self._drafter is not None:
            self._drafter.configure(
                slots=self.slots, max_len=self.max_len,
                prompt_buckets=self.prompt_buckets,
                draft_k=self.draft_k)
        # ---- paged-attention kernel + quantized KV (ISSUE 16): both
        # are ENGINE-STATIC knobs baked into the compiled programs at
        # pool build — never retrace triggers. Stored before _build_pool
        # (which reads them) and re-read verbatim on driver restart.
        if attn_kernel not in gpt_decode.ATTN_KERNELS:
            raise ValueError(
                f"unknown attn_kernel {attn_kernel!r}; expected one of "
                f"{gpt_decode.ATTN_KERNELS}")
        if kv_dtype not in gpt_decode.KV_DTYPES:
            raise ValueError(
                f"unknown kv_dtype {kv_dtype!r}; expected one of "
                f"{gpt_decode.KV_DTYPES}")
        if not paged and (attn_kernel != "gather" or kv_dtype != "fp"):
            raise ValueError(
                "attn_kernel/kv_dtype are paged-pool knobs; construct "
                "the engine with paged=True (or pass page_size through "
                "the config plane)")
        self.attn_kernel = attn_kernel
        self.kv_dtype = kv_dtype
        # ---- tensor parallelism (ISSUE 20): ENGINE-STATIC mesh width.
        # tp=N shards weights over heads/ffn and the KV pool over the
        # head dim; validated eagerly so a bad (cfg, tp) pair fails at
        # construction, not first dispatch. _tp_mesh also raises when
        # fewer than N devices are visible — on CPU, force host devices
        # via XLA_FLAGS before importing jax.
        self.tp = int(tp)
        gpt_decode._tp_mesh(cfg, self.tp)
        # Guards the put-vs-final-drain race: once _fail_all flips
        # _draining under this lock, no new submission can land in a
        # queue nobody will ever read again. Created BEFORE the pool so
        # every _build_pool call site can hold it (its holds= contract).
        self._admit_lock = threading.Lock()
        with self._admit_lock:
            self._build_pool(paged, page_size, n_pages, prefix_cache)
        # Per-slot host state; index i mirrors pool row i. ``_token`` /
        # ``_rngs`` are the host copies uploaded with each dispatch
        # (tiny against the chunk compute; keeping them host-side avoids
        # per-admission scatter programs).
        self._state: List[Optional[_Slot]] = [None] * self.slots
        self._token = np.zeros((self.slots,), np.int32)
        self._rngs = np.zeros((self.slots, 2), np.uint32)
        self._queue: "queue.SimpleQueue" = queue.SimpleQueue()
        # Driver-local FIFO fed from the submit queue; the head defers
        # in place when paged admission runs out of pages, preserving
        # arrival order across the backpressure boundary.
        self._pending: "collections.deque[_EngineRequest]" = \
            collections.deque()
        self._draining = False
        self._fail_lock = threading.Lock()
        self._stats_lock = threading.Lock()
        self._stats = {"admitted": 0, "completed": 0, "expired": 0,
                       "abandoned": 0, "prefills": 0, "dispatches": 0,
                       "tokens": 0, "occupancy_sum": 0.0,
                       "peak_active": 0, "prefix_hits": 0,
                       "prefix_tokens_reused": 0, "cow_copies": 0,
                       "admissions_deferred": 0, "lane_parks": 0,
                       "preempted": 0, "resumed": 0, "driver_restarts": 0,
                       "spec_rounds": 0, "spec_proposed": 0,
                       "spec_accepted": 0, "spec_fallback_rounds": 0,
                       "spec_lanes": 0,
                       "handoffs_exported": 0, "handoffs_imported": 0,
                       "handoff_import_fallbacks": 0,
                       "handoff_ship_bytes": 0,
                       "attn_kernel_dispatches": 0}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # ---- driver supervision (ISSUE 7): the driver stamps _beat at
        # every loop iteration; supervise() treats a stale beat from a
        # live thread as a wedge (stuck dispatch / stuck user fault) and
        # a dead thread as a crash. Each driver run gets an epoch — a
        # wedged thread that wakes after a restart finds the epoch moved
        # and drops its results instead of corrupting the new pool.
        self.wedge_timeout_s = float(wedge_timeout_s)
        self.max_driver_restarts = int(max_driver_restarts)
        self._beat = time.monotonic()
        self._epoch = 0
        self._shutdown = False
        self._supervise_lock = threading.Lock()
        #: Chaos-harness fault armed via inject_fault() (testing only).
        self._fault: Optional[dict] = None
        self._throttle_s = 0.0
        # Fallback flight-recorder ids for bare in-process submissions
        # (no router upstream to stamp the contextvar).
        self._req_uid = 0
        if auto_start:
            self.start()

    # THE engine program budget (rtflow RT109, ISSUE 15): one prefill
    # program per prompt bucket (the true prompt length is traced, so
    # every length within a bucket shares its program) + 1 fused chunk
    # program + the 2 KV-handoff programs (export + import). The verify
    # program is budgeted separately in _bind_verify. rtflow audits
    # this bound against every factory call and dispatch shape
    # reachable from here; the budget-vs-actual test pins it to the
    # jit cache sizes on nano CPU.
    # rtlint: program-budget: len(prompt_buckets) + 3
    def _build_pool(self, paged: bool, page_size: int, n_pages: int,
                    prefix_cache: bool):  # rtlint: holds=_admit_lock
        """Allocate THE persistent pool (flat or paged) and bind the
        matching jitted programs. Called once at construction, by
        :meth:`ensure_paging` on a never-used engine, and by
        :meth:`_restart_driver` — EVERY call site holds ``_admit_lock``
        (rtlint RT101 real finding: the restart path used to swap
        ``_pool``/``_prefix``/``_cache`` under only ``_fail_lock``,
        racing a concurrent ``ensure_paging`` config push)."""
        gpt_decode = self._gd
        cfg = self.cfg
        self.paged = bool(paged)
        # The dispatch-side weights: placed once per pool build (a
        # NamedSharding scatter when tp > 1, the raw host pytree when
        # tp == 1 — shard_params is an identity there). The drafter
        # keeps ``self.params``: it runs its own single-chip programs.
        self._params_dev = gpt_decode.shard_params(
            self.params, cfg, self.tp)
        if not self.paged:
            self.page_size = 0
            self.n_pages = 0
            self.max_pages = 0
            self._pool = None
            self._prefix = None
            self._pt = None
            self._prefill = gpt_decode.jit_prefill_into_slot(
                cfg, self.temperature, self.tp)
            self._step = gpt_decode.jit_decode_chunk_slots(
                cfg, self.chunk, self.temperature, self.eos_token,
                self.tp)
            self._export = gpt_decode.jit_export_slot_kv(cfg, self.tp)
            self._import = gpt_decode.jit_import_slot_kv(cfg, self.tp)
            self._cache = gpt_decode.init_slot_cache(cfg, self.slots,
                                                     self.max_len,
                                                     self.tp)
            self._bind_verify()
            return
        self.page_size = int(page_size)
        if self.page_size < 1:
            raise ValueError("page_size must be >= 1")
        self.max_pages = -(-self.max_len // self.page_size)   # ceil
        # Default budget: the SAME KV **bytes** as the flat fp pool
        # ([slots, max_len] worth of positions), re-cut into pages of
        # the configured kv_dtype — an int8 pool's page is ~half the
        # bytes, so the same budget holds ~2x the pages (the ISSUE 16
        # sizing fix: counting pages in positions instead of bytes left
        # half an int8 engine's HBM budget unused).
        fp_bytes = gpt_decode.kv_bytes_per_page(cfg, self.page_size)
        kv_bytes = gpt_decode.kv_bytes_per_page(cfg, self.page_size,
                                                self.kv_dtype)
        self.n_pages = int(n_pages) or \
            (self.slots * self.max_pages * fp_bytes) // kv_bytes
        if self.n_pages < self.max_pages:
            raise ValueError(
                f"n_pages {self.n_pages} cannot hold one max_len "
                f"sequence ({self.max_pages} pages of {self.page_size})")
        self._pool = _PagePool(self.n_pages)
        self._prefix = _PrefixCache(self._pool, self.page_size) \
            if prefix_cache else None
        self._pt = np.full((self.slots, self.max_pages),
                           gpt_decode.PT_SENTINEL, np.int32)
        self._prefill = gpt_decode.jit_prefill_into_slot_paged(
            cfg, self.page_size, self.temperature, self.kv_dtype,
            self.tp)
        self._step = gpt_decode.jit_decode_chunk_slots_paged(
            cfg, self.chunk, self.page_size, self.temperature,
            self.eos_token, self.kv_dtype, self.attn_kernel, self.tp)
        self._export = gpt_decode.jit_export_slot_kv_paged(
            cfg, self.page_size, self.kv_dtype, self.tp)
        self._import = gpt_decode.jit_import_slot_kv_paged(
            cfg, self.page_size, self.kv_dtype, self.tp)
        self._cache = gpt_decode.init_paged_cache(
            cfg, self.slots, self.n_pages, self.page_size,
            self.kv_dtype, self.tp)
        self._bind_verify()

    # rtlint: program-budget: 1
    def _bind_verify(self):  # rtlint: holds=_admit_lock
        """(Re)bind the verify program to the current pool layout and
        drafter — ONE compiled program per (pool shape, draft_k), or
        None with speculative decoding off (the flat/paged bindings are
        branch-exclusive, so the RT109 budget is 1, not 2). Called from
        :meth:`_build_pool` and :meth:`ensure_spec`, both of which hold
        ``_admit_lock``."""
        if self._drafter is None:
            self._verify = None
        elif self.paged:
            self._verify = self._gd.jit_verify_chunk_slots_paged(
                self.cfg, self.draft_k, self.page_size,
                self.temperature, self.kv_dtype, self.tp)
        else:
            self._verify = self._gd.jit_verify_chunk_slots(
                self.cfg, self.draft_k, self.temperature, self.tp)

    def ensure_paging(self, page_size: Optional[int] = None,
                      prefix_cache: Optional[bool] = None,
                      n_pages: Optional[int] = None,
                      attn_kernel: Optional[str] = None,
                      kv_dtype: Optional[str] = None):
        """Idempotently apply paging knobs from the config plane
        (``@serve.batch(continuous=True, page_size=..)`` or the
        deployment schema's ``engine:`` block). A matching engine is a
        no-op; a mismatched engine is rebuilt IF it has never admitted a
        request, else this raises — pool shape is load-bearing state,
        not something to swap under live lanes. ``attn_kernel`` /
        ``kv_dtype`` follow the same discipline: they are baked into
        the pool's compiled programs (and, for ``kv_dtype``, its byte
        layout), so a mismatch triggers the same rebuild-if-unused
        path."""
        want_ps = int(page_size) if page_size is not None else None
        if want_ps is not None and want_ps < 1:
            raise ValueError("page_size must be >= 1")
        if attn_kernel is not None and \
                attn_kernel not in self._gd.ATTN_KERNELS:
            raise ValueError(
                f"unknown attn_kernel {attn_kernel!r}; expected one of "
                f"{self._gd.ATTN_KERNELS}")
        if kv_dtype is not None and kv_dtype not in self._gd.KV_DTYPES:
            raise ValueError(
                f"unknown kv_dtype {kv_dtype!r}; expected one of "
                f"{self._gd.KV_DTYPES}")
        with self._admit_lock:
            if want_ps is None and not self.paged and (
                    prefix_cache or n_pages is not None or
                    (attn_kernel or "gather") != "gather" or
                    (kv_dtype or "fp") != "fp"):
                # Silently no-opping would leave the operator believing
                # prefix caching / pool sizing / the kernel / int8 KV
                # is active on a flat pool.
                raise ValueError(
                    "prefix_cache/n_pages/attn_kernel/kv_dtype are "
                    "paged-pool knobs; this engine is flat — pass "
                    "page_size to repage it")
            knob_change = (
                (attn_kernel is not None and
                 attn_kernel != self.attn_kernel) or
                (kv_dtype is not None and kv_dtype != self.kv_dtype))
            if want_ps is None and self.paged and (
                    n_pages is not None or knob_change):
                want_ps = self.page_size   # rebuild keeps the page size
            need_rebuild = want_ps is not None and (
                not self.paged or self.page_size != want_ps or
                (n_pages is not None and int(n_pages) != self.n_pages) or
                knob_change)
            if need_rebuild:
                with self._stats_lock:
                    used = self._stats["admitted"]
                if used or self._queue.qsize() or self._pending or \
                        any(s is not None for s in self._state):
                    raise ValueError(
                        f"cannot repage a live engine (page_size="
                        f"{self.page_size or None} -> {want_ps}); "
                        f"construct it paged or apply the config "
                        f"before traffic")
                if attn_kernel is not None:
                    self.attn_kernel = attn_kernel
                if kv_dtype is not None:
                    self.kv_dtype = kv_dtype
                self._build_pool(True, want_ps, int(n_pages or 0),
                                 prefix_cache if prefix_cache is not None
                                 else self._prefix is not None)
            elif prefix_cache is not None and self.paged:
                if prefix_cache and self._prefix is None:
                    self._prefix = _PrefixCache(self._pool,
                                                self.page_size)
                elif not prefix_cache and self._prefix is not None:
                    self._prefix.clear()
                    self._prefix = None
        return self

    def ensure_spec(self, spec_decode=None, draft_k: Optional[int] = None,
                    spec_threshold: Optional[float] = None):
        """Idempotently apply the speculative-decoding knobs from the
        config plane (``@serve.batch(continuous=True, spec_decode=..)``
        or the deployment schema's ``engine:`` block). A matching
        engine is a no-op; a mismatched engine is reconfigured IF it
        has never admitted a request, else this raises — the drafter's
        per-slot state and the verify program are load-bearing, not
        something to swap under live lanes."""
        from .draft import make_drafter

        if draft_k is not None and int(draft_k) < 1:
            raise ValueError("draft_k must be >= 1")
        with self._admit_lock:
            want_k = int(draft_k) if draft_k is not None else self.draft_k
            cur = self._drafter
            if spec_decode is None:
                want = cur
            elif isinstance(spec_decode, str) and cur is not None \
                    and cur.name == spec_decode:
                want = cur
            elif spec_decode is True and cur is not None:
                want = cur
            else:
                want = make_drafter(spec_decode, self.params, self.cfg)
            want_thr = float(spec_threshold) \
                if spec_threshold is not None else self.spec_threshold
            if want_thr > 0.0 and self.temperature > 0.0:
                raise ValueError(
                    "spec_threshold > 0 (adaptive speculation) "
                    "requires temperature 0 — see DecodeEngine")
            if want is cur and want_k == self.draft_k \
                    and want_thr == self.spec_threshold:
                return self
            with self._stats_lock:
                used = self._stats["admitted"]
            if used or self._queue.qsize() or self._pending or \
                    any(s is not None for s in self._state):
                raise ValueError(
                    "cannot change spec_decode/draft_k on a live "
                    "engine; construct it with the knobs or apply the "
                    "config before traffic")
            self.draft_k = want_k
            self.spec_threshold = want_thr
            self._drafter = want
            if want is not None:
                want.configure(slots=self.slots, max_len=self.max_len,
                               prompt_buckets=self.prompt_buckets,
                               draft_k=self.draft_k)
            self._bind_verify()
        return self

    def ensure_role(self, role: Optional[str] = None,
                    handoff_ttl_s: Optional[float] = None):
        """Idempotently apply the disaggregation knobs from the config
        plane (the deployment schema's ``engine: role:`` assignment —
        the controller stamps each replica's role when reconciling a
        ``roles:`` block). A matching engine is a no-op; a mismatched
        engine is re-roled IF it has never admitted or exported, else
        this raises — the role gates which queues exist, not something
        to flip under live lanes."""
        if role is not None and role not in ("both", "prefill",
                                             "decode"):
            raise ValueError(f"unknown engine role {role!r}")
        with self._admit_lock:
            if role is not None and role != self.role:
                with self._stats_lock:
                    used = self._stats["admitted"] \
                        + self._stats["handoffs_exported"]
                if used or self._queue.qsize() or self._pending or \
                        any(s is not None for s in self._state):
                    raise ValueError(
                        f"cannot change engine role ({self.role} -> "
                        f"{role}) on a live engine; construct it with "
                        f"the role or apply the config before traffic")
                self.role = role
            if handoff_ttl_s is not None:
                self._leases.ttl_s = float(handoff_ttl_s)
        return self

    def ensure_tp(self, tp: Optional[int] = None):
        """Idempotently apply the tensor-parallel width from the config
        plane (the deployment schema's ``engine: tp:`` knob). A
        matching engine is a no-op; a mismatched engine is rebuilt IF
        it has never admitted a request, else this raises — the mesh is
        baked into every compiled program AND the pool's device layout,
        so flipping it under live lanes would orphan the sharded
        cache."""
        if tp is None:
            return self
        want = int(tp)
        with self._admit_lock:
            if want == self.tp:
                return self
            with self._stats_lock:
                used = self._stats["admitted"]
            if used or self._queue.qsize() or self._pending or \
                    any(s is not None for s in self._state):
                raise ValueError(
                    f"cannot change tp ({self.tp} -> {want}) on a "
                    f"live engine; construct it with tp= or apply the "
                    f"config before traffic")
            # Validate (divisibility + visible devices) BEFORE mutating.
            self._gd._tp_mesh(self.cfg, want)
            self.tp = want
            self._build_pool(self.paged, self.page_size, self.n_pages,
                             self._prefix is not None)
        return self

    #: Config-plane knob split for :meth:`apply_config`.
    _PAGE_KEYS = ("page_size", "prefix_cache", "n_pages",
                  "attn_kernel", "kv_dtype")
    _SPEC_KEYS = ("spec_decode", "draft_k", "spec_threshold")
    _ROLE_KEYS = ("role", "handoff_ttl_s")
    _TP_KEYS = ("tp",)

    def apply_config(self, **knobs):
        """Route a deployment ``engine:`` config block to the right
        idempotent applier: paged-KV knobs to :meth:`ensure_paging`,
        speculative-decoding knobs to :meth:`ensure_spec`,
        disaggregation knobs to :meth:`ensure_role`. Unknown keys
        raise (the schema validates too — this guards direct callers).
        """
        known = set(self._PAGE_KEYS) | set(self._SPEC_KEYS) \
            | set(self._ROLE_KEYS) | set(self._TP_KEYS)
        unknown = set(knobs) - known
        if unknown:
            raise ValueError(
                f"unknown engine config keys {sorted(unknown)}; known: "
                f"{sorted(known)}")
        page = {k: v for k, v in knobs.items()
                if k in self._PAGE_KEYS and v is not None}
        spec = {k: v for k, v in knobs.items()
                if k in self._SPEC_KEYS and v is not None}
        rolek = {k: v for k, v in knobs.items()
                 if k in self._ROLE_KEYS and v is not None}
        tpk = {k: v for k, v in knobs.items()
               if k in self._TP_KEYS and v is not None}
        # tp first: a repage after the mesh flip lands on the already-
        # sharded pool, while the reverse would rebuild twice.
        if tpk:
            self.ensure_tp(**tpk)
        if page:
            self.ensure_paging(**page)
        if spec:
            self.ensure_spec(**spec)
        if rolek:
            self.ensure_role(**rolek)
        return self

    # ------------------------------------------------------------- admission
    def _validate_admission(self, prompt, max_new: int):
        """Shared admission-time validation for every entry point that
        prefills from a prompt (``submit`` and ``handoff``):
        canonicalize the prompt, pick its compile bucket, and bound the
        generation against the cache. Returns ``(prompt, bucket)``."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        S = prompt.shape[0]
        if S < 1:
            raise ValueError("empty prompt")
        bucket = next((b for b in self.prompt_buckets if b >= S), None)
        if bucket is None:
            raise ValueError(
                f"prompt length {S} exceeds largest prompt bucket "
                f"{self.prompt_buckets[-1]}")
        if S + max_new > self.max_len:
            raise ValueError(
                f"prompt ({S}) + max_new ({max_new}) exceeds cache "
                f"length {self.max_len}")
        return prompt, bucket

    def _new_req_id(self) -> str:
        """Flight-recorder correlation id for this admission: the
        router-stamped id when one rode the request context here, else
        a local ``eng-<pid>-<n>`` id so bare in-process streams still
        correlate across their own events."""
        rid = get_request_id()
        if rid:
            return rid
        with self._admit_lock:
            self._req_uid += 1
            return f"eng-{os.getpid():x}-{self._req_uid}"

    def submit(self, prompt, max_new: int, *,
               deadline_s: Optional[float] = None,
               trace_ctx: Optional[dict] = None,
               seed: int = 0, resume_from: int = 0) -> _StreamLane:
        """Enqueue one request; returns its stream lane immediately. The
        driver admits it at the next chunk boundary with a free slot.
        Safe from any thread.

        ``resume_from=n`` is the mid-stream failover replay token: the
        caller already holds the first ``n`` tokens of this exact
        (prompt, knobs, seed) stream — delivered by another replica
        before it died — so the engine replays the generation (the
        per-request PRNG lane is deterministic; a paged engine's prefix
        cache makes the prompt prefill near-free) and suppresses the
        first ``n`` tokens from the lane."""
        if self.role == "prefill":
            raise ValueError(
                "prefill-role engine only exports handoffs (use "
                "handoff()); decode streams need a decode-capable "
                "engine")
        prompt, bucket = self._validate_admission(prompt, max_new)
        resume_from = int(resume_from)
        if resume_from < 0 or resume_from > max_new:
            raise ValueError(
                f"resume_from {resume_from} outside [0, max_new="
                f"{max_new}] — the replay token counts tokens this "
                f"stream already delivered")
        lane = _StreamLane()
        if max_new <= 0:
            lane.q.put((_STREAM_END, None))
            return lane
        req_id = self._new_req_id()
        with self._admit_lock:
            # _draining (not thread-aliveness) is the admission gate: a
            # not-yet-started engine (auto_start=False) queues work for
            # start(), while a shut-down, draining, or crashed driver —
            # which flipped _draining in _fail_all — rejects (retryably:
            # the router re-picks) instead of accepting submissions
            # nobody will ever read.
            if self._draining:
                raise EngineShutdownError(
                    "engine is not accepting requests (draining or shut "
                    "down); resubmit on another replica")
            self._queue.put(_EngineRequest(
                prompt=prompt, bucket=bucket, max_new=int(max_new),
                lane=lane, deadline_s=deadline_s, trace_ctx=trace_ctx,
                seed=int(seed), enq_t=time.time(), skip=resume_from,
                req_id=req_id))
        if resume_from:
            self._count(resumed=1)
            _events.emit("engine.resume", request=req_id,
                         resume_from=int(resume_from),
                         epoch=self._epoch)
        return lane

    def stream(self, prompt, max_new: int, **kw):
        """``submit`` + drain: an iterator of np.int32 ``[j]`` chunk
        slices (first slice is the prefill token alone). ``close()``
        marks the lane abandoned even before the first pull."""
        return _EngineStream(self.submit(prompt, max_new, **kw))

    # --------------------------------------------------- disaggregation
    def handoff(self, prompt, max_new: int, *, seed: int = 0,
                deadline_s: Optional[float] = None,
                trace_ctx: Optional[dict] = None,
                ttl_s: Optional[float] = None) -> dict:
        """Prefill ``prompt`` into a transient slot, sample the first
        token, EXPORT the slot's K/V, and return a leased handoff
        descriptor (ISSUE 14). The slot frees before this returns — a
        prefill-role engine never holds slot-pool steady state.

        The descriptor carries the lease (``lease_id``/``epoch``/
        ``expires_at``), the byte-verification ``digest``, the shipped
        payload (inline, or an object-plane ``ref`` the decode side
        pulls through the chunked-transfer path), and the full replay
        identity (``prompt``/``seed``/``max_new``) — so ANY
        decode-capable engine can either import the bytes or, if they
        are gone, re-prefill the identical stream from scratch.

        Blocks the calling thread until the driver exports (bounded by
        ``deadline_s``); safe from any thread."""
        if self.role == "decode":
            raise ValueError(
                "decode-role engine cannot export handoffs; use a "
                "prefill or both-role engine")
        prompt, bucket = self._validate_admission(prompt, max_new)
        if max_new < 1:
            raise ValueError("handoff needs max_new >= 1 (the first "
                             "token is sampled at prefill)")
        lane = _StreamLane()
        req_id = self._new_req_id()
        with self._admit_lock:
            if self._draining:
                raise EngineShutdownError(
                    "engine is not accepting requests (draining or "
                    "shut down); resubmit on another replica")
            self._queue.put(_EngineRequest(
                prompt=prompt, bucket=bucket, max_new=int(max_new),
                lane=lane, deadline_s=deadline_s, trace_ctx=trace_ctx,
                seed=int(seed), enq_t=time.time(), export=True,
                ttl_s=float(ttl_s or 0.0), req_id=req_id))
        # Synchronous drain: ONE item (the descriptor), then END. The
        # wait is deadline-bounded so a wedged driver surfaces as the
        # deadline error instead of a hang.
        from .request import remaining_s
        while True:
            rem = remaining_s(deadline_s)
            try:
                kind, val = lane.q.get(
                    timeout=rem if rem is not None else 120.0)
            except queue.Empty:
                lane.closed = True
                raise RequestDeadlineExceeded(
                    "handoff export did not complete before the "
                    "request deadline") from None
            if kind == "err":
                raise val
            if kind is _STREAM_END:
                raise EngineShutdownError(
                    "handoff export lane closed without a descriptor")
            return val

    def claim_handoff(self, lease_id: str, epoch: int) -> bool:
        """Decode-side acknowledgement that a shipped payload was
        imported: releases the lease (and the pin on the shipped
        object) before its expiry. Unknown/stale leases return False —
        the sweep already reclaimed them, which is also fine: the
        claimer holds the bytes it needs. Safe from any thread."""
        ok = self._leases.claim(lease_id, int(epoch))
        _events.emit("handoff.claim", lease=lease_id,
                     epoch=int(epoch), released=ok)
        return ok

    def admit_prefilled(self, desc: dict, *,
                        deadline_s: Optional[float] = None,
                        trace_ctx: Optional[dict] = None,
                        resume_from: int = 0) -> _StreamLane:
        """Admit a handed-off stream: resolve the descriptor's payload
        (inline, or a chunked object-plane pull), BYTE-VERIFY it, and
        enqueue an import admission — the driver scatters the shipped
        K/V into a free slot/pages and decoding continues bit-exactly
        from the prefill engine's state. Any resolution or verification
        failure degrades to a LOCAL prefill of the descriptor's
        prompt+seed (token-identical by determinism), counted as a
        fallback. Returns the stream lane; safe from any thread."""
        from .handoff import HandoffError, resolve_payload, verify_payload
        from .request import remaining_s

        if self.role == "prefill":
            raise ValueError(
                "prefill-role engine cannot import handoffs; use a "
                "decode or both-role engine")
        prompt = np.asarray(desc["prompt"], np.int32).reshape(-1)
        max_new = int(desc["max_new"])
        seed = int(desc["seed"])
        resume_from = int(resume_from)
        payload = None
        try:
            rem = remaining_s(deadline_s)
            payload = resolve_payload(
                desc, timeout_s=min(rem, 30.0) if rem is not None
                else 30.0)
            # Cross-plane check FIRST: the descriptor's digest traveled
            # over the RPC plane, independently of the object-plane
            # payload — a stale or wrong payload that is internally
            # consistent would pass verify_payload alone.
            if desc.get("digest") and \
                    payload.get("digest") != desc["digest"]:
                raise HandoffError(
                    "shipped payload digest does not match the "
                    "descriptor's (stale or clobbered object)")
            verify_payload(payload)
            if int(payload["pos"]) + max_new > self.max_len:
                raise HandoffError(
                    f"shipped pos {payload['pos']} + max_new "
                    f"{max_new} exceeds cache length {self.max_len}")
            want = (self.cfg.n_layer, int(payload["pos"]),
                    self.cfg.n_head, self.cfg.head_dim)
            if tuple(payload["k"].shape) != want \
                    or tuple(payload["v"].shape) != want:
                raise HandoffError(
                    f"shipped KV shape {tuple(payload['k'].shape)} "
                    f"does not fit this engine's model ({want})")
            # Layout identity (ISSUE 16): quantized payloads only land
            # on an engine with the SAME kv_dtype and page_size — int8
            # codes are meaningless without their page-aligned scales,
            # and scales are page-granular. Any mismatch (int8->fp,
            # fp->int8, or a different page cut) degrades to the local
            # re-prefill, which is token-identical by determinism.
            ship_dt = payload.get("kv_dtype", "fp")
            mine = self.kv_dtype if self.paged else "fp"
            if ship_dt != mine:
                raise HandoffError(
                    f"shipped kv_dtype {ship_dt!r} does not match this "
                    f"engine's ({mine!r})")
            if ship_dt == "int8" and \
                    int(payload.get("page_size", 0)) != self.page_size:
                raise HandoffError(
                    f"shipped page_size {payload.get('page_size')} "
                    f"does not match this engine's ({self.page_size})")
            # tp-layout identity (ISSUE 20): the handoff plane ships
            # CANONICAL host-order KV only — the exporter gathers its
            # mesh and the importer's jit scatters into its own, so an
            # N-way prefill feeds an M-way decode with no negotiation.
            # A payload stamped with any other layout came from a
            # foreign/newer protocol; its bytes would scatter wrong, so
            # it degrades to the counted local re-prefill below.
            ship_layout = payload.get("layout", "canonical")
            if ship_layout != "canonical":
                raise HandoffError(
                    f"shipped KV layout {ship_layout!r} is not the "
                    f"canonical host layout; refusing to scatter into "
                    f"a tp={self.tp} mesh")
        except HandoffError:
            payload = None
        if payload is None:
            # Degraded path: the bytes are gone or bad — re-prefill the
            # SAME deterministic stream locally. Counted so the A/B and
            # the chaos harness can see who paid what.
            self._count(handoff_import_fallbacks=1)
            from .._private.metrics import serve_metrics
            serve_metrics()["prefill_fallbacks"].inc(
                labels={"deployment": self.deployment,
                        "where": "engine"})
            return self.submit(prompt, max_new, seed=seed,
                               deadline_s=deadline_s,
                               trace_ctx=trace_ctx,
                               resume_from=resume_from)
        if resume_from < 0 or resume_from > max_new:
            raise ValueError(
                f"resume_from {resume_from} outside [0, max_new="
                f"{max_new}]")
        # The preemption-replay fallback needs a bucket only when the
        # payload is lost mid-flight; an over-long prompt just pins the
        # import path (re-import replays it fine).
        bucket = next((b for b in self.prompt_buckets
                       if b >= prompt.shape[0]), self.prompt_buckets[-1])
        lane = _StreamLane()
        req_id = self._new_req_id()
        with self._admit_lock:
            if self._draining:
                raise EngineShutdownError(
                    "engine is not accepting requests (draining or "
                    "shut down); resubmit on another replica")
            self._queue.put(_EngineRequest(
                prompt=prompt, bucket=bucket, max_new=max_new,
                lane=lane, deadline_s=deadline_s, trace_ctx=trace_ctx,
                seed=seed, enq_t=time.time(), skip=resume_from,
                handoff={"payload": payload,
                         "created_t": desc.get("created_t")},
                req_id=req_id))
        if resume_from:
            self._count(resumed=1)
        return lane

    def queue_depth(self) -> int:
        """Requests accepted but not yet admitted to a slot (submit
        queue + the driver's deferred FIFO). THE offline-pipeline
        throttle signal (ISSUE 11): a saturated pool wants this small
        but nonzero — zero risks an idle boundary, unbounded means the
        admission queue is absorbing the whole dataset. Exported as the
        ``serve_engine_queue_depth`` gauge once per driver loop.
        Safe from any thread (both reads are approximate by nature)."""
        return self._queue.qsize() + len(self._pending)

    # ------------------------------------------------------------- lifecycle
    def start(self):
        if self._thread is not None and self._thread.is_alive():
            return
        with self._admit_lock:
            self._draining = False
        self._shutdown = False
        self._stop = threading.Event()
        self._beat = time.monotonic()
        self._thread = threading.Thread(
            target=self._run, args=(self._stop, self._epoch),
            daemon=True, name=f"rt-serve-engine-{self.deployment}")
        self._thread.start()

    def shutdown(self, timeout_s: float = 5.0):
        """Stop the driver and fail EVERY queued or in-flight lane with
        :class:`EngineShutdownError` — unconditionally. The driver's own
        exit path fails lanes too, but only if it is alive to run it; a
        never-started driver (``auto_start=False``) or one that died at
        startup would otherwise leave queued submissions hanging
        forever, so the drain repeats here (idempotent: the queue is
        drained once, double error puts on a lane are inert)."""
        self._shutdown = True
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)
        # A driver that outlived the join (stuck in a long dispatch /
        # first-call compile) still owns the slot structures and the
        # page pool: fail the lanes but leave the bookkeeping to its
        # own exit path, or freed pages would be double-unref'd.
        alive = self._thread is not None and self._thread.is_alive()
        self._fail_all(EngineShutdownError("engine shut down"),
                       free_state=not alive)

    def drain(self, timeout_s: float = 5.0) -> bool:
        """Graceful wind-down (replica teardown path): stop admissions
        NOW — ``submit`` raises the retryable
        :class:`EngineShutdownError`, so routers re-pick another replica
        — fail queued-but-unstarted requests the same way (they have no
        delivered state; the retry is a fresh stream), let RUNNING lanes
        finish, and fail stragglers retryably at the deadline (clients
        resume elsewhere via ``resume_from``). Returns True when every
        lane finished inside the budget. The driver keeps running — the
        caller tears the replica down afterwards."""
        with self._admit_lock:
            self._draining = True
        deadline = time.monotonic() + max(float(timeout_s), 0.0)
        # rtsan RS104 audit (ISSUE 13): a 10 ms poll, NOT a condition —
        # the state it watches (_state/_pending) is driver-thread-owned
        # by contract, so a condition here would need the driver to
        # notify under a lock it deliberately never takes on its hot
        # loop. Deadline-bounded, and no lock is held across the sleep.
        while time.monotonic() < deadline:
            if not any(s is not None for s in self._state) \
                    and not self._queue.qsize() and not self._pending:
                return True
            time.sleep(0.01)
        alive = self._thread is not None and self._thread.is_alive()
        self._fail_all(
            EngineShutdownError(
                f"engine drained with lanes still running after "
                f"{timeout_s:.1f}s; resubmit to resume"),
            free_state=not alive)
        return False

    def supervise(self) -> bool:
        """Driver health verdict, with a one-shot recovery: True while
        the driver is alive and beating (or deliberately stopped); on
        the FIRST death/wedge, fail current lanes with the retryable
        :class:`EngineRestartError` (clients resume on another replica),
        restart the driver, and still report True — the replica stays.
        A second death/wedge reports False, escalating to
        controller-driven replica replacement. Called from the replica's
        ``check_health``; safe from any thread."""
        with self._supervise_lock:
            t = self._thread
            if t is None or self._shutdown:
                # Never started (auto_start=False) or deliberately shut
                # down: not a health signal.
                return True
            alive = t.is_alive()
            beat_age = time.monotonic() - self._beat
            wedged = alive and beat_age > self.wedge_timeout_s
            if alive and not wedged:
                return True
            with self._stats_lock:
                restarts = self._stats["driver_restarts"]
            if restarts >= self.max_driver_restarts:
                return False
            self._restart_driver(
                f"driver wedged (no heartbeat for {beat_age:.1f}s)"
                if wedged else "driver thread died")
            return True

    def _restart_driver(self, reason: str):
        """Supervisor recovery: retire the current driver epoch (a
        wedged thread that later wakes drops its results at the epoch
        guards), fail its lanes retryably, rebuild EVERY pool structure
        fresh — the old thread may still hold the old ones mid-dispatch
        — and start a new driver."""
        exc = EngineRestartError(
            f"engine driver restarted ({reason}); resubmit to resume")
        old_stop = self._stop
        old_stop.set()            # the old thread exits when it wakes
        with self._fail_lock:
            # Lanes error retryably; state/pages are NOT freed into the
            # old structures (the wedged thread may still be touching
            # them) — the rebuild below replaces them wholesale.
            self._fail_all_locked(exc, free_state=False)
            self._epoch += 1
            # The rebuild holds _admit_lock too (lock order: fail →
            # admit, same as _fail_all_locked): ensure_paging reads and
            # swaps the pool structures under _admit_lock, and a config
            # push racing this restart must see either the old pool or
            # the new one — never a half-built mix.
            with self._admit_lock:
                self._build_pool(self.paged, self.page_size or 16,
                                 self.n_pages, self._prefix is not None)
                if self._drafter is not None:
                    # The pool was rebuilt from scratch and every lane
                    # failed; per-slot drafter state must follow.
                    self._drafter.reset()
                self._state = [None] * self.slots
                self._token = np.zeros((self.slots,), np.int32)
                self._rngs = np.zeros((self.slots, 2), np.uint32)
                self._pending = collections.deque()
                self._queue = queue.SimpleQueue()
        self._count(driver_restarts=1)
        from .._private.metrics import serve_metrics
        serve_metrics()["engine_driver_restarts"].inc(
            labels={"deployment": self.deployment})
        _events.emit("engine.driver_restart", epoch=self._epoch,
                     deployment=self.deployment, reason=reason)
        self._thread = None
        self.start()

    def inject_fault(self, kind: str = "driver_die", at_tokens: int = 0,
                     wedge_s: float = 0.0):
        """Arm ONE chaos fault on the driver (testing only), triggered
        at the next loop boundary once ``at_tokens`` tokens have been
        delivered:

        - ``kind="driver_die"``: the driver thread raises — lanes fail
          with the retryable :class:`EngineRestartError`, clients resume
          elsewhere, and :meth:`supervise` restarts the driver once.
        - ``kind="driver_wedge"`` (with ``wedge_s``): the driver stalls
          without heartbeating, simulating a stuck dispatch; supervise
          detects the stale beat and recovers as above.
        - ``kind="kill_process"``: hard ``os._exit`` — the whole replica
          worker dies mid-stream, exercising the actor-death retry path.
        - ``kind="driver_slow"`` (with ``wedge_s``): a PERSISTENT
          per-loop stall of ``wedge_s`` seconds (heartbeat still beats)
          — simulates a heavily loaded device so chaos tests can
          interleave kills with a stream that is reliably mid-flight.
        """
        if kind not in ("driver_die", "driver_wedge", "kill_process",
                        "driver_slow"):
            raise ValueError(f"unknown fault kind {kind!r}")
        if kind == "driver_slow":
            self._throttle_s = float(wedge_s)
            return
        self._fault = {"kind": kind, "at_tokens": int(at_tokens),
                       "wedge_s": float(wedge_s)}

    def _check_fault(self):
        """Driver-loop fault point (no-op unless armed; one-shot except
        the persistent ``driver_slow`` throttle)."""
        throttle = getattr(self, "_throttle_s", 0.0)
        if throttle:
            time.sleep(throttle)
        f = self._fault
        if f is None:
            return
        with self._stats_lock:
            toks = self._stats["tokens"]
        if toks < f["at_tokens"]:
            return
        self._fault = None
        if f["kind"] == "driver_wedge":
            # Stall WITHOUT beating: supervise() sees a live thread with
            # a stale heartbeat — the wedge signature.
            time.sleep(f["wedge_s"])
        elif f["kind"] == "kill_process":
            os._exit(43)
        else:
            raise RuntimeError(
                f"injected engine driver death at {toks} tokens")

    def stats(self) -> dict:
        with self._stats_lock:
            out = dict(self._stats)
        out["active_slots"] = sum(s is not None for s in self._state)
        out["slots"] = self.slots
        out["queue_depth"] = out["queued"] = self.queue_depth()
        d = max(out["dispatches"], 1)
        out["avg_occupancy"] = out.pop("occupancy_sum") / d
        out["dispatches_per_token"] = (
            (out["dispatches"] + out["prefills"]) / max(out["tokens"], 1))
        out["paged"] = self.paged
        out["deployment"] = self.deployment
        out["tp"] = self.tp
        sp_r = out.pop("spec_rounds")
        sp_p = out.pop("spec_proposed")
        sp_a = out.pop("spec_accepted")
        sp_f = out.pop("spec_fallback_rounds")
        sp_l = out.pop("spec_lanes")
        if self._drafter is not None:
            out["spec"] = {
                "drafter": self._drafter.name,
                "draft_k": self.draft_k,
                "threshold": self.spec_threshold,
                "rounds": sp_r, "proposed": sp_p, "accepted": sp_a,
                "lanes": sp_l, "fallback_rounds": sp_f,
                "acceptance_rate": sp_a / max(sp_p, 1),
                # Per LANE per verify forward (the literature's
                # numbers): a lane commits its accepted prefix PLUS
                # the correction/bonus token every round it verifies.
                "mean_accept_len": sp_a / max(sp_l, 1),
                "accepted_per_forward": (sp_a + sp_l) / max(sp_l, 1),
            }
        # ---- disaggregation (ISSUE 14): always surfaced — a zero
        # block on a colocated engine is itself the signal that no
        # handoffs happened.
        out["role"] = self.role
        ls = self._leases.stats()
        out["handoff"] = {
            "exported": out.pop("handoffs_exported"),
            "imported": out.pop("handoffs_imported"),
            "import_fallbacks": out.pop("handoff_import_fallbacks"),
            "ship_bytes": out.pop("handoff_ship_bytes"),
            "leases_outstanding": ls["outstanding"],
            "leases_claimed": ls["claimed"],
            "leases_reclaimed": ls["reclaimed"],
        }
        t = self._thread
        out["driver_alive"] = bool(t is not None and t.is_alive())
        out["heartbeat_age_s"] = round(time.monotonic() - self._beat, 3)
        out["draining"] = self._draining
        # Flight-recorder health (ISSUE 19): ring fill fraction and
        # per-kind rate-cap drops for THIS process's recorder — rides
        # the replica metrics pull up into serve.status().
        out["events"] = _events.stats()
        # Runtime-sanitizer block (ISSUE 13): only when tools/rtsan is
        # already loaded AND active in this process — checked via
        # sys.modules so ray_tpu never imports the analyzer tree into
        # workers on its own (same boundary as the rtlint metrics
        # lint). Chaos benchmarks assert findings == 0 here.
        import sys as _sys
        _rtsan = _sys.modules.get("tools.rtsan")
        if _rtsan is not None and _rtsan.is_active():
            out["sanitizer"] = _rtsan.stats_block("serve/")
        if self.paged:
            out["page_size"] = self.page_size
            out["n_pages"] = self.n_pages
            out["pages_free"] = self._pool.available()
            out["pages_used"] = self.n_pages - self._pool.available()
            out["parked_slots"] = sum(
                s is not None and s.parked for s in self._state)
            if self._prefix is not None:
                out["prefix_cache_entries"] = len(self._prefix)
                out["prefix_evictions"] = self._prefix.evictions
            out["attn_kernel"] = self.attn_kernel
            out["kv_dtype"] = self.kv_dtype
            out["kv_bytes_per_token"] = self._gd.kv_bytes_per_page(
                self.cfg, self.page_size, self.kv_dtype) / self.page_size
        else:
            for k in ("prefix_hits", "prefix_tokens_reused",
                      "cow_copies", "admissions_deferred", "lane_parks",
                      "preempted", "attn_kernel_dispatches"):
                out.pop(k, None)
        return out

    def _count(self, **deltas):
        with self._stats_lock:
            for k, v in deltas.items():
                self._stats[k] += v

    # ---------------------------------------------------------- driver loop
    # THE driver loop: everything it calls below dispatches against
    # pool structures only this thread (or a supervisor that already
    # fenced it off by epoch) may touch. entry=driver: the thread that
    # enters _run IS the driver — rtsan (tools/rtsan) registers it here
    # and asserts every other owner=driver method runs on it (a
    # supervisor restart re-registers automatically on the new thread's
    # first loop).
    # rtlint: owner=driver entry=driver
    def _run(self, stop: threading.Event, epoch: int):
        try:
            while not stop.is_set():
                # Heartbeat BEFORE any work: supervise() reads its age
                # to tell a wedged dispatch from a live idle loop.
                self._beat = time.monotonic()
                self._check_fault()
                if stop.is_set():
                    # Woke from a wedge (fault sleep / stuck dispatch)
                    # to find the supervisor restarted past this run:
                    # exit before touching the rebuilt structures.
                    break
                self._admit_pending(epoch)
                self._observe_queue_depth()
                self._sweep_leases()
                if not any(s is not None for s in self._state):
                    if self._pending:
                        # Deferred head with an empty pool and ZERO
                        # running lanes cannot happen (n_pages holds a
                        # full max_len sequence and the prefix cache
                        # evicts first) — but never busy-spin on it.
                        time.sleep(0.001)
                        continue
                    # Idle: block briefly for the next arrival instead
                    # of spinning; the timeout bounds shutdown latency.
                    try:
                        self._pending.append(self._queue.get(timeout=0.05))
                    except queue.Empty:
                        continue
                    continue  # boundary: admission pass first
                if self._drafter is not None:
                    self._dispatch_spec(epoch)
                else:
                    self._dispatch_chunk(epoch)
            self._fail_all(EngineShutdownError("engine shut down"),
                           epoch=epoch)
        except BaseException as e:  # noqa: BLE001 - driver died: fan out
            # An unexpected driver death is RECOVERABLE for the lanes —
            # their streams replay deterministically elsewhere — so they
            # fail with the retryable restart error, not the raw cause.
            if not isinstance(e, EngineShutdownError):
                exc: BaseException = EngineRestartError(
                    f"engine driver died: {e!r}; resubmit to resume")
                exc.__cause__ = e
            else:
                exc = e
            self._fail_all(exc, epoch=epoch)
            raise

    def _fail_all(self, exc: BaseException, free_state: bool = True,
                  epoch: Optional[int] = None):
        """Fail every queued / in-flight lane with ``exc``.

        ``free_state=False`` (shutdown racing a still-alive driver)
        only PUTS errors — slot state, the pending deque, and the page
        pool stay driver-owned, so refcounts drop exactly once when the
        driver's own exit path runs this with ``free_state=True``.
        ``epoch`` (driver exit paths) makes the call a no-op when the
        supervisor already retired that driver's run — a late exit must
        not fail the RESTARTED engine's lanes. Double error puts on a
        lane are inert."""
        # Serialized: shutdown() calls this unconditionally (covering a
        # dead/never-started driver) and may race the dying driver's own
        # exit path — page refcounts must only drop once per slot.
        with self._fail_lock:
            if epoch is not None and epoch != self._epoch:
                return           # stale driver: its lanes already moved
            self._fail_all_locked(exc, free_state)

    def _fail_all_locked(self, exc: BaseException, free_state: bool):
        with self._admit_lock:
            self._draining = True    # no put can land past this point
        for i, st in enumerate(self._state):
            if st is not None:
                st.lane.q.put(("err", exc))
                if free_state:
                    # Ownership transferred: free_state=True means the
                    # driver is confirmed dead (_fail_all's contract),
                    # so the failing thread IS the owner — the same
                    # dead-owner rebind rtsan's RS103 grants at runtime.
                    # rtlint: disable=RT110 ownership transfer (above)
                    self._free_slot(i)
        if free_state:
            while self._pending:
                self._pending.popleft().lane.q.put(("err", exc))
        else:
            for req in list(self._pending):
                req.lane.q.put(("err", exc))
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                return
            req.lane.q.put(("err", exc))

    # Ownership transfers to the failing thread only once the driver is
    # confirmed dead — see _fail_all's free_state contract.
    # rtlint: owner=driver
    def _free_slot(self, i: int):
        """Release slot i: page references drop (pages whose last ref
        was this slot return to the free list; prefix-cached pages stay
        resident) and the page-table row resets to sentinel."""
        st = self._state[i]
        if st is not None and st.pages:
            self._pool.unref(st.pages)
            self._pt[i, :] = self._gd.PT_SENTINEL
        if st is not None and self._drafter is not None:
            self._drafter.free(i)
        self._state[i] = None

    def _alloc_pages(self, n: int, pool: Optional[_PagePool] = None,
                     prefix: Optional[_PrefixCache] = None
                     ) -> Optional[List[int]]:
        """Allocate n pages, evicting LRU prefix-cache entries while
        short. None = genuinely out (every page pinned by live lanes) —
        the caller defers or parks, never clamps. ``pool``/``prefix``
        let an in-flight admission keep ONE consistent snapshot across
        a supervisor restart (default: the engine's current ones)."""
        pool = self._pool if pool is None else pool
        prefix = self._prefix if prefix is None else prefix
        while pool.available() < n:
            if prefix is None or not prefix.evict_lru():
                return None
            _driver_emit("engine.page_evict", epoch=self._epoch,
                         wanted=n, free=pool.available())
        pages = pool.alloc(n)
        if pages is not None:
            _driver_emit("engine.page_alloc", epoch=self._epoch,
                         n=n, free=pool.available())
        return pages

    def _observe_pages(self, sm=None):
        if not self.paged:
            return
        if sm is None:
            from .._private.metrics import serve_metrics
            sm = serve_metrics()
        free = self._pool.available()
        labels = {"deployment": self.deployment}
        sm["engine_pages_free"].set(free, labels=labels)
        sm["engine_pages_used"].set(self.n_pages - free, labels=labels)
        sm["engine_kv_bytes_per_token"].set(
            self._gd.kv_bytes_per_page(self.cfg, self.page_size,
                                       self.kv_dtype) / self.page_size,
            labels=labels)

    def _sweep_leases(self):  # rtlint: owner=driver
        """Reclaim expired handoff leases once per driver loop
        (ISSUE 14): dropping each orphan's pin frees the shipped pages
        on the object plane, so a decode replica (or router) that died
        between grant and claim can never pin the pool."""
        if not len(self._leases):
            return
        n = self._leases.sweep()
        if n:
            from .._private.metrics import serve_metrics

            serve_metrics()["handoff_leases_reclaimed"].inc(
                n, labels={"deployment": self.deployment})
            _driver_emit("handoff.reclaim", count=n,
                         epoch=self._epoch,
                         outstanding=len(self._leases))

    def _observe_queue_depth(self):  # rtlint: owner=driver
        """Export the admission backlog once per driver loop (gauge
        semantics want one writer: the driver, same as the page
        gauges)."""
        from .._private.metrics import serve_metrics

        serve_metrics()["engine_queue_depth"].set(
            self.queue_depth(), labels={"deployment": self.deployment})

    def _admit_pending(self, epoch: int = -1):  # rtlint: owner=driver
        """Chunk-boundary admission: fill every free slot in FIFO order.
        Expired / abandoned requests are failed out without spending a
        prefill; a paged admission that cannot get pages DEFERS — it
        stays at the queue head (order preserved) and retries next
        boundary, by which time a lane may have freed pages."""
        if epoch >= 0 and epoch != self._epoch:
            # Stale driver (the supervisor restarted past it while it
            # was blocked mid-iteration): every structure it can see is
            # the NEW driver's — touching them would race the live
            # admission pass or discard its requests.
            return
        while True:
            try:
                self._pending.append(self._queue.get_nowait())
            except queue.Empty:
                break
        if self._draining and self._pending:
            # Draining: queued-but-unstarted requests fail retryably NOW
            # (no delivered state — the retry is a fresh stream on
            # another replica) while running lanes ride to completion.
            exc = EngineShutdownError(
                "engine draining; resubmit on another replica")
            while self._pending:
                self._pending.popleft().lane.q.put(("err", exc))
            return
        # Cull dead entries EVERYWHERE in the deque first — deferral
        # under page pressure must not delay a deadline error that
        # costs nothing to deliver. In-place rotation keeps FIFO order.
        for _ in range(len(self._pending)):
            req = self._pending.popleft()
            if req.lane.closed:
                self._count(abandoned=1)
                continue
            if deadline_expired(req.deadline_s):
                from .._private.metrics import serve_metrics
                self._count(expired=1)
                serve_metrics()["requests_expired"].inc(
                    labels={"where": "engine",
                            "deployment": self.deployment})
                req.lane.q.put(("err", RequestDeadlineExceeded(
                    "request expired while queued for engine admission")))
                continue
            self._pending.append(req)
        if any(s is not None and s.parked for s in self._state):
            # Page pressure: freed pages must reach the (older) parked
            # lanes before new admissions may take them — otherwise a
            # preempted lane's pages would be re-pinned immediately and
            # the pool would thrash prefills instead of progressing.
            return
        while self._pending and any(s is None for s in self._state):
            admitted = self._admit_one(self._pending[0], epoch)
            if epoch >= 0 and epoch != self._epoch:
                # The supervisor restarted past this driver WHILE its
                # prefill was blocked on the device: the deque now holds
                # the new driver's requests — popping would silently
                # discard one (its lane would hang to its deadline).
                return
            if not admitted:
                self._count(admissions_deferred=1)
                return               # out of pages: keep FIFO, back off
            self._pending.popleft()

    # rtlint: owner=driver
    def _admit_one(self, req: _EngineRequest, epoch: int = -1) -> bool:
        """Prefill ``req`` into a free slot; returns False to defer
        (paged mode, no pages). Lane-closed/expired checks happen in
        :meth:`_admit_pending` before any resources are taken. A stale
        driver (the supervisor restarted past it while its prefill was
        stuck on the device) drops the result at the epoch guard instead
        of writing into the rebuilt pool."""
        from .._private.metrics import serve_metrics

        slot = next(i for i, s in enumerate(self._state) if s is None)
        import jax

        P = req.prompt.shape[0]
        sm = serve_metrics()
        if req.handoff is not None:
            return self._admit_import(req, slot, sm, epoch)
        if self.paged:
            admitted = self._prefill_paged(req, slot, P, sm, jax, epoch)
            if admitted is None:
                return False
            first, pages, t_admit = admitted
        else:
            t_admit = time.time()
            padded = np.zeros((1, req.bucket), np.int32)
            padded[0, :P] = req.prompt
            tok, cache, key = self._prefill(
                self._params_dev, self._cache, padded, np.int32(P),
                np.int32(slot), jax.random.PRNGKey(req.seed))
            # One transfer per admission — THE TTFT point.
            # rtlint: sync-ok=ttft first token streams from the host
            first = int(np.asarray(tok))
            if epoch >= 0 and epoch != self._epoch:
                return True          # stale driver: drop on the floor
            self._cache = cache
            # Host mirror of the slot's PRNG lane (tiny [2] uint32).
            # rtlint: sync-ok=prng-mirror re-uploaded per dispatch
            self._rngs[slot] = np.asarray(key)
            pages = []
        sm["engine_admission_wait"].observe(
            max(t_admit - req.enq_t, 0.0),
            labels={"deployment": self.deployment})
        if req.trace_ctx is not None:
            tracing.record_span("engine.admission", req.enq_t, t_admit,
                                parent_ctx=req.trace_ctx, slot=slot,
                                deployment=self.deployment)
        self._count(prefills=1,
                    admitted=1 if (req.skip == 0 and not req.export)
                    else 0)
        self._token[slot] = first
        if req.export:
            return self._finish_export(req, slot, P, pages, first, sm)
        return self._enter_steady_state(req, slot, first, P, pages, sm)

    # rtlint: owner=driver
    def _enter_steady_state(self, req: _EngineRequest, slot: int,
                            first: int, P: int, pages: List[int],
                            sm) -> bool:
        """Shared admission tail for every path that lands a first
        token in a slot (local prefill AND handoff import): deliver or
        replay-suppress the first token, close out single-token/EOS
        requests, otherwise install the slot's steady state and seed
        the drafter. The replay bookkeeping (``emitted``/``skip``)
        must stay bit-equal between the two entry paths or a resumed
        stream diverges by one token."""
        _driver_emit("engine.admit", request=req.req_id, slot=slot,
                     epoch=self._epoch, prompt_len=P,
                     max_new=req.max_new, resume_from=req.skip)
        skip = req.skip
        if skip > 0:
            skip -= 1            # replay: the first token was delivered
        else:                    # before the preemption/failover
            self._count(tokens=1)
            sm["engine_tokens"].inc(
                labels={"deployment": self.deployment})
            req.lane.q.put(("item", np.asarray([first], np.int32)))
        if req.max_new <= 1 or (self.eos_token >= 0
                                and first == self.eos_token):
            req.lane.q.put((_STREAM_END, None))
            self._count(completed=1)
            if pages:
                self._pool.unref(pages)
                self._pt[slot, :] = self._gd.PT_SENTINEL
            self._observe_pages(sm)
            return True
        self._state[slot] = _Slot(
            lane=req.lane, remaining=req.max_new - 1,
            deadline_s=req.deadline_s, trace_ctx=req.trace_ctx,
            req=req, emitted=1 if req.skip == 0 else req.skip,
            pos=P, pages=pages, skip=skip)
        if self._drafter is not None:
            # Deterministic per-slot drafter state from the prompt +
            # first token — a resume_from replay rebuilds it bit-equal.
            self._drafter.admit(slot, req.prompt, first)
        self._observe_pages(sm)
        return True

    # rtlint: owner=driver
    def _prefill_paged(self, req: _EngineRequest, slot: int, P: int,
                       sm, jax, epoch: int = -1
                       ) -> Optional[Tuple[int, List[int], float]]:
        """Paged admission: map the cached prefix (refcounted, COW fork
        if it ends mid-page), allocate fresh pages for the suffix,
        prefill ONLY the suffix, then register the prompt's pages in the
        prefix cache. Returns None (nothing taken) when pages are
        unavailable even after LRU eviction — or when a supervisor
        restart retired this driver's epoch while its prefill ran (the
        stale result must not touch the rebuilt pool)."""
        gd = self._gd
        ps = self.page_size
        # ONE pool/prefix snapshot for the whole admission: a supervisor
        # restart swaps self._pool wholesale, and page accounting split
        # across two pool objects would corrupt both free lists.
        pool = self._pool
        prefix = self._prefix
        hist, shared_pages = (0, [])
        if prefix is not None:
            hist, shared_pages = prefix.lookup(req.prompt)
        shared_full = hist // ps
        partial = hist % ps
        cow_src = shared_pages[shared_full] if partial else \
            gd.PT_SENTINEL
        shared = shared_pages[:shared_full]
        # Pin everything we read BEFORE eviction-driven allocation can
        # free it from under us.
        pool.ref(shared)
        if partial:
            pool.ref([cow_src])
        n_fresh = -(-P // ps) - shared_full
        fresh = self._alloc_pages(n_fresh, pool, prefix)
        if fresh is None:
            pool.unref(shared)
            if partial:
                pool.unref([cow_src])
            return None
        pages = shared + fresh
        t_admit = time.time()
        suffix = req.prompt[hist:]
        sl = P - hist
        bucket = next(b for b in self.prompt_buckets if b >= sl)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :sl] = suffix
        pt_row = np.full((self.max_pages,), gd.PT_SENTINEL, np.int32)
        pt_row[:len(pages)] = pages
        self._pt[slot] = pt_row
        tok, cache, key = self._prefill(
            self._params_dev, self._cache, padded, np.int32(sl),
            np.int32(hist), pt_row, np.int32(cow_src), np.int32(slot),
            jax.random.PRNGKey(req.seed))
        # One transfer per admission — THE TTFT point.
        # rtlint: sync-ok=ttft first token streams from the host
        first = int(np.asarray(tok))
        if epoch >= 0 and epoch != self._epoch:
            # Stale driver: drop the result AND hand back every page
            # this admission took — against the SAME pool snapshot, so
            # the accounting stays balanced whether the restart replaced
            # the pool before or during the admission (a leak here would
            # shrink the free list forever).
            pool.unref(pages)
            if partial:
                pool.unref([cow_src])
            return None
        self._cache = cache
        # Host mirror of the slot's PRNG lane (tiny [2] uint32).
        # rtlint: sync-ok=prng-mirror re-uploaded per dispatch
        self._rngs[slot] = np.asarray(key)
        if partial:
            # The fork read src synchronously inside the dispatch above;
            # its pin is no longer needed.
            pool.unref([cow_src])
            self._count(cow_copies=1)
            sm["engine_cow_copies"].inc(
                labels={"deployment": self.deployment})
        if hist:
            self._count(prefix_hits=1, prefix_tokens_reused=hist)
            sm["engine_prefix_hits"].inc(
                labels={"deployment": self.deployment})
        if prefix is not None:
            prefix.insert(req.prompt, pages)
        return first, pages, t_admit

    # rtlint: owner=driver
    def _finish_export(self, req: _EngineRequest, slot: int, P: int,
                       pages: List[int], first: int, sm) -> bool:
        """Handoff export tail (ISSUE 14), run right after the prefill
        landed in the transient slot: extract the slot's K/V into ship
        order, trim to the true prompt length on the host, free the
        slot's pages, grant the lease, and deliver the descriptor on
        the request's lane. The slot never enters steady state — a
        prefill-role engine's pool is a staging area, not a residence.
        """
        from . import handoff as _ho

        quant = self.paged and self.kv_dtype == "int8"
        ks = vs = None
        if quant:
            k_dev, v_dev, ks_dev, vs_dev = self._export(
                self._cache, self._pt[slot])
        elif self.paged:
            k_dev, v_dev = self._export(self._cache, self._pt[slot])
        else:
            k_dev, v_dev = self._export(self._cache, np.int32(slot))
        # Trim to pos BEFORE hashing/shipping: positions past P hold
        # pad/stale garbage the mask never read — shipping them would
        # make the digest depend on pool history.
        # The export IS the handoff payload: the bytes must reach the
        # host to digest and ship — one round-trip per export.
        # rtlint: sync-ok=ship handoff payload leaves through the host
        k = np.asarray(k_dev)[:, :P].copy()
        # rtlint: sync-ok=ship second half of the same payload
        v = np.asarray(v_dev)[:, :P].copy()
        if quant:
            # int8 ships CODES (trimmed like fp — the merge writes
            # canonical zeros past pos, so page bytes are a pure
            # function of held tokens) plus the per-page scales for
            # the covering pages. The digest covers both.
            n_cover = -(-P // self.page_size)
            # rtlint: sync-ok=ship per-page K scales ride the payload
            ks = np.asarray(ks_dev)[:, :n_cover].copy()
            # rtlint: sync-ok=ship per-page V scales ride the payload
            vs = np.asarray(vs_dev)[:, :n_cover].copy()
        rng = np.asarray(self._rngs[slot], np.uint32).copy()
        if pages:
            self._pool.unref(pages)
            self._pt[slot, :] = self._gd.PT_SENTINEL
        payload = _ho.build_payload(k=k, v=v, prompt=req.prompt, pos=P,
                                    first=first, rng=rng, seed=req.seed,
                                    max_new=req.max_new, ks=ks, vs=vs,
                                    kv_dtype=self.kv_dtype if quant
                                    else None,
                                    page_size=self.page_size if quant
                                    else None)
        fields, nbytes = _ho.ship_payload(payload)
        lease_id, expires = self._leases.grant(
            epoch=self._epoch, pin=fields.get("ref"), nbytes=nbytes,
            ttl_s=req.ttl_s or None)
        desc = dict(fields)
        desc.update({
            "lease_id": lease_id, "epoch": self._epoch,
            "expires_at": expires, "digest": payload["digest"],
            "prompt": req.prompt, "pos": P, "first": first,
            "seed": req.seed, "max_new": req.max_new,
            "created_t": time.time(), "nbytes": nbytes,
            "node_id": _node_id(), "deployment": self.deployment})
        # tokens counts the sampled first token, so the chaos fault
        # points (kill/throttle at token N) work on prefill engines.
        self._count(handoffs_exported=1, handoff_ship_bytes=nbytes,
                    tokens=1)
        _driver_emit("handoff.grant", request=req.req_id,
                     lease=lease_id, epoch=self._epoch, nbytes=nbytes,
                     ttl_s=req.ttl_s or self._leases.ttl_s)
        _driver_emit("engine.export", request=req.req_id, slot=slot,
                     epoch=self._epoch, prompt_len=P, nbytes=nbytes)
        sm["kv_ship_bytes"].inc(
            nbytes, labels={"deployment": self.deployment})
        req.lane.q.put(("item", desc))
        req.lane.q.put((_STREAM_END, None))
        self._observe_pages(sm)
        return True

    # rtlint: owner=driver
    def _admit_import(self, req: _EngineRequest, slot: int, sm,
                      epoch: int = -1) -> bool:
        """Handoff import admission (ISSUE 14): scatter the verified
        ship buffer into a free slot (flat) or freshly mapped pages
        (paged), restore the slot's PRNG lane and fed token, and enter
        steady-state decode exactly where the prefill engine stopped.
        Returns False to defer (paged mode, no pages). A recompute
        preemption re-enqueues the request WITH its payload, so the
        replay is a re-import, not a re-prefill."""
        payload = req.handoff["payload"]
        P = int(payload["pos"])
        gd = self._gd
        L = self.cfg.n_layer
        H, hd = self.cfg.n_head, self.cfg.head_dim
        dt = payload["k"].dtype
        t_admit = time.time()
        if self.paged:
            ps = self.page_size
            # ONE pool snapshot for the whole admission (see
            # _prefill_paged): a supervisor restart must never split
            # page accounting across two pool objects.
            pool = self._pool
            prefix = self._prefix
            n_cover = -(-P // ps)
            pages = self._alloc_pages(n_cover, pool, prefix)
            if pages is None:
                return False          # out of pages: defer, keep FIFO
            pt_row = np.full((self.max_pages,), gd.PT_SENTINEL,
                             np.int32)
            pt_row[:len(pages)] = pages
            self._pt[slot] = pt_row
            k_pad = np.zeros((L, self.max_pages * ps, H, hd), dt)
            v_pad = np.zeros((L, self.max_pages * ps, H, hd), dt)
            k_pad[:, :P] = payload["k"]
            v_pad[:, :P] = payload["v"]
            if self.kv_dtype == "int8":
                # Quantized handoff: the codes pad/reshape exactly like
                # fp K/V; the per-page scales pad to the full table
                # width and scatter under the same page mask.
                ks_pad = np.zeros((L, self.max_pages, H), np.float32)
                vs_pad = np.zeros((L, self.max_pages, H), np.float32)
                ks_pad[:, :n_cover] = payload["ks"]
                vs_pad[:, :n_cover] = payload["vs"]
                cache = self._import(
                    self._cache,
                    k_pad.reshape(L, self.max_pages, ps, H, hd),
                    v_pad.reshape(L, self.max_pages, ps, H, hd),
                    ks_pad, vs_pad,
                    pt_row, np.int32(slot), np.int32(P))
            else:
                cache = self._import(
                    self._cache,
                    k_pad.reshape(L, self.max_pages, ps, H, hd),
                    v_pad.reshape(L, self.max_pages, ps, H, hd),
                    pt_row, np.int32(slot), np.int32(P))
            if epoch >= 0 and epoch != self._epoch:
                pool.unref(pages)     # stale driver: hand pages back
                return True
            # Shipped pages cover the WHOLE prompt: register them so
            # later local admissions of the same prompt prefix map the
            # imported pages instead of re-prefilling.
            if prefix is not None and P == req.prompt.shape[0]:
                prefix.insert(req.prompt, pages)
        else:
            pages = []
            k_pad = np.zeros((L, self.max_len, H, hd), dt)
            v_pad = np.zeros((L, self.max_len, H, hd), dt)
            k_pad[:, :P] = payload["k"]
            v_pad[:, :P] = payload["v"]
            cache = self._import(self._cache, k_pad, v_pad,
                                 np.int32(slot), np.int32(P))
            if epoch >= 0 and epoch != self._epoch:
                return True           # stale driver: drop on the floor
        self._cache = cache
        first = int(payload["first"])
        self._token[slot] = first
        self._rngs[slot] = np.asarray(payload["rng"], np.uint32)
        sm["engine_admission_wait"].observe(
            max(t_admit - req.enq_t, 0.0),
            labels={"deployment": self.deployment})
        created = req.handoff.get("created_t")
        if created:
            # Export stamp -> successful import: THE handoff latency.
            # Wall-clock across processes, like the deadline it rides
            # with.
            sm["kv_handoff"].observe(
                max(time.time() - float(created), 0.0),
                labels={"deployment": self.deployment})
        if req.trace_ctx is not None:
            tracing.record_span("engine.admission", req.enq_t, t_admit,
                                parent_ctx=req.trace_ctx, slot=slot,
                                imported=True,
                                deployment=self.deployment)
        self._count(handoffs_imported=1,
                    admitted=1 if req.skip == 0 else 0)
        _driver_emit("engine.import", request=req.req_id, slot=slot,
                     epoch=self._epoch, pos=P)
        return self._enter_steady_state(req, slot, first, P, pages, sm)

    def _cover_pages(self) -> bool:  # rtlint: owner=driver
        """Allocate-on-advance (paged mode, chunk boundary): every
        occupied slot must have pages mapped through the positions this
        chunk will write (``pos + min(chunk, remaining)``). A slot that
        cannot be covered PARKS — it keeps its state and pages but sits
        out the dispatch mask until a page frees. Returns True if at
        least one lane can run; False means every occupied slot was
        parked and the youngest lane has been preempted by recompute
        to break the deadlock."""
        ps = self.page_size
        # Cull dead PARKED lanes first: a parked slot sits out the
        # dispatch mask, so it never reaches the post-dispatch
        # closed/deadline checks — a consumer that walked away (or a
        # deadline that already passed) would otherwise pin its pages
        # forever and could force recompute-preemption of a healthy
        # lane. Freed pages are immediately available to the coverage
        # loop below.
        culled = False
        for i, st in enumerate(self._state):
            if st is None or not st.parked:
                continue
            if st.lane.closed:
                self._free_slot(i)
                self._count(abandoned=1)
                culled = True
            elif deadline_expired(st.deadline_s):
                from .._private.metrics import serve_metrics
                st.lane.q.put(("err", RequestDeadlineExceeded(
                    "request deadline passed while parked for pages")))
                self._free_slot(i)
                self._count(expired=1)
                serve_metrics()["requests_expired"].inc(
                    labels={"where": "engine",
                            "deployment": self.deployment})
                culled = True
        if culled:
            self._observe_pages()
            if not any(s is not None for s in self._state):
                return False         # nothing left to dispatch
        runnable = 0
        for i, st in enumerate(self._state):
            if st is None:
                continue
            if self._drafter is not None:
                # Verify writes K/V at pos..pos+draft_k (the fed token
                # plus every proposal); writes past the covered pages
                # drop, which is only safe for positions a CONTINUING
                # lane can never commit — i.e. beyond remaining. Under
                # adaptive speculation the slot may instead run a chunk
                # round this boundary, so cover the max of both modes.
                need = st.pos + max(
                    min(self.draft_k, st.remaining) + 1,
                    min(self.chunk, st.remaining))
            else:
                need = st.pos + min(self.chunk, st.remaining)
            while len(st.pages) * ps < need:
                got = self._alloc_pages(1)
                if got is None:
                    break
                self._pt[i, len(st.pages)] = got[0]
                st.pages.extend(got)
            short = len(st.pages) * ps < need
            if short and not st.parked:
                self._count(lane_parks=1)
            st.parked = short
            runnable += not short
        if runnable:
            return True
        # Deadlock: every occupied slot is parked and nothing will free
        # a page on its own. Preempt the youngest lane (least sunk
        # work) BY RECOMPUTE: free its pages, requeue its request at
        # the head, and let the replay suppress the already-delivered
        # tokens — the consumer sees a stall, never an error or a
        # duplicate. Each preemption strictly shrinks the lane set, and
        # one lane always fits (n_pages >= max_pages), so this
        # terminates.
        youngest = max(
            (i for i, s in enumerate(self._state) if s is not None),
            key=lambda i: self._state[i].admitted_t)
        st = self._state[youngest]
        req = st.req
        req.skip = st.emitted
        req.enq_t = time.time()
        self._free_slot(youngest)
        self._pending.appendleft(req)
        self._count(preempted=1)
        _driver_emit("engine.preempt", request=req.req_id,
                     slot=youngest, epoch=self._epoch,
                     delivered=st.emitted)
        self._observe_pages()
        return False

    # rtlint: owner=driver
    def _dispatch_chunk(self, epoch: int = -1, cover: bool = True):
        """ONE fused device dispatch decoding every active slot, then
        per-slot routing/trimming and boundary frees. A stale driver —
        one whose dispatch was stuck on the device while the supervisor
        restarted past it — drops the whole result at the post-dispatch
        epoch guard: its lanes were already failed retryably and the
        pool rebuilt.

        ``cover=False`` serves adaptive speculation: the spec
        dispatcher already ran the coverage pass for this boundary
        before deciding to fall back to a chunk round."""
        from .._private.metrics import serve_metrics

        if epoch >= 0 and epoch != self._epoch:
            # Stale driver: _cover_pages parks/preempts lanes — running
            # it against the NEW driver's pool would preempt a healthy
            # restarted lane.
            return
        if cover and self.paged and not self._cover_pages():
            return                    # re-run admission/coverage pass
        active = np.array([s is not None and not s.parked
                           for s in self._state], bool)
        n_active = int(active.sum())
        t0 = time.time()
        if self.paged:
            toks, cache, _done, rngs = self._step(
                self._params_dev, self._cache, self._token, self._rngs,
                active, self._pt)
        else:
            toks, cache, _done, rngs = self._step(
                self._params_dev, self._cache, self._token, self._rngs,
                active)
        # ONE transfer per fused k-step chunk — the engine's designed
        # streaming granularity.
        # rtlint: sync-ok=chunk-boundary one transfer per chunk
        toks_np = np.asarray(toks)
        # rtlint: sync-ok=chunk-boundary PRNG lanes ride the same sync
        rngs_np = np.asarray(rngs)
        t1 = time.time()
        if epoch >= 0 and epoch != self._epoch:
            return                    # stale driver: drop on the floor
        self._cache = cache
        sm = serve_metrics()
        sm["engine_slot_occupancy"].observe(
            n_active / self.slots, labels={"deployment": self.deployment})
        sm["engine_dispatches"].inc(
            labels={"deployment": self.deployment})
        self._count(dispatches=1, occupancy_sum=n_active / self.slots)
        # Rate-capped: under a dispatch-per-token storm the cap drops
        # the excess (counted) instead of flooding the ring.
        _driver_emit("engine.dispatch", epoch=self._epoch,
                     active=n_active, chunk=self.chunk,
                     dispatch_s=round(t1 - t0, 6))
        if self.tp > 1:
            # Post-mortem breadcrumb for sharded dispatch: which mesh
            # shape ran which compiled program. Same rate cap as
            # engine.dispatch — one pair per chunk boundary.
            _driver_emit("shard.dispatch", epoch=self._epoch,
                         mesh=[("tp", self.tp)],
                         program="chunk_paged" if self.paged
                         else "chunk")
        if self.paged and self.attn_kernel == "pallas":
            # One fused-kernel dispatch per chunk program launch (the
            # kernel runs k times per layer inside it).
            sm["engine_attn_kernel_dispatches"].inc(
                labels={"deployment": self.deployment})
            self._count(attn_kernel_dispatches=1)
        with self._stats_lock:
            self._stats["peak_active"] = max(self._stats["peak_active"],
                                             n_active)
        emitted = 0
        for i, st in enumerate(self._state):
            if st is None or st.parked:
                continue                     # parked: nothing advanced
            self._token[i] = toks_np[i, -1]
            self._rngs[i] = rngs_np[i]
            st.pos += self.chunk             # mirrors the device pos
            if st.lane.closed:               # consumer left: free now
                self._free_slot(i)
                self._count(abandoned=1)
                continue
            if deadline_expired(st.deadline_s):
                st.lane.q.put(("err", RequestDeadlineExceeded(
                    "request deadline passed mid-generation")))
                self._free_slot(i)
                self._count(expired=1)
                sm["requests_expired"].inc(
                    labels={"where": "engine",
                            "deployment": self.deployment})
                continue
            row = toks_np[i]
            j = min(self.chunk, st.remaining)
            finished = st.remaining <= self.chunk
            if self.eos_token >= 0:
                hits = np.flatnonzero(row[:j] == self.eos_token)
                if hits.size:                # free at the EOS, not the
                    j = int(hits[0]) + 1     # end of the gang batch
                    finished = True
            if st.trace_ctx is not None:
                tracing.record_span("decode.chunk", t0, t1,
                                    parent_ctx=st.trace_ctx, slot=i,
                                    active_slots=n_active, tokens=j,
                                    deployment=self.deployment)
            # Recompute replay: the first ``skip`` regenerated tokens
            # were already delivered before the preemption — suppress
            # them, stream only the new tail.
            cut = min(st.skip, j)
            st.skip -= cut
            if j > cut:
                st.lane.q.put(("item", row[cut:j].copy()))
                st.emitted += j - cut
                emitted += j - cut
            st.remaining -= j
            if finished:
                st.lane.q.put((_STREAM_END, None))
                self._free_slot(i)
                self._count(completed=1)
            elif self._drafter is not None:
                # Adaptive fallback round: keep the drafter's history
                # (and its self-assessment) current; -1 marks "nothing
                # was proposed this round".
                self._drafter.observe(i, row[:j], -1)
        if emitted:
            sm["engine_tokens"].inc(
                emitted, labels={"deployment": self.deployment})
            self._count(tokens=emitted)
        self._observe_pages(sm)

    def _dispatch_spec(self, epoch: int = -1):  # rtlint: owner=driver
        """Draft-k-verify-once twin of :meth:`_dispatch_chunk`
        (ISSUE 9): the drafter proposes ``draft_k`` tokens per active
        slot, ONE batched target forward verifies them all, and each
        slot advances by its OWN ``accepted + 1`` (the target's
        correction/bonus token rides along) — variable per-slot advance
        flowing through the same EOS/deadline/freeing/``resume_from``
        replay logic as the fixed-k path. A stale driver drops the
        whole result at the post-dispatch epoch guard.

        ``spec_threshold > 0`` makes speculation POOL-WIDE adaptive:
        the boundary verifies only when the drafters' mean
        self-assessed acceptance over the runnable lanes clears the
        threshold, and runs ONE plain chunk dispatch otherwise. The
        decision is all-or-nothing because the chunk program's cost is
        paid once for the whole pool — a boundary that dispatched both
        programs for a split pool would always commit fewer tokens per
        wall-second than chunking everyone. Greedy engines only (the
        constructor enforces it): the decision depends on pool
        composition, which is replay-safe only when sampling consumes
        no randomness."""
        from .._private.metrics import serve_metrics

        if epoch >= 0 and epoch != self._epoch:
            return
        if self.paged and not self._cover_pages():
            return                    # re-run admission/coverage pass
        active = np.array([s is not None and not s.parked
                           for s in self._state], bool)
        n_active = int(active.sum())
        if not n_active:
            return
        if self.spec_threshold > 0.0:
            ests = [self._drafter.estimate(i)
                    for i in range(self.slots) if active[i]]
            if not any(e is None for e in ests) \
                    and sum(ests) / n_active < self.spec_threshold:
                # Unpredictable pool: one chunk dispatch beats a verify
                # that would mostly commit correction tokens. The
                # drafter still observes (chunk path) so its estimate
                # recovers the moment streams turn repetitive.
                self._count(spec_fallback_rounds=1)
                self._dispatch_chunk(epoch, cover=False)
                return
        draft = self._drafter.propose(active, self._token)
        t0 = time.time()
        if self.paged:
            committed, n_acc, cache, rngs = self._verify(
                self._params_dev, self._cache, self._token, draft,
                self._rngs, active, self._pt)
        else:
            committed, n_acc, cache, rngs = self._verify(
                self._params_dev, self._cache, self._token, draft,
                self._rngs, active)
        # ONE transfer per verify round: committed tokens, accept
        # counts, and PRNG lanes come back together.
        # rtlint: sync-ok=verify-boundary one transfer per round
        com_np = np.asarray(committed)
        # rtlint: sync-ok=verify-boundary same round-trip
        acc_np = np.asarray(n_acc)
        # rtlint: sync-ok=verify-boundary same round-trip
        rngs_np = np.asarray(rngs)
        t1 = time.time()
        if epoch >= 0 and epoch != self._epoch:
            return                    # stale driver: drop on the floor
        self._cache = cache
        sm = serve_metrics()
        labels = {"deployment": self.deployment}
        sm["engine_slot_occupancy"].observe(n_active / self.slots,
                                            labels=labels)
        sm["engine_dispatches"].inc(labels=labels)
        accepted_total = int(acc_np[active].sum()) if n_active else 0
        sm["engine_spec_proposed"].inc(self.draft_k * n_active,
                                       labels=labels)
        if accepted_total:
            sm["engine_spec_accepted"].inc(accepted_total, labels=labels)
        self._count(dispatches=1, occupancy_sum=n_active / self.slots,
                    spec_rounds=1, spec_proposed=self.draft_k * n_active,
                    spec_accepted=accepted_total, spec_lanes=n_active)
        _driver_emit("engine.dispatch", epoch=self._epoch,
                     active=n_active, spec=True,
                     accepted=accepted_total)
        if self.tp > 1:
            _driver_emit("shard.dispatch", epoch=self._epoch,
                         mesh=[("tp", self.tp)],
                         program="verify_paged" if self.paged
                         else "verify")
        with self._stats_lock:
            self._stats["peak_active"] = max(self._stats["peak_active"],
                                             n_active)
        emitted = 0
        for i, st in enumerate(self._state):
            if st is None or st.parked or not active[i]:
                continue                     # parked or chunk-mode slot
            na = int(acc_np[i])
            adv = na + 1
            sm["engine_spec_accept_len"].observe(na, labels=labels)
            self._rngs[i] = rngs_np[i]
            st.pos += adv                    # mirrors the device pos
            if st.lane.closed:               # consumer left: free now
                self._free_slot(i)
                self._count(abandoned=1)
                continue
            if deadline_expired(st.deadline_s):
                st.lane.q.put(("err", RequestDeadlineExceeded(
                    "request deadline passed mid-generation")))
                self._free_slot(i)
                self._count(expired=1)
                sm["requests_expired"].inc(
                    labels={"where": "engine",
                            "deployment": self.deployment})
                continue
            row = com_np[i]
            j = min(adv, st.remaining)
            finished = st.remaining <= adv
            if self.eos_token >= 0:
                hits = np.flatnonzero(row[:j] == self.eos_token)
                if hits.size:                # free at the EOS
                    j = int(hits[0]) + 1
                    finished = True
            self._token[i] = row[j - 1]      # last DELIVERED token
            if st.trace_ctx is not None:
                tracing.record_span("decode.chunk", t0, t1,
                                    parent_ctx=st.trace_ctx, slot=i,
                                    active_slots=n_active, tokens=j,
                                    accepted=na,
                                    deployment=self.deployment)
            # Replay suppression counts DELIVERED tokens — variable
            # advance changes nothing about the token arithmetic.
            cut = min(st.skip, j)
            st.skip -= cut
            if j > cut:
                st.lane.q.put(("item", row[cut:j].copy()))
                st.emitted += j - cut
                emitted += j - cut
            st.remaining -= j
            if finished:
                st.lane.q.put((_STREAM_END, None))
                self._free_slot(i)           # drafter.free rides along
                self._count(completed=1)
            else:
                self._drafter.observe(i, row[:j], na)
        if emitted:
            sm["engine_tokens"].inc(emitted, labels=labels)
            self._count(tokens=emitted)
        self._observe_pages(sm)
