"""Continuous-batching decode engine: one driver thread, one persistent
slot pool (ISSUE 5 tentpole).

``@serve.batch(stream=True)`` gang-schedules: a batch forms once, runs
its whole generation off a freshly allocated KV cache, and a request
arriving mid-generation waits for the NEXT batch (or spawns a competing
per-batch stream thread that contends for the one device). The engine
replaces gang scheduling with **slot scheduling** — the standard
continuous-batching design of production inference stacks, mapped onto
TPU-friendly static shapes:

- ONE long-lived pooled KV cache (``[L, B_slots, max_len, H, hd]``,
  :func:`~ray_tpu.models.gpt_decode.init_slot_cache`) allocated at
  construction. No per-request ``init_cache``; slots are recycled by
  re-prefilling in place.
- A single driver thread owns every device dispatch, so concurrent
  requests never contend for the device — request threads only enqueue
  (device-concurrency discipline per the TPU concurrency study in
  PAPERS.md).
- Admission happens at **chunk boundaries**:
  :func:`~ray_tpu.models.gpt_decode.prefill_into_slot` writes the
  prompt's K/V into a free slot (one compiled program per prompt
  bucket; the TRUE length is traced, so any length within a bucket
  shares the program) and the first sampled token streams out
  immediately — TTFT is one prefill dispatch away from admission, not
  one full gang generation.
- :func:`~ray_tpu.models.gpt_decode.decode_chunk_slots` then decodes
  ALL active slots in one fused k-step dispatch; a slot frees the
  moment its lane samples EOS, exhausts ``max_new``, passes its
  deadline, or its consumer walks away — instead of riding out the
  batch.

Static-shape discipline: the compiled-program set is exactly
``len(prompt_buckets)`` prefill programs + 1 chunk program, bounded for
ANY admission pattern (see the recompile guard in
``tests/test_serve_engine.py``).

Results stream back through the same :class:`~.batching._StreamLane`
queues the batched streaming path uses, so replicas, handles, and the
HTTP proxy need no new transport: ``engine.submit(...)`` returns a lane,
``engine.stream(...)`` an iterator of per-chunk ``np.int32[j]`` slices.
"""
from __future__ import annotations

import queue
import threading
import time
from dataclasses import dataclass, field
from typing import Any, List, Optional, Sequence

import numpy as np

from ..util import tracing
from .batching import (_STREAM_END, _EngineStream, _StreamLane,
                       default_buckets)
from .request import RequestDeadlineExceeded, deadline_expired


def default_prompt_buckets(max_len: int) -> List[int]:
    """Powers of two from 8 up to (and including) max_len."""
    return sorted(b for b in default_buckets(max_len) if b >= 8) \
        or [max_len]


@dataclass
class _EngineRequest:
    """One queued admission: everything the driver needs to prefill a
    slot and route its stream."""

    prompt: np.ndarray            # [S] int32
    bucket: int                   # padded prompt length (compile shape)
    max_new: int
    lane: _StreamLane
    deadline_s: Optional[float]
    trace_ctx: Optional[dict]
    seed: int
    enq_t: float


@dataclass
class _Slot:
    """Host-side state of one occupied slot between chunk boundaries."""

    lane: _StreamLane
    remaining: int                # tokens still owed to the caller
    deadline_s: Optional[float]
    trace_ctx: Optional[dict]
    emitted: int = 1              # the prefill-derived token
    admitted_t: float = field(default_factory=time.time)


class EngineShutdownError(RuntimeError):
    """The engine stopped while this request was queued or decoding."""


class DecodeEngine:
    """Slot-based continuous-batching engine for the chunked GPT decode
    path.

    Usage (inside a deployment; or see ``@serve.batch(continuous=True)``
    for the decorator form)::

        engine = DecodeEngine(params, cfg, slots=8, chunk=8,
                              max_len=256, eos_token=eos)
        for slice_ in engine.stream(prompt_ids, max_new=64):
            ...                       # np.int32 [j] per chunk, first j=1

    All device work runs on the engine's single driver thread;
    ``submit``/``stream`` only enqueue and are safe from any thread.
    At ``temperature == 0`` each stream is token-identical to
    :func:`~ray_tpu.models.gpt_decode.generate_chunked` for the same
    prompt (asserted in ``tests/test_serve_engine.py``).
    """

    def __init__(self, params, cfg, *, slots: int = 4, chunk: int = 8,
                 max_len: int = 0, temperature: float = 0.0,
                 eos_token: int = -1,
                 prompt_buckets: Optional[Sequence[int]] = None,
                 deployment: str = "", auto_start: bool = True):
        from ..models import gpt_decode

        self.params = params
        self.cfg = cfg
        self.slots = int(slots)
        self.chunk = int(chunk)
        self.max_len = int(max_len or cfg.max_seq)
        self.temperature = float(temperature)
        self.eos_token = int(eos_token)
        self.deployment = deployment or "engine"
        if self.slots < 1 or self.chunk < 1:
            raise ValueError("slots and chunk must be >= 1")
        if self.max_len > cfg.max_seq:
            raise ValueError(f"max_len {self.max_len} exceeds model "
                             f"max_seq {cfg.max_seq}")
        buckets = sorted(set(int(b) for b in (
            prompt_buckets or default_prompt_buckets(self.max_len))))
        if not buckets or buckets[0] < 1:
            raise ValueError(f"invalid prompt_buckets {buckets}")
        if buckets[-1] > self.max_len:
            raise ValueError(
                f"largest prompt bucket {buckets[-1]} exceeds cache "
                f"length {self.max_len}")
        self.prompt_buckets = buckets
        self._gd = gpt_decode
        self._prefill = gpt_decode.jit_prefill_into_slot(
            cfg, self.temperature)
        self._step = gpt_decode.jit_decode_chunk_slots(
            cfg, self.chunk, self.temperature, self.eos_token)
        # THE persistent pool: allocated once, recycled forever.
        self._cache = gpt_decode.init_slot_cache(cfg, self.slots,
                                                 self.max_len)
        # Per-slot host state; index i mirrors pool row i. ``_token`` /
        # ``_rngs`` are the host copies uploaded with each dispatch
        # (tiny against the chunk compute; keeping them host-side avoids
        # per-admission scatter programs).
        self._state: List[Optional[_Slot]] = [None] * self.slots
        self._token = np.zeros((self.slots,), np.int32)
        self._rngs = np.zeros((self.slots, 2), np.uint32)
        self._queue: "queue.SimpleQueue" = queue.SimpleQueue()
        # Guards the put-vs-final-drain race: once _fail_all flips
        # _draining under this lock, no new submission can land in a
        # queue nobody will ever read again.
        self._admit_lock = threading.Lock()
        self._draining = False
        self._stats_lock = threading.Lock()
        self._stats = {"admitted": 0, "completed": 0, "expired": 0,
                       "abandoned": 0, "prefills": 0, "dispatches": 0,
                       "tokens": 0, "occupancy_sum": 0.0}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if auto_start:
            self.start()

    # ------------------------------------------------------------- admission
    def submit(self, prompt, max_new: int, *,
               deadline_s: Optional[float] = None,
               trace_ctx: Optional[dict] = None,
               seed: int = 0) -> _StreamLane:
        """Enqueue one request; returns its stream lane immediately. The
        driver admits it at the next chunk boundary with a free slot.
        Safe from any thread."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        S = prompt.shape[0]
        if S < 1:
            raise ValueError("empty prompt")
        bucket = next((b for b in self.prompt_buckets if b >= S), None)
        if bucket is None:
            raise ValueError(
                f"prompt length {S} exceeds largest prompt bucket "
                f"{self.prompt_buckets[-1]}")
        if S + max_new > self.max_len:
            raise ValueError(
                f"prompt ({S}) + max_new ({max_new}) exceeds cache "
                f"length {self.max_len}")
        if self._thread is None or not self._thread.is_alive():
            raise EngineShutdownError("engine is not running")
        lane = _StreamLane()
        if max_new <= 0:
            lane.q.put((_STREAM_END, None))
            return lane
        with self._admit_lock:
            if self._draining:
                raise EngineShutdownError("engine is not running")
            self._queue.put(_EngineRequest(
                prompt=prompt, bucket=bucket, max_new=int(max_new),
                lane=lane, deadline_s=deadline_s, trace_ctx=trace_ctx,
                seed=int(seed), enq_t=time.time()))
        return lane

    def stream(self, prompt, max_new: int, **kw):
        """``submit`` + drain: an iterator of np.int32 ``[j]`` chunk
        slices (first slice is the prefill token alone). ``close()``
        marks the lane abandoned even before the first pull."""
        return _EngineStream(self.submit(prompt, max_new, **kw))

    # ------------------------------------------------------------- lifecycle
    def start(self):
        if self._thread is not None and self._thread.is_alive():
            return
        with self._admit_lock:
            self._draining = False
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"rt-serve-engine-{self.deployment}")
        self._thread.start()

    def shutdown(self, timeout_s: float = 5.0):
        """Stop the driver; queued and in-flight lanes fail with
        :class:`EngineShutdownError`."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)

    def stats(self) -> dict:
        with self._stats_lock:
            out = dict(self._stats)
        out["active_slots"] = sum(s is not None for s in self._state)
        out["slots"] = self.slots
        out["queued"] = self._queue.qsize()
        d = max(out["dispatches"], 1)
        out["avg_occupancy"] = out.pop("occupancy_sum") / d
        out["dispatches_per_token"] = (
            (out["dispatches"] + out["prefills"]) / max(out["tokens"], 1))
        return out

    def _count(self, **deltas):
        with self._stats_lock:
            for k, v in deltas.items():
                self._stats[k] += v

    # ---------------------------------------------------------- driver loop
    def _run(self):
        try:
            while not self._stop.is_set():
                self._admit_pending()
                if not any(s is not None for s in self._state):
                    # Idle: block briefly for the next arrival instead
                    # of spinning; the timeout bounds shutdown latency.
                    try:
                        req = self._queue.get(timeout=0.05)
                    except queue.Empty:
                        continue
                    self._admit_one(req)
                    continue  # boundary: drain more arrivals first
                self._dispatch_chunk()
            self._fail_all(EngineShutdownError("engine shut down"))
        except BaseException as e:  # noqa: BLE001 - driver died: fan out
            self._fail_all(e)
            raise

    def _fail_all(self, exc: BaseException):
        with self._admit_lock:
            self._draining = True    # no put can land past this point
        for i, st in enumerate(self._state):
            if st is not None:
                st.lane.q.put(("err", exc))
                self._state[i] = None
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                return
            req.lane.q.put(("err", exc))

    def _admit_pending(self):
        """Chunk-boundary admission: fill every free slot from the FIFO
        queue. Expired / abandoned requests are failed out without
        spending a prefill."""
        while any(s is None for s in self._state):
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                return
            self._admit_one(req)

    def _admit_one(self, req: _EngineRequest):
        from .._private.metrics import serve_metrics

        if req.lane.closed:
            self._count(abandoned=1)
            return
        if deadline_expired(req.deadline_s):
            self._count(expired=1)
            serve_metrics()["requests_expired"].inc(
                labels={"where": "engine", "deployment": self.deployment})
            req.lane.q.put(("err", RequestDeadlineExceeded(
                "request expired while queued for engine admission")))
            return
        slot = next(i for i, s in enumerate(self._state) if s is None)
        now = time.time()
        serve_metrics()["engine_admission_wait"].observe(
            max(now - req.enq_t, 0.0),
            labels={"deployment": self.deployment})
        if req.trace_ctx is not None:
            tracing.record_span("engine.admission", req.enq_t, now,
                                parent_ctx=req.trace_ctx, slot=slot,
                                deployment=self.deployment)
        import jax

        padded = np.zeros((1, req.bucket), np.int32)
        padded[0, :req.prompt.shape[0]] = req.prompt
        tok, self._cache, key = self._prefill(
            self.params, self._cache, padded,
            np.int32(req.prompt.shape[0]), np.int32(slot),
            jax.random.PRNGKey(req.seed))
        first = int(np.asarray(tok))
        self._count(prefills=1, admitted=1, tokens=1)
        serve_metrics()["engine_tokens"].inc(
            labels={"deployment": self.deployment})
        self._token[slot] = first
        self._rngs[slot] = np.asarray(key)
        req.lane.q.put(("item", np.asarray([first], np.int32)))
        if req.max_new <= 1 or (self.eos_token >= 0
                                and first == self.eos_token):
            req.lane.q.put((_STREAM_END, None))
            self._count(completed=1)
            return
        self._state[slot] = _Slot(
            lane=req.lane, remaining=req.max_new - 1,
            deadline_s=req.deadline_s, trace_ctx=req.trace_ctx)

    def _dispatch_chunk(self):
        """ONE fused device dispatch decoding every active slot, then
        per-slot routing/trimming and boundary frees."""
        from .._private.metrics import serve_metrics

        active = np.array([s is not None for s in self._state], bool)
        n_active = int(active.sum())
        t0 = time.time()
        toks, self._cache, _done, rngs = self._step(
            self.params, self._cache, self._token, self._rngs, active)
        toks_np = np.asarray(toks)        # ONE transfer per chunk
        rngs_np = np.asarray(rngs)
        t1 = time.time()
        sm = serve_metrics()
        sm["engine_slot_occupancy"].observe(
            n_active / self.slots, labels={"deployment": self.deployment})
        sm["engine_dispatches"].inc(
            labels={"deployment": self.deployment})
        self._count(dispatches=1, occupancy_sum=n_active / self.slots)
        emitted = 0
        for i, st in enumerate(self._state):
            if st is None:
                continue
            self._token[i] = toks_np[i, -1]
            self._rngs[i] = rngs_np[i]
            if st.lane.closed:               # consumer left: free now
                self._state[i] = None
                self._count(abandoned=1)
                continue
            if deadline_expired(st.deadline_s):
                st.lane.q.put(("err", RequestDeadlineExceeded(
                    "request deadline passed mid-generation")))
                self._state[i] = None
                self._count(expired=1)
                sm["requests_expired"].inc(
                    labels={"where": "engine",
                            "deployment": self.deployment})
                continue
            row = toks_np[i]
            j = min(self.chunk, st.remaining)
            finished = st.remaining <= self.chunk
            if self.eos_token >= 0:
                hits = np.flatnonzero(row[:j] == self.eos_token)
                if hits.size:                # free at the EOS, not the
                    j = int(hits[0]) + 1     # end of the gang batch
                    finished = True
            if st.trace_ctx is not None:
                tracing.record_span("decode.chunk", t0, t1,
                                    parent_ctx=st.trace_ctx, slot=i,
                                    active_slots=n_active, tokens=j,
                                    deployment=self.deployment)
            st.lane.q.put(("item", row[:j].copy()))
            st.remaining -= j
            st.emitted += j
            emitted += j
            if finished:
                st.lane.q.put((_STREAM_END, None))
                self._state[i] = None
                self._count(completed=1)
        if emitted:
            sm["engine_tokens"].inc(
                emitted, labels={"deployment": self.deployment})
            self._count(tokens=emitted)
