"""Blockwise (flash) causal attention as Pallas TPU kernels.

The XLA einsum path materializes the full ``[B, H, S, S]`` float32 logit
tensor in HBM — at GPT-2 bench shapes that is the dominant memory traffic
of the whole step. This kernel keeps the softmax online in VMEM: each
``(batch, head, q-block)`` program streams K/V blocks through the MXU,
tracking the running row max/sum, and writes only the ``[bq, hd]`` output
block plus a logsumexp residual for the backward pass.

At GPT-2 head sizes (hd=64) the kernel is VPU-bound, not MXU-bound: the
softmax (exp, masking, online max/sum) does as many vector ops as the two
small-K matmuls do MACs. Three measured-on-v5e design points follow:

- all dots keep bf16 inputs (MXU-native) with f32 accumulation via
  ``preferred_element_type`` — casting inputs to f32 forces a multi-pass
  matmul ~4x slower;
- the softmax scale is folded into ``q`` *outside* the kernel (one XLA
  elementwise op that fuses into the producing matmul) instead of a
  per-block ``[bq, bk]`` multiply inside it;
- the causal mask is applied only to blocks that straddle the diagonal
  (with ``block_q == block_k`` that is exactly the ``j == i`` block);
  fully-visible blocks skip the compare/select pass entirely, and the
  mask itself is a broadcast of a per-program ``[bq, 1]`` row-id column
  against a ``[1, bk]`` col-id row — one vector pass, no 2D iota pair.

This beats ``jax.experimental.pallas.ops.tpu.flash_attention`` by ~5x at
GPT-2 bench shapes on v5e (36ms vs 200ms for 12 fwd layers, B=32,
S=1024). The reference framework has no native attention at all — its
long-context story is delegated to integrations (SURVEY.md §5
"long-context: nothing native") — so this file is new TPU-first
capability, not a port.

Backward follows the flash decomposition — an XLA precompute of
``delta = rowsum(dO * O)``, then block softmax recomputed from the saved
logsumexp instead of stored probabilities — but in ONE fused kernel
(grid over k-blocks) producing dK, dV *and* dQ. The textbook two-kernel
split recomputes the softmax twice (once for dQ over q-blocks, once for
dK/dV over k-blocks); at GPT-2 head sizes the kernel is VPU-bound on
exactly that exp/mask work, so halving it is ~1.3x on the backward
(measured 101ms → 77ms for 12 layers fwd+bwd, B=32, S=1024, v5e). The
fusion exploits the TPU's sequential grid: every j-program accumulates
its ``ds @ k_j`` contribution into a full-sequence dQ accumulator that
lives in VMEM across the j-sweep (zeroed at j==0), which only works
because grid steps with the same (b, h) run back-to-back on one core —
this is a Mosaic-specific accumulation pattern, not portable flash.
"""
from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu  # noqa: F401  (doc import)

NEG_INF = -1e30


def _use_interpret() -> bool:
    return jax.devices()[0].platform != "tpu"


def _mask_diag_block(s, i, j, bq, bk):
    """Causal-mask logits of the diagonal block (rows i*bq+r, cols j*bk+c)."""
    rows = lax.broadcasted_iota(jnp.int32, (bq, 1), 0) + i * bq
    cols = lax.broadcasted_iota(jnp.int32, (1, bk), 1) + j * bk
    return jnp.where(cols > rows, NEG_INF, s)


# ------------------------------------------------------------------ forward
def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, causal, block_k):
    bq, hd = q_ref.shape[2], q_ref.shape[3]
    kv_len = k_ref.shape[2]
    i = pl.program_id(2)
    num_kb = kv_len // block_k
    # Causal: q rows in block i never see k blocks past (i+1)*bq.
    upper = pl.cdiv((i + 1) * bq, block_k) if causal else num_kb

    q = q_ref[0, 0]                                  # [bq, hd] bf16, scaled

    def make_body(masked):
        def body(j, carry):
            acc, m, l = carry
            kj = k_ref[0, 0, pl.ds(j * block_k, block_k), :]  # [bk, hd]
            vj = v_ref[0, 0, pl.ds(j * block_k, block_k), :]
            s = lax.dot_general(q, kj, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
            if masked:
                s = _mask_diag_block(s, i, j, bq, block_k)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
            p = jnp.exp(s - m_new)                   # [bq, bk] f32
            alpha = jnp.exp(m - m_new)               # [bq, 1]
            l = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
            pv = lax.dot_general(p.astype(vj.dtype), vj,
                                 (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
            acc = acc * alpha + pv
            return acc, m_new, l
        return body

    carry = (jnp.zeros((bq, hd), jnp.float32),
             jnp.full((bq, 1), NEG_INF, jnp.float32),
             jnp.zeros((bq, 1), jnp.float32))
    if causal:
        # Off-diagonal blocks (fully visible) skip the mask pass; only the
        # final (diagonal-straddling) block pays for it.
        carry = lax.fori_loop(0, upper - 1, make_body(False), carry)
        carry = make_body(True)(upper - 1, carry)
    else:
        carry = lax.fori_loop(0, upper, make_body(False), carry)
    acc, m, l = carry
    o_ref[0, 0] = (acc / l).astype(o_ref.dtype)
    lse_ref[0, 0] = m + jnp.log(l)                   # [bq, 1]


def _flash_fwd(q, k, v, *, causal, block_q, block_k, interpret):
    """q is pre-scaled. Shapes [B, H, S, hd]."""
    B, H, S, hd = q.shape
    Sk = k.shape[2]
    bq = min(block_q, S)
    bk = min(block_k, Sk)
    assert S % bq == 0 and Sk % bk == 0, (S, Sk, bq, bk)
    if causal:
        assert bq == bk, "causal path requires block_q == block_k"
    o, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, causal=causal, block_k=bk),
        grid=(B, H, S // bq),
        in_specs=[
            pl.BlockSpec((1, 1, bq, hd), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, Sk, hd), lambda b, h, i: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, Sk, hd), lambda b, h, i: (b, h, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, bq, hd), lambda b, h, i: (b, h, i, 0)),
            # lse kept 4D [B,H,S,1]: trailing dims (bq, 1) satisfy the
            # (8,128)-or-full tiling rule; a 3D [.., bq] block does not.
            pl.BlockSpec((1, 1, bq, 1), lambda b, h, i: (b, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, S, hd), q.dtype),
            jax.ShapeDtypeStruct((B, H, S, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)
    return o, lse


# ----------------------------------------------------------------- backward
def _bwd_fused_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                      dq_ref, dk_ref, dv_ref, *, causal, block_q):
    """One k-block program computes dK/dV for its block AND accumulates
    every q-block's dQ contribution into a full-sequence VMEM
    accumulator. Correct only because TPU grid steps with the same
    (b, h) run sequentially on one core: dq_ref's block index ignores j,
    so Mosaic keeps the buffer resident across the j-sweep."""
    bk, hd = k_ref.shape[2], k_ref.shape[3]
    q_len = q_ref.shape[2]
    j = pl.program_id(2)
    num_qb = q_len // block_q
    # Causal: q blocks strictly before the diagonal contribute nothing.
    start = j * bk // block_q if causal else 0

    @pl.when(j == 0)
    def _zero_dq():
        dq_ref[...] = jnp.zeros_like(dq_ref)

    kj = k_ref[0, 0]                                 # [bk, hd] bf16
    vj = v_ref[0, 0]

    def make_body(masked):
        def body(i, carry):
            dk, dv = carry
            qi = q_ref[0, 0, pl.ds(i * block_q, block_q), :]  # scaled
            doi = do_ref[0, 0, pl.ds(i * block_q, block_q), :]
            lse = lse_ref[0, 0, pl.ds(i * block_q, block_q), :]
            delta = delta_ref[0, 0, pl.ds(i * block_q, block_q), :]
            s = lax.dot_general(qi, kj, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
            if masked:
                s = _mask_diag_block(s, i, j, block_q, bk)
            p = jnp.exp(s - lse)                     # [bq, bk] f32
            pb = p.astype(doi.dtype)
            dv = dv + lax.dot_general(pb, doi, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
            dp = lax.dot_general(doi, vj, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
            ds = (p * (dp - delta)).astype(qi.dtype)
            dk = dk + lax.dot_general(ds, qi, (((0,), (0,)), ((), ())),
                                      preferred_element_type=jnp.float32)
            # dQ_i += ds @ K_j — the whole point of the fusion: the same
            # (s, p) recompute serves dK/dV and dQ.
            dq_i = lax.dot_general(ds, kj, (((1,), (0,)), ((), ())),
                                   preferred_element_type=jnp.float32)
            sl = (0, 0, pl.ds(i * block_q, block_q), slice(None))
            dq_ref[sl] += dq_i
            return dk, dv
        return body

    carry = (jnp.zeros((bk, hd), jnp.float32),
             jnp.zeros((bk, hd), jnp.float32))
    if causal:
        # The first visible q block (the diagonal) is masked; the rest see
        # this k block in full.
        carry = make_body(True)(start, carry)
        carry = lax.fori_loop(start + 1, num_qb, make_body(False), carry)
    else:
        carry = lax.fori_loop(0, num_qb, make_body(False), carry)
    dk, dv = carry
    # qi carried the softmax scale, so dk = ds^T (q*scale) is complete.
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


def _flash_bwd(qs, k, v, o, lse, do, *, sm_scale, causal, block_q, block_k,
               interpret):
    """qs is the pre-scaled q. Returns grads wrt the ORIGINAL q, k, v."""
    B, H, S, hd = qs.shape
    Sk = k.shape[2]
    bq = min(block_q, S)
    bk = min(block_k, Sk)
    if causal:
        # The fused kernel masks exactly one diagonal-straddling q-block
        # per k-block, which is only the full causal boundary when the
        # blocks match (same invariant _flash_fwd enforces).
        assert bq == bk, "causal backward requires block_q == block_k"
    # delta = rowsum(dO * O): tiny, let XLA fuse it. Kept [B,H,S,1] like lse.
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32),
                    axis=-1, keepdims=True)

    dqs, dk, dv = pl.pallas_call(
        functools.partial(_bwd_fused_kernel, causal=causal, block_q=bq),
        grid=(B, H, Sk // bk),
        in_specs=[
            pl.BlockSpec((1, 1, S, hd), lambda b, h, j: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, bk, hd), lambda b, h, j: (b, h, j, 0)),
            pl.BlockSpec((1, 1, bk, hd), lambda b, h, j: (b, h, j, 0)),
            pl.BlockSpec((1, 1, S, hd), lambda b, h, j: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, S, 1), lambda b, h, j: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, S, 1), lambda b, h, j: (b, h, 0, 0)),
        ],
        out_specs=[
            # dq: full-S accumulator, same block for every j (resident
            # in VMEM across the j-sweep; f32 so += stays exact).
            pl.BlockSpec((1, 1, S, hd), lambda b, h, j: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, bk, hd), lambda b, h, j: (b, h, j, 0)),
            pl.BlockSpec((1, 1, bk, hd), lambda b, h, j: (b, h, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, S, hd), jnp.float32),
            jax.ShapeDtypeStruct((B, H, Sk, hd), k.dtype),
            jax.ShapeDtypeStruct((B, H, Sk, hd), v.dtype),
        ],
        interpret=interpret,
    )(qs, k, v, do, lse, delta)
    # dL/dq = dL/dqs * sm_scale (qs = q * sm_scale).
    dq = (dqs * sm_scale).astype(qs.dtype)
    return dq, dk, dv


# -------------------------------------------------------------- public API
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    qs = (q * jnp.asarray(sm_scale, q.dtype)) if sm_scale != 1.0 else q
    o, _ = _flash_fwd(qs, k, v, causal=causal, block_q=block_q,
                      block_k=block_k, interpret=interpret)
    return o


def _flash_fwd_rule(q, k, v, sm_scale, causal, block_q, block_k, interpret):
    qs = (q * jnp.asarray(sm_scale, q.dtype)) if sm_scale != 1.0 else q
    o, lse = _flash_fwd(qs, k, v, causal=causal, block_q=block_q,
                        block_k=block_k, interpret=interpret)
    return o, (qs, k, v, o, lse)


def _flash_bwd_rule(sm_scale, causal, block_q, block_k, interpret, res, g):
    qs, k, v, o, lse = res
    return _flash_bwd(qs, k, v, o, lse, g, sm_scale=sm_scale, causal=causal,
                      block_q=block_q, block_k=block_k, interpret=interpret)


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def _pick_block(S: int) -> int:
    """Largest power-of-two block (<=512, measured best on v5e) dividing S."""
    for b in (512, 256, 128, 64, 32, 16, 8):
        if S % b == 0:
            return b
    return S


def flash_attention(q, k, v, *, causal: bool = True,
                    sm_scale: Optional[float] = None,
                    block_q: Optional[int] = None,
                    block_k: Optional[int] = None,
                    interpret: Optional[bool] = None) -> jax.Array:
    """Blockwise attention. q, k, v: ``[B, S, H, hd]`` → ``[B, S, H, hd]``.

    Differentiable (custom VJP, flash backward). Falls back to the Pallas
    interpreter off-TPU so tests run on the virtual CPU mesh.
    """
    if interpret is None:
        interpret = _use_interpret()
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    if block_q is None:
        block_q = _pick_block(q.shape[1])
    if block_k is None:
        block_k = block_q if causal else _pick_block(k.shape[1])
    qt = jnp.transpose(q, (0, 2, 1, 3))              # [B, H, S, hd]
    kt = jnp.transpose(k, (0, 2, 1, 3))
    vt = jnp.transpose(v, (0, 2, 1, 3))
    o = _flash(qt, kt, vt, sm_scale, causal, block_q, block_k, interpret)
    return jnp.transpose(o, (0, 2, 1, 3))
