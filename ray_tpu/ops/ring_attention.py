"""Sequence-parallel attention: ring (ppermute) and Ulysses (all-to-all).

The reference has **no** native sequence/context parallelism — SURVEY.md §5
records zero hits for ring-attention/Ulysses across the tree; long-context
scaling is delegated to integrations. Here it is first-class: both
strategies operate on sequence-sharded activations ``[B, S/n, H, hd]``
inside a ``jax.shard_map`` region over a mesh axis (the TPU-native
replacement for the reference's NCCL process groups; collectives ride ICI).

**Ring** (`ring_attention`): K/V chunks rotate around the ring via
``lax.ppermute`` while each device accumulates an online softmax over its
local queries — attention memory stays O(S_local²) per device regardless
of global sequence length. Causality is enforced per source chunk: chunks
from later ranks are skipped entirely (``lax.cond`` — no FLOPs burned on
fully-masked blocks), the self chunk gets the triangular mask, earlier
chunks are attended in full.

**Ulysses** (`ulysses_attention`): two ``lax.all_to_all``s swap the
sequence shard for a head shard so each device computes full-sequence
attention for ``H/n`` heads. Cheaper collectives than ring for moderate
S, but requires ``n_heads % axis_size == 0``.

Both are pure differentiable JAX (ppermute/all_to_all have transpose
rules), so they compose with grads, remat, and the rest of GSPMD.
"""
from __future__ import annotations

import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def _axis_size(axis_name: str, axis_size: Optional[int]) -> int:
    if axis_size is not None:
        return axis_size
    return lax.axis_size(axis_name)


def ring_attention(q, k, v, *, axis_name: str, causal: bool = True,
                   sm_scale: Optional[float] = None,
                   axis_size: Optional[int] = None) -> jax.Array:
    """Ring attention over a mesh axis. Call inside ``jax.shard_map``.

    q, k, v: local chunks ``[B, S_loc, H, hd]`` (sequence sharded over
    ``axis_name``). Returns local output ``[B, S_loc, H, hd]``.
    """
    n = _axis_size(axis_name, axis_size)
    r = lax.axis_index(axis_name)
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])
    B, Sq, H, hd = q.shape
    Sk = k.shape[1]
    perm = [(i, (i + 1) % n) for i in range(n)]
    tril = jnp.tril(jnp.ones((Sq, Sk), jnp.bool_))

    # Keep einsum operands in the input dtype (bf16 on TPU — MXU-native;
    # an f32 cast forces a multi-pass matmul ~4x slower). Accumulation is
    # f32 via preferred_element_type; only the softmax state is f32.
    qf = (q * jnp.asarray(sm_scale, q.dtype))

    def attend(carry_o, m, l, kc, vc, src):
        logits = jnp.einsum("bqhd,bkhd->bhqk", qf, kc,
                            preferred_element_type=jnp.float32)
        if causal:
            allowed = (src < r) | (tril & (src == r))
            logits = jnp.where(allowed, logits, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(logits, axis=-1))       # [B,H,Sq]
        p = jnp.exp(logits - m_new[..., None])
        alpha = jnp.exp(m - m_new)                             # [B,H,Sq]
        l_new = l * alpha + jnp.sum(p, axis=-1)
        pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(vc.dtype), vc,
                        preferred_element_type=jnp.float32)
        o_new = carry_o * jnp.transpose(alpha, (0, 2, 1))[..., None] + pv
        return o_new, m_new, l_new

    def step(carry, t):
        o, m, l, kc, vc = carry
        src = (r - t) % n
        if causal:
            o, m, l = lax.cond(
                src <= r,
                lambda args: attend(*args),
                lambda args: (args[0], args[1], args[2]),
                (o, m, l, kc, vc, src))
        else:
            o, m, l = attend(o, m, l, kc, vc, src)
        kc = lax.ppermute(kc, axis_name, perm)
        vc = lax.ppermute(vc, axis_name, perm)
        return (o, m, l, kc, vc), None

    # The accumulators must carry the same varying-across-mesh type as the
    # attend() outputs for shard_map's cond VMA check, whatever axes the
    # surrounding shard_map spans. Deriving them from q (times zero — XLA
    # folds it) inherits exactly q's vma.
    zero = jnp.sum(qf.astype(jnp.float32) * 0.0, axis=-1)  # vma of q
    zero_t = jnp.transpose(zero, (0, 2, 1))          # [B, H, Sq] f32
    init = (qf.astype(jnp.float32) * 0.0,
            zero_t + NEG_INF,
            zero_t,
            k, v)
    (o, m, l, _, _), _ = lax.scan(step, init, jnp.arange(n))
    o = o / jnp.transpose(l, (0, 2, 1))[..., None]
    return o.astype(q.dtype)


def ulysses_attention(q, k, v, *, axis_name: str, causal: bool = True,
                      sm_scale: Optional[float] = None,
                      axis_size: Optional[int] = None) -> jax.Array:
    """Ulysses attention: all-to-all head/seq swap. Call inside shard_map.

    q, k, v: local chunks ``[B, S_loc, H, hd]``; requires ``H % n == 0``.
    """
    n = _axis_size(axis_name, axis_size)
    H = q.shape[2]
    assert H % n == 0, f"ulysses needs n_head ({H}) % axis size ({n}) == 0"
    if sm_scale is None:
        sm_scale = 1.0 / math.sqrt(q.shape[-1])

    def gather_seq(x):  # [B, S/n, H, hd] -> [B, S, H/n, hd]
        return lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)

    qg, kg, vg = gather_seq(q), gather_seq(k), gather_seq(v)
    S = qg.shape[1]
    # bf16 einsum operands, f32 accumulation (see ring_attention note).
    logits = jnp.einsum("bqhd,bkhd->bhqk",
                        qg * jnp.asarray(sm_scale, q.dtype), kg,
                        preferred_element_type=jnp.float32)
    if causal:
        mask = jnp.tril(jnp.ones((S, S), jnp.bool_))
        logits = jnp.where(mask, logits, NEG_INF)
    p = jax.nn.softmax(logits, axis=-1)
    o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(vg.dtype), vg,
                   preferred_element_type=jnp.float32).astype(q.dtype)
    # [B, S, H/n, hd] -> [B, S/n, H, hd]
    return lax.all_to_all(o, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)
