"""Native codec loader: compile-on-first-use with a pure-Python fallback.

The reference ships its data plane as prebuilt C++ (bazel targets under
``src/ray/``); this runtime compiles its single-file extension lazily with
the system compiler and caches the .so next to the source, keyed by the
python ABI. If no compiler is available the callers fall back to the
Python implementations in ``_private/serialization.py``.
"""
from __future__ import annotations

import os
import subprocess
import sysconfig
import threading

_here = os.path.dirname(os.path.abspath(__file__))
_lock = threading.Lock()
_mod = None
_tried = False


def _so_path() -> str:
    tag = sysconfig.get_config_var("SOABI") or "generic"
    return os.path.join(_here, f"_rt_native.{tag}.so")


def _build() -> str:
    src = os.path.join(_here, "codec.cpp")
    out = _so_path()
    if os.path.exists(out) and \
            os.path.getmtime(out) >= os.path.getmtime(src):
        return out
    include = sysconfig.get_paths()["include"]
    cxx = os.environ.get("CXX", "g++")
    cmd = [cxx, "-O3", "-shared", "-fPIC", "-std=c++17",
           f"-I{include}", src, "-o", out + ".tmp"]
    subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    os.replace(out + ".tmp", out)
    return out


def load():
    """The native module, or None when unavailable."""
    global _mod, _tried
    if _mod is not None or _tried:
        return _mod
    with _lock:
        if _mod is not None or _tried:
            return _mod
        _tried = True
        if os.environ.get("RT_DISABLE_NATIVE", "") == "1":
            return None
        try:
            so = _build()
            import importlib.util

            spec = importlib.util.spec_from_file_location("_rt_native", so)
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            _mod = mod
        except Exception:  # noqa: BLE001 - fall back to pure python
            _mod = None
        return _mod
