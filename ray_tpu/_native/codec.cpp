// Native data-plane codec for the object store / RPC framing.
//
// Capability parity with the reference's C++ data plane (reference:
// src/ray/object_manager/plasma/ arena + src/ray/common/buffer.h — frame
// assembly and scatter/gather happen in C++, never in Python): the hot
// pack/unpack of pickle-5 frame lists into single contiguous blobs is a
// single-pass memcpy here instead of Python-level bytes concatenation.
//
// Layout (matches ray_tpu/_private/serialization.py pack_frames):
//   [u32 nframes][u64 size_0]...[u64 size_{n-1}] frame_0 ... frame_{n-1}
//
// Built as a plain CPython extension (no pybind11 — not in the image).

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <atomic>
#include <cstdint>
#include <cstring>

namespace {

// pack_frames(list[buffer]) -> bytes
PyObject* pack_frames(PyObject* /*self*/, PyObject* arg) {
  PyObject* seq = PySequence_Fast(arg, "pack_frames expects a sequence");
  if (seq == nullptr) return nullptr;
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);

  // First pass: acquire buffers, total size.
  Py_buffer* views =
      static_cast<Py_buffer*>(PyMem_Malloc(sizeof(Py_buffer) * (n ? n : 1)));
  if (views == nullptr) {
    Py_DECREF(seq);
    return PyErr_NoMemory();
  }
  Py_ssize_t acquired = 0;
  uint64_t total = 4 + 8 * static_cast<uint64_t>(n);
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject* item = PySequence_Fast_GET_ITEM(seq, i);
    if (PyObject_GetBuffer(item, &views[i], PyBUF_CONTIG_RO) != 0) {
      goto fail;
    }
    acquired++;
    total += static_cast<uint64_t>(views[i].len);
  }

  {
    PyObject* out = PyBytes_FromStringAndSize(nullptr, (Py_ssize_t)total);
    if (out == nullptr) goto fail;
    char* p = PyBytes_AS_STRING(out);
    uint32_t n32 = static_cast<uint32_t>(n);
    std::memcpy(p, &n32, 4);
    p += 4;
    for (Py_ssize_t i = 0; i < n; i++) {
      uint64_t len = static_cast<uint64_t>(views[i].len);
      std::memcpy(p, &len, 8);
      p += 8;
    }
    for (Py_ssize_t i = 0; i < n; i++) {
      if (views[i].len > 0) std::memcpy(p, views[i].buf, views[i].len);
      p += views[i].len;
    }
    for (Py_ssize_t i = 0; i < acquired; i++) PyBuffer_Release(&views[i]);
    PyMem_Free(views);
    Py_DECREF(seq);
    return out;
  }

fail:
  for (Py_ssize_t i = 0; i < acquired; i++) PyBuffer_Release(&views[i]);
  PyMem_Free(views);
  Py_DECREF(seq);
  return nullptr;
}

// frame_offsets(buffer) -> list[(offset, size)]  (zero-copy: caller slices
// its own memoryview, so the blob's lifetime stays with the caller)
PyObject* frame_offsets(PyObject* /*self*/, PyObject* arg) {
  Py_buffer view;
  if (PyObject_GetBuffer(arg, &view, PyBUF_CONTIG_RO) != 0) return nullptr;
  const char* p = static_cast<const char*>(view.buf);
  uint64_t remaining = static_cast<uint64_t>(view.len);
  if (remaining < 4) {
    PyBuffer_Release(&view);
    PyErr_SetString(PyExc_ValueError, "blob too short for header");
    return nullptr;
  }
  uint32_t n;
  std::memcpy(&n, p, 4);
  // Pairs with the writer's release fence in write_into: once a nonzero
  // count is observed, the size table and frame bytes published before
  // it must be visible too (matters on weakly-ordered CPUs; x86 TSO
  // gets this for free).
  std::atomic_thread_fence(std::memory_order_acquire);
  if (remaining < 4 + 8ull * n) {
    PyBuffer_Release(&view);
    PyErr_SetString(PyExc_ValueError, "blob too short for size table");
    return nullptr;
  }
  PyObject* out = PyList_New(n);
  if (out == nullptr) {
    PyBuffer_Release(&view);
    return nullptr;
  }
  uint64_t off = 4 + 8ull * n;
  for (uint32_t i = 0; i < n; i++) {
    uint64_t len;
    std::memcpy(&len, p + 4 + 8ull * i, 8);
    // Subtraction form: `off + len` can wrap for a torn/corrupt u64 size
    // (off <= remaining holds inductively, so the subtraction is safe).
    if (len > remaining - off) {
      Py_DECREF(out);
      PyBuffer_Release(&view);
      PyErr_SetString(PyExc_ValueError, "frame overruns blob");
      return nullptr;
    }
    PyObject* tup = Py_BuildValue("(KK)", (unsigned long long)off,
                                  (unsigned long long)len);
    if (tup == nullptr) {
      Py_DECREF(out);
      PyBuffer_Release(&view);
      return nullptr;
    }
    PyList_SET_ITEM(out, i, tup);
    off += len;
  }
  PyBuffer_Release(&view);
  return out;
}

// write_into(dst_buffer, offset, frames) -> total_written
// One-pass scatter of a frame list into a writable buffer (the shm
// segment), skipping the intermediate bytes object entirely.
PyObject* write_into(PyObject* /*self*/, PyObject* args) {
  PyObject* dst_obj;
  unsigned long long offset;
  PyObject* frames;
  if (!PyArg_ParseTuple(args, "OKO", &dst_obj, &offset, &frames)) {
    return nullptr;
  }
  Py_buffer dst;
  if (PyObject_GetBuffer(dst_obj, &dst, PyBUF_CONTIG) != 0) return nullptr;
  PyObject* seq = PySequence_Fast(frames, "write_into expects a sequence");
  if (seq == nullptr) {
    PyBuffer_Release(&dst);
    return nullptr;
  }
  Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
  uint64_t total = 4 + 8ull * n;
  char* base = static_cast<char*>(dst.buf);
  uint64_t cap = static_cast<uint64_t>(dst.len);
  uint32_t n32 = static_cast<uint32_t>(n);
  uint64_t pos = offset;
  if (pos + total > cap) goto overflow;
  pos += 4 + 8ull * n;  // sizes written in the loop below
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject* item = PySequence_Fast_GET_ITEM(seq, i);
    Py_buffer v;
    if (PyObject_GetBuffer(item, &v, PyBUF_CONTIG_RO) != 0) {
      Py_DECREF(seq);
      PyBuffer_Release(&dst);
      return nullptr;
    }
    uint64_t len = static_cast<uint64_t>(v.len);
    if (pos + len > cap) {
      PyBuffer_Release(&v);
      goto overflow;
    }
    std::memcpy(base + offset + 4 + 8ull * i, &len, 8);
    if (len > 0) std::memcpy(base + pos, v.buf, len);
    pos += len;
    total += len;
    PyBuffer_Release(&v);
  }
  // Publish-after-write: the frame count lands LAST, so a concurrent
  // reader of a shared segment sees either count=0 (not ready → retry)
  // or a fully written table + data — never a torn structure.
  std::atomic_thread_fence(std::memory_order_release);
  std::memcpy(base + offset, &n32, 4);
  Py_DECREF(seq);
  PyBuffer_Release(&dst);
  return PyLong_FromUnsignedLongLong(total);

overflow:
  Py_DECREF(seq);
  PyBuffer_Release(&dst);
  PyErr_SetString(PyExc_ValueError, "destination buffer too small");
  return nullptr;
}

PyMethodDef methods[] = {
    {"pack_frames", pack_frames, METH_O,
     "Pack a list of buffers into one length-prefixed blob."},
    {"frame_offsets", frame_offsets, METH_O,
     "Parse a packed blob's header into (offset, size) pairs."},
    {"write_into", write_into, METH_VARARGS,
     "Scatter a frame list into a writable buffer at an offset."},
    {nullptr, nullptr, 0, nullptr}};

PyModuleDef module = {PyModuleDef_HEAD_INIT, "_rt_native",
                      "Native data-plane codec.", -1, methods,
                      nullptr, nullptr, nullptr, nullptr};

}  // namespace

PyMODINIT_FUNC PyInit__rt_native(void) { return PyModule_Create(&module); }
