"""``python -m ray_tpu`` — cluster status/state/metrics CLI.

Capability parity with the reference's ``ray status`` / ``ray list``
CLI (reference: ``python/ray/scripts/scripts.py``,
``util/state/state_cli.py``), attaching to a running head via the
``session.json`` discovery file each head writes at startup.

Commands:
    python -m ray_tpu start --head            # standalone head daemon
    python -m ray_tpu start --address H:P     # node daemon joining a head
    python -m ray_tpu status                  # cluster summary
    python -m ray_tpu list nodes|workers|actors|placement_groups|tasks
    python -m ray_tpu metrics                 # prometheus text
    python -m ray_tpu timeline out.json       # chrome-trace export
    python -m ray_tpu dashboard               # print dashboard URL

``start --head`` keeps the control plane alive independently of any
driver (reference: ``ray start --head``); drivers then attach with
``rt.init(address="auto")`` locally, ``rt.init(address=<sock>)`` on the
same host, or ``rt.init(address="host:port")`` from another machine.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import time as _time


def _find_session(session_dir: str = "") -> dict:
    if session_dir:
        candidates = [os.path.join(session_dir, "session.json")]
    else:
        root = os.path.join(os.environ.get("TMPDIR", "/tmp"), "ray_tpu")
        candidates = sorted(
            glob.glob(os.path.join(root, "*", "session.json")),
            key=os.path.getmtime, reverse=True)
    for path in candidates:
        try:
            with open(path) as f:
                info = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        # Stale session? The head's pid must still be alive.
        try:
            os.kill(info["pid"], 0)
        except (OSError, KeyError):
            continue
        info["session_dir"] = os.path.dirname(path)
        return info
    raise SystemExit(
        "no live ray_tpu session found (is a driver running?); "
        "pass --session-dir explicitly")


def _connect(info: dict):
    import ray_tpu as rt

    rt.init(address=info["head_sock"])
    return rt


def _cmd_serve(args) -> int:
    """``serve deploy/run/status/config/shutdown`` against the running
    cluster (reference: ``serve/scripts.py``)."""
    from ray_tpu import serve
    from ray_tpu.serve import schema

    if args.serve_cmd == "deploy":
        import yaml

        with open(args.config_file) as f:
            cfg = yaml.safe_load(f)
        names = schema.deploy_config(cfg)
        print(f"deployed applications: {', '.join(names)}")
    elif args.serve_cmd == "run":
        app = schema.import_application(args.import_path)
        print(f"running app {args.name!r} at route "
              f"{args.route_prefix!r}; ctrl-c to exit")
        serve.run(app, name=args.name, route_prefix=args.route_prefix,
                  blocking=True)
    elif args.serve_cmd == "status":
        print(json.dumps(serve.status(), indent=1, default=str))
    elif args.serve_cmd == "config":
        import yaml

        cfg = schema.get_last_config()
        print(yaml.safe_dump(cfg) if cfg else "# no config deployed")
    elif args.serve_cmd == "shutdown":
        serve.shutdown()
        print("serve shut down")
    return 0


def _cmd_start(args) -> int:
    if args.address:   # join an existing head as a node daemon
        import tempfile

        from ._private import node_main
        from .api import _detect_tpu_chips

        session_dir = args.session_dir or tempfile.mkdtemp(
            prefix="ray_tpu_node_")
        # Same TPU autodetection as the head path: joining a TPU host
        # without --num-tpus must still advertise its chips.
        num_tpus = (args.num_tpus if args.num_tpus is not None
                    else float(_detect_tpu_chips()))
        argv = ["--head", args.address, "--session-dir", session_dir,
                "--num-cpus", str(args.num_cpus)]
        if num_tpus:
            argv += ["--num-tpus", str(num_tpus)]
        if getattr(args, "die_with_parent", False):
            argv += ["--die-with-parent"]
        return node_main.main(argv)
    if not args.head:
        raise SystemExit("start requires --head or --address")
    # Standalone head (reference `ray start --head`): the control plane
    # outlives any driver; session.json is the discovery file.
    import asyncio
    import time

    from ._private.accelerators import gang_resources
    from ._private.config import Config, set_global_config
    from ._private.head import HeadService
    from .api import _detect_tpu_chips

    session_dir = args.session_dir or os.path.join(
        os.environ.get("TMPDIR", "/tmp"), "ray_tpu",
        f"session_{int(time.time() * 1000)}_{os.getpid()}")
    os.makedirs(session_dir, exist_ok=True)
    config = Config({})
    set_global_config(config)
    total = {"CPU": float(args.num_cpus),
             "TPU": float(args.num_tpus if args.num_tpus is not None
                          else _detect_tpu_chips()),
             # Same default total as rt.init()'s embedded head — a
             # missing "memory" resource would strand memory-requesting
             # leases forever.
             "memory": float(os.sysconf("SC_PAGE_SIZE")
                             * os.sysconf("SC_PHYS_PAGES"))}
    for k, v in gang_resources(total["TPU"]).items():
        total.setdefault(k, v)

    from ._private import reaper

    reaper.become_subreaper()
    if getattr(args, "die_with_parent", False):
        reaper.die_with_parent()
        reaper.start_orphan_watchdog()

    async def run():
        import signal

        head = HeadService(session_dir, config, total)
        await head.start()
        print(f"head started\n  session: {session_dir}\n"
              f"  sock:    {head.sock_path}\n"
              f"  tcp:     {head.tcp_address[0]}:{head.tcp_address[1]}",
              flush=True)
        # SIGTERM (systemd/docker stop) must run head.stop() like the
        # node daemon does, not die mid-loop with a stale session.json.
        loop = asyncio.get_running_loop()
        stop = asyncio.Event()
        loop.add_signal_handler(signal.SIGTERM, stop.set)
        loop.add_signal_handler(signal.SIGINT, stop.set)
        try:
            await stop.wait()
        finally:
            await head.stop()

    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        pass
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="ray_tpu")
    parser.add_argument("--session-dir", default="",
                        help="session directory (default: newest live)")
    sub = parser.add_subparsers(dest="cmd", required=True)
    p_start = sub.add_parser("start")
    p_start.add_argument("--head", action="store_true")
    # SUPPRESS: without it the subparser's default would clobber a
    # --session-dir passed before the subcommand.
    p_start.add_argument("--session-dir", dest="session_dir",
                         default=argparse.SUPPRESS,
                         help="where session.json lands")
    p_start.add_argument("--die-with-parent", action="store_true",
                         help="SIGKILL the head when its spawner dies "
                              "(test harnesses; operators omit it)")
    p_start.add_argument("--address", default="",
                         help="join an existing head at host:port")
    p_start.add_argument("--num-cpus", type=float,
                         default=float(os.cpu_count() or 1))
    p_start.add_argument("--num-tpus", type=float, default=None)
    sub.add_parser("status")
    p_stop = sub.add_parser("stop")
    p_stop.add_argument("--force", action="store_true",
                        help="SIGKILL instead of SIGTERM")
    p_list = sub.add_parser("list")
    p_list.add_argument("kind", choices=[
        "nodes", "workers", "actors", "placement_groups", "tasks"])
    sub.add_parser("metrics")
    p_logs = sub.add_parser("logs")
    p_logs.add_argument("worker_id", nargs="?", default="",
                        help="worker id hex prefix (>=12 chars); omit "
                             "to list available log files")
    p_logs.add_argument("--bytes", type=int, default=65536)
    p_tl = sub.add_parser("timeline")
    p_tl.add_argument("output", nargs="?", default="timeline.json")
    sub.add_parser("dashboard")
    p_serve = sub.add_parser("serve")
    serve_sub = p_serve.add_subparsers(dest="serve_cmd", required=True)
    p_sdeploy = serve_sub.add_parser("deploy")
    p_sdeploy.add_argument("config_file")
    p_srun = serve_sub.add_parser("run")
    p_srun.add_argument("import_path")
    p_srun.add_argument("--name", default="default")
    p_srun.add_argument("--route-prefix", default="/")
    serve_sub.add_parser("status")
    serve_sub.add_parser("config")
    serve_sub.add_parser("shutdown")
    p_job = sub.add_parser("job")
    job_sub = p_job.add_subparsers(dest="job_cmd", required=True)
    p_submit = job_sub.add_parser("submit")
    p_submit.add_argument("entrypoint")
    p_submit.add_argument("--working-dir", default=None)
    for name in ("status", "logs", "stop"):
        p = job_sub.add_parser(name)
        p.add_argument("job_id")
    job_sub.add_parser("list")
    args = parser.parse_args(argv)

    if args.cmd == "start":
        return _cmd_start(args)
    info = _find_session(args.session_dir)
    if args.cmd == "stop":
        # Reference: ``ray stop``. SIGTERM lets the head persist state
        # and reap its workers (the child-subreaper takes orphans down
        # with it); the session file is then stale by liveness check.
        import signal as _signal

        sig = _signal.SIGKILL if args.force else _signal.SIGTERM
        try:
            os.kill(info["pid"], sig)
        except ProcessLookupError:
            # Exited between the session liveness check and the signal:
            # the desired end state already holds.
            print(f"head (pid {info['pid']}) already stopped")
            return 0
        except OSError as e:
            print(f"head pid {info['pid']}: {e}")
            return 1
        from ._private.utils import process_exited

        deadline = _time.time() + 15
        while _time.time() < deadline:
            if process_exited(info["pid"]):
                break
            _time.sleep(0.1)
        else:
            print(f"head pid {info['pid']} still shutting down "
                  "(state persists on exit); --force to SIGKILL")
            return 1
        print(f"stopped head (pid {info['pid']}, "
              f"session {info['session_dir']})")
        return 0
    if args.cmd == "job":
        from .job_submission import JobSubmissionClient

        client = JobSubmissionClient(info["head_sock"])
        if args.job_cmd == "submit":
            renv = ({"working_dir": args.working_dir}
                    if args.working_dir else None)
            print(client.submit_job(entrypoint=args.entrypoint,
                                    runtime_env=renv))
        elif args.job_cmd == "status":
            print(json.dumps(client.get_job_info(args.job_id), indent=1))
        elif args.job_cmd == "logs":
            print(client.get_job_logs(args.job_id), end="")
        elif args.job_cmd == "stop":
            print(client.stop_job(args.job_id)["status"])
        elif args.job_cmd == "list":
            print(json.dumps(client.list_jobs(), indent=1))
        return 0
    rt = _connect(info)
    try:
        if args.cmd == "serve":
            return _cmd_serve(args)
        if args.cmd == "status":
            summary = rt.state("summary")
            print(f"session: {info['session_dir']}")
            if info.get("dashboard_url"):
                print(f"dashboard: {info['dashboard_url']}")
            for k, v in summary.items():
                print(f"  {k}: {v}")
        elif args.cmd == "list":
            print(json.dumps(rt.state(args.kind), indent=1, default=str))
        elif args.cmd == "metrics":
            print(rt.metrics_text(), end="")
        elif args.cmd == "logs":
            from .core.worker import CoreWorker

            out = CoreWorker.current().head_call(
                "worker_log", {"worker_id": args.worker_id,
                               "bytes": args.bytes})
            if "files" in out:
                print("\n".join(out["files"]))
            else:
                print(out["data"], end="")
        elif args.cmd == "timeline":
            events = rt.timeline(format="chrome")
            with open(args.output, "w") as f:
                json.dump(events, f)
            print(f"wrote {len(events)} events to {args.output}")
        elif args.cmd == "dashboard":
            print(rt.dashboard_url() or "dashboard disabled")
    finally:
        rt.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
