"""Dashboard-lite: the head's HTTP observability endpoint.

Capability parity with the reference's dashboard head + metrics exporter
(reference: ``python/ray/dashboard/head.py:81`` aiohttp app;
``src/ray/stats`` prometheus exporter), collapsed into one dependency-free
asyncio HTTP server on the head:

- ``GET /metrics``        → prometheus text (cluster-merged)
- ``GET /api/state?kind=``→ JSON state listing (nodes/workers/actors/…)
- ``GET /api/timeline``   → chrome://tracing JSON events
- ``GET /``               → tiny HTML index linking the above

No aiohttp in the image, so requests are parsed by hand (GET only).
"""
from __future__ import annotations

import asyncio
import json
import os
from typing import Callable, Optional
from urllib.parse import parse_qs, urlparse


def _load_index_html() -> str:
    """The SPA ships as a sibling asset (dashboard_index.html): tabbed
    cluster/jobs/actors/workers/data/events views over /api/state,
    /api/node (per-node agent stats), /api/logs (worker log tail),
    /api/jobs + /api/job_logs, and the timeline export — the reference
    dashboard's core views (dashboard/client/src, ~22k-line React)
    rebuilt as one dependency-free page. Falls back to the embedded
    minimal page if the asset is missing from a stripped install."""
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "dashboard_index.html")
    try:
        with open(path, encoding="utf-8") as f:
            return f.read()
    except OSError:
        return _FALLBACK_HTML


# Minimal fallback UI (the full SPA lives in dashboard_index.html).
_FALLBACK_HTML = """<!doctype html>
<html><head><meta charset="utf-8"><title>ray_tpu dashboard</title>
<style>
 body{font-family:system-ui,sans-serif;margin:1.5rem;color:#222}
 h1{font-size:1.2rem} h2{font-size:1rem;margin:1.2rem 0 .3rem}
 table{border-collapse:collapse;font-size:.85rem;width:100%}
 th,td{border:1px solid #ddd;padding:.25rem .5rem;text-align:left}
 th{background:#f5f5f5} tr:nth-child(even){background:#fafafa}
 .pill{display:inline-block;padding:0 .5rem;border-radius:1rem}
 .ALIVE{background:#d9f2d9}.DEAD{background:#f7d4d4}
 .links a{margin-right:1rem} #err{color:#b00}
</style></head><body>
<h1>ray_tpu dashboard</h1>
<div class="links"><a href="/metrics">prometheus metrics</a>
<a href="/api/timeline">chrome trace</a>
<a href="/api/state?kind=summary">raw state</a></div>
<div id="err"></div>
<h2>Summary</h2><table id="summary"></table>
<h2>Nodes</h2><table id="nodes"></table>
<h2>Actors</h2><table id="actors"></table>
<h2>Workers</h2><table id="workers"></table>
<h2>Placement groups</h2><table id="placement_groups"></table>
<script>
async function fetchState(kind){
  const r = await fetch('/api/state?kind='+kind);
  if(!r.ok) throw new Error(kind+': '+r.status);
  return r.json();
}
function cell(v){
  const s = (v && typeof v === 'object') ? JSON.stringify(v) : String(v);
  // Escape before innerHTML insertion: state values carry user strings
  // (actor names, error text) that must never execute as markup.
  return s.replace(/&/g,'&amp;').replace(/</g,'&lt;')
          .replace(/>/g,'&gt;').replace(/"/g,'&quot;');
}
function renderRows(id, rows){
  const t = document.getElementById(id);
  if(!rows || !rows.length){ t.innerHTML = '<tr><td>none</td></tr>'; return; }
  const cols = Object.keys(rows[0]);
  let html = '<tr>'+cols.map(c=>'<th>'+c+'</th>').join('')+'</tr>';
  for(const row of rows){
    html += '<tr>'+cols.map(c=>{
      const v = cell(row[c]);
      const pill = (c==='state'||c==='status')
        ? ' class="pill '+v+'"' : '';
      return '<td><span'+pill+'>'+v+'</span></td>';
    }).join('')+'</tr>';
  }
  t.innerHTML = html;
}
async function refresh(){
  try{
    const s = await fetchState('summary');
    document.getElementById('summary').innerHTML =
      Object.entries(s).map(([k,v]) =>
        '<tr><th>'+k+'</th><td>'+cell(v)+'</td></tr>').join('');
    for(const kind of ['nodes','actors','workers','placement_groups'])
      renderRows(kind, await fetchState(kind));
    document.getElementById('err').textContent = '';
  }catch(e){ document.getElementById('err').textContent = e; }
}
refresh(); setInterval(refresh, 2000);
</script></body></html>
"""


async def read_get_request(reader):
    """Parse a GET request line + drain headers; returns (path, query
    dict) or None for non-GET. Shared by the head dashboard and the
    per-node agents — one HTTP parser to maintain."""
    request = await asyncio.wait_for(reader.readline(), 10)
    while True:  # drain headers
        line = await asyncio.wait_for(reader.readline(), 10)
        if line in (b"\r\n", b"\n", b""):
            break
    parts = request.decode("latin1").split()
    if len(parts) < 2 or parts[0] != "GET":
        return None
    url = urlparse(parts[1])
    return url.path, {k: v[0] for k, v in parse_qs(url.query).items()}


async def respond(writer, code: int, ctype: str, body: bytes):
    reason = {200: "OK", 404: "Not Found", 405: "Method Not Allowed"}
    head = (f"HTTP/1.1 {code} {reason.get(code, '?')}\r\n"
            f"Content-Type: {ctype}\r\n"
            f"Content-Length: {len(body)}\r\n"
            f"Connection: close\r\n\r\n").encode()
    writer.write(head + body)
    await writer.drain()


class DashboardServer:
    def __init__(self, state_fn: Callable[[str], object],
                 metrics_fn: Callable[[], str],
                 timeline_fn: Callable[[], list],
                 log_fn=None, node_fn=None,
                 jobs_fn=None, job_logs_fn=None,
                 host: str = "127.0.0.1", port: int = 0):
        self._state_fn = state_fn
        self._metrics_fn = metrics_fn
        self._timeline_fn = timeline_fn
        # async (query dict) -> {"data": str}|{"files": [...]}; serves
        # /api/logs (reference: dashboard log module).
        self._log_fn = log_fn
        # async (query dict with node_id) -> stats dict; serves
        # /api/node — the head proxying every node's agent (reference:
        # dashboard head aggregating per-node agents).
        self._node_fn = node_fn
        # async () -> [job records] and async (query) -> {"logs": str};
        # serve /api/jobs + /api/job_logs (reference: dashboard job
        # module routes).
        self._jobs_fn = jobs_fn
        self._job_logs_fn = job_logs_fn
        self._host = host
        self._port = port
        self._server: Optional[asyncio.AbstractServer] = None

    async def start(self):
        self._server = await asyncio.start_server(
            self._serve, host=self._host, port=self._port)
        self._port = self._server.sockets[0].getsockname()[1]
        return self

    @property
    def url(self) -> str:
        return f"http://{self._host}:{self._port}"

    async def stop(self):
        if self._server:
            self._server.close()
            try:
                await self._server.wait_closed()
            except Exception:
                pass

    async def _serve(self, reader: asyncio.StreamReader,
                     writer: asyncio.StreamWriter):
        try:
            parsed = await read_get_request(reader)
            if parsed is None:
                await self._respond(writer, 405, "text/plain",
                                    b"GET only")
                return
            path, q = parsed
            if path == "/metrics":
                body = self._metrics_fn().encode()
                await self._respond(
                    writer, 200, "text/plain; version=0.0.4", body)
            elif path == "/api/state":
                data = self._state_fn(q.get("kind", "summary"))
                await self._respond(writer, 200, "application/json",
                                    json.dumps(data).encode())
            elif path == "/api/timeline":
                await self._respond(
                    writer, 200, "application/json",
                    json.dumps(self._timeline_fn()).encode())
            elif path == "/api/node" and self._node_fn is not None:
                try:
                    data = await self._node_fn(q)
                    await self._respond(writer, 200, "application/json",
                                        json.dumps(data).encode())
                except Exception as e:  # noqa: BLE001 - unknown node
                    await self._respond(writer, 404, "application/json",
                                        json.dumps(
                                            {"error": str(e)}).encode())
            elif path == "/api/jobs" and self._jobs_fn is not None:
                data = await self._jobs_fn()
                await self._respond(writer, 200, "application/json",
                                    json.dumps(data).encode())
            elif path == "/api/job_logs" and self._job_logs_fn is not None:
                try:
                    data = await self._job_logs_fn(q)
                    await self._respond(writer, 200, "application/json",
                                        json.dumps(data).encode())
                except Exception as e:  # noqa: BLE001 - unknown job
                    await self._respond(writer, 404, "application/json",
                                        json.dumps(
                                            {"error": str(e)}).encode())
            elif path == "/api/logs" and self._log_fn is not None:
                try:
                    data = await self._log_fn(q)
                    await self._respond(writer, 200, "application/json",
                                        json.dumps(data).encode())
                except Exception as e:  # noqa: BLE001 - missing log file
                    await self._respond(writer, 404, "application/json",
                                        json.dumps(
                                            {"error": str(e)}).encode())
            elif path == "/":
                await self._respond(writer, 200, "text/html",
                                    _load_index_html().encode())
            else:
                await self._respond(writer, 404, "text/plain",
                                    b"not found")
        except Exception:  # noqa: BLE001 - a bad client mustn't kill the head
            pass
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:
                pass

    _respond = staticmethod(respond)
