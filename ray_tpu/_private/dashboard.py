"""Dashboard-lite: the head's HTTP observability endpoint.

Capability parity with the reference's dashboard head + metrics exporter
(reference: ``python/ray/dashboard/head.py:81`` aiohttp app;
``src/ray/stats`` prometheus exporter), collapsed into one dependency-free
asyncio HTTP server on the head:

- ``GET /metrics``        → prometheus text (cluster-merged)
- ``GET /api/state?kind=``→ JSON state listing (nodes/workers/actors/…)
- ``GET /api/timeline``   → chrome://tracing JSON events
- ``GET /``               → tiny HTML index linking the above

No aiohttp in the image, so requests are parsed by hand (GET only).
"""
from __future__ import annotations

import asyncio
import json
from typing import Callable, Optional
from urllib.parse import parse_qs, urlparse


class DashboardServer:
    def __init__(self, state_fn: Callable[[str], object],
                 metrics_fn: Callable[[], str],
                 timeline_fn: Callable[[], list],
                 host: str = "127.0.0.1", port: int = 0):
        self._state_fn = state_fn
        self._metrics_fn = metrics_fn
        self._timeline_fn = timeline_fn
        self._host = host
        self._port = port
        self._server: Optional[asyncio.AbstractServer] = None

    async def start(self):
        self._server = await asyncio.start_server(
            self._serve, host=self._host, port=self._port)
        self._port = self._server.sockets[0].getsockname()[1]
        return self

    @property
    def url(self) -> str:
        return f"http://{self._host}:{self._port}"

    async def stop(self):
        if self._server:
            self._server.close()
            try:
                await self._server.wait_closed()
            except Exception:
                pass

    async def _serve(self, reader: asyncio.StreamReader,
                     writer: asyncio.StreamWriter):
        try:
            request = await asyncio.wait_for(reader.readline(), 10)
            while True:  # drain headers
                line = await asyncio.wait_for(reader.readline(), 10)
                if line in (b"\r\n", b"\n", b""):
                    break
            parts = request.decode("latin1").split()
            if len(parts) < 2 or parts[0] != "GET":
                await self._respond(writer, 405, "text/plain",
                                    b"GET only")
                return
            url = urlparse(parts[1])
            q = {k: v[0] for k, v in parse_qs(url.query).items()}
            if url.path == "/metrics":
                body = self._metrics_fn().encode()
                await self._respond(
                    writer, 200, "text/plain; version=0.0.4", body)
            elif url.path == "/api/state":
                data = self._state_fn(q.get("kind", "summary"))
                await self._respond(writer, 200, "application/json",
                                    json.dumps(data).encode())
            elif url.path == "/api/timeline":
                await self._respond(
                    writer, 200, "application/json",
                    json.dumps(self._timeline_fn()).encode())
            elif url.path == "/":
                body = (b"<html><body><h3>ray_tpu dashboard</h3><ul>"
                        b'<li><a href="/metrics">/metrics</a></li>'
                        b'<li><a href="/api/state?kind=summary">'
                        b"/api/state</a></li>"
                        b'<li><a href="/api/timeline">/api/timeline</a>'
                        b"</li></ul></body></html>")
                await self._respond(writer, 200, "text/html", body)
            else:
                await self._respond(writer, 404, "text/plain",
                                    b"not found")
        except Exception:  # noqa: BLE001 - a bad client mustn't kill the head
            pass
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:
                pass

    @staticmethod
    async def _respond(writer, code: int, ctype: str, body: bytes):
        reason = {200: "OK", 404: "Not Found", 405: "Method Not Allowed"}
        head = (f"HTTP/1.1 {code} {reason.get(code, '?')}\r\n"
                f"Content-Type: {ctype}\r\n"
                f"Content-Length: {len(body)}\r\n"
                f"Connection: close\r\n\r\n").encode()
        writer.write(head + body)
        await writer.drain()
