"""Crash-durable flight recorder: the cluster's black box (ISSUE 19).

Every process gets one :func:`emit`-style structured event API backed by
a **preallocated mmap'd ring file**, so the last-N events of ANY process
— including one that just took a SIGKILL — are readable from disk
afterwards. Spans (``util/tracing.py``) explain a request that finished;
this module explains the one that didn't: the event that was half
written when the process died is the torn final record, everything
before it is intact.

Durability model (the same kill-survival contract as
``_private/wal.py``, adapted from append-only frames to a fixed ring):
mmap stores land in the kernel page cache, which survives process death
(power loss is out of scope). Each slot commits with a
write-payload → write-length+CRC → write-seq protocol, seq last, so a
reader accepts a slot only when its seq is stamped AND its CRC matches
— a kill between any two stores yields exactly one torn slot, which
the reader tolerates and counts.

Event shape: ``emit(kind, **attrs)``. Three attrs are the correlation
vocabulary the post-mortem collector (``tools/rtblackbox``) joins on:

- ``request=`` — the router-stamped request id (``rq-<pid>-<n>``),
  carried across proxy → router → prefill handoff → decode → resume;
- ``lane=`` — the engine stream lane serving the request;
- ``epoch=`` — the engine driver epoch (restart generation).

Every record carries BOTH clocks: ``time.monotonic()`` for ordering
(CLOCK_MONOTONIC is machine-wide, so events of different processes on
one host merge without trusting wall clocks) and ``time.time()`` for
human labels. The ring header stores a (wall, monotonic) **anchor**
pair plus the host boot id; the collector uses one reference anchor per
boot domain to place every process's monotonic stamps on a single
timeline — a process with a skewed wall clock merges in the right
order anyway.

Cost contract (pinned by tests):

- **disabled** (no ``RT_EVENTS_DIR``): :func:`emit`/:func:`driver_emit`
  short-circuit on one module-global load — no dict churn past the
  kwargs build, no lock, no I/O, and the ring machinery is never
  constructed;
- **enabled**: per-kind token-bucket rate caps bound the write rate, so
  a dispatch-per-token storm costs capped ring writes plus cheap
  dropped-count increments — the ring file never grows (preallocated)
  and low-rate kinds are never flooded out by a hot one.

``driver_emit`` is THE helper for ``owner=driver`` hot loops (rtlint
RT112 enforces this): identical fast path, tighter default cap, and a
documented promise that it never raises and never blocks on anything
but the recorder's own mutex.
"""
from __future__ import annotations

import os
import pickle
import struct
import threading
import time
import zlib
from typing import Any, Dict, Optional

#: Environment switch: a directory path enables the recorder in every
#: process that inherits the environment (workers inherit os.environ
#: through the node daemon's spawn env). Unset = recorder fully off.
EVENTS_DIR_ENV = "RT_EVENTS_DIR"

#: Ring geometry defaults: 4096 slots x 512 bytes = a 2 MiB file plus
#: one header page per process. ~4k events of last-N is hours of
#: control-plane history or seconds of a dispatch storm — exactly the
#: window a post-mortem needs.
DEFAULT_SLOTS = 4096
DEFAULT_SLOT_SIZE = 512
HEADER_SIZE = 4096

#: Per-kind token-bucket caps (events/second, sustained; burst is 2x).
#: ``driver_emit`` uses the tighter driver cap so the engine hot loop
#: can call it per dispatch without ever flooding the ring.
DEFAULT_RATE_PER_S = 500.0
DRIVER_RATE_PER_S = 200.0

_MAGIC = b"RTEVRING1\0"
#: Header: magic, version, slot_size, n_slots, pid, wall anchor,
#: monotonic anchor, boot id (36 ascii), process label (64 utf-8).
_HEADER = struct.Struct("<10sHIIIdd36s64s")
#: Slot prefix: seq (0 = never committed), payload length, CRC32.
_SLOT = struct.Struct("<QII")


def _boot_id() -> str:
    """Host boot identity: monotonic clocks are comparable exactly
    within one boot of one machine."""
    try:
        with open("/proc/sys/kernel/random/boot_id") as f:
            return f.read().strip()[:36]
    except OSError:
        return ""


class Recorder:
    """One process's ring writer. Thread-safe: emits come from router
    threads, replica request threads, AND the engine driver thread, so
    the slot claim + store runs under one short mutex (no I/O inside —
    the mmap store is a memcpy into the page cache)."""

    def __init__(self, path: str, proc: str = "", *,
                 n_slots: int = DEFAULT_SLOTS,
                 slot_size: int = DEFAULT_SLOT_SIZE,
                 rate_per_s: float = DEFAULT_RATE_PER_S,
                 wall_skew_s: float = 0.0):
        import mmap

        self.path = path
        self.proc = proc or f"proc-{os.getpid()}"
        self.n_slots = int(n_slots)
        self.slot_size = int(slot_size)
        self.rate_per_s = float(rate_per_s)
        #: Test hook ONLY: pretend this process's wall clock is skewed
        #: (anchor and every record), so merge-ordering tests can prove
        #: the collector orders by monotonic anchors, not wall time.
        self._wall_skew = float(wall_skew_s)
        size = HEADER_SIZE + self.n_slots * self.slot_size
        # Preallocate the whole ring up front: emit never extends the
        # file, so a storm can't grow it and a full disk fails HERE
        # (at enable time), never in a hot loop.
        fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o644)
        try:
            os.ftruncate(fd, size)
            self._mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        self._lock = threading.Lock()
        self._seq = 0                      # last committed seq
        self.emitted = 0
        self.dropped: Dict[str, int] = {}  # kind -> rate-capped drops
        self.truncated = 0                 # attrs too big for a slot
        self._buckets: Dict[str, list] = {}  # kind -> [tokens, last_t]
        self.wall_anchor = time.time() + self._wall_skew
        self.mono_anchor = time.monotonic()
        self._mm[0:_HEADER.size] = _HEADER.pack(
            _MAGIC, 1, self.slot_size, self.n_slots, os.getpid(),
            self.wall_anchor, self.mono_anchor,
            _boot_id().encode("ascii", "replace").ljust(36, b"\0")[:36],
            self.proc.encode("utf-8", "replace").ljust(64, b"\0")[:64])

    # ------------------------------------------------------------- emit
    def emit(self, kind: str, attrs: Dict[str, Any],
             rate_per_s: Optional[float] = None) -> bool:
        """Record one event; returns False when the kind's rate cap
        dropped it. Never raises: a recorder failure must never take
        down the loop it observes."""
        mono = time.monotonic()
        with self._lock:
            if not self._take_token(kind, mono, rate_per_s):
                self.dropped[kind] = self.dropped.get(kind, 0) + 1
                _count_dropped(kind)
                return False
            seq = self._seq + 1
            payload = self._encode(kind, mono, attrs)
            off = HEADER_SIZE + ((seq - 1) % self.n_slots) * self.slot_size
            try:
                # Commit protocol (kill-safe): invalidate, payload,
                # len+crc, seq LAST. A SIGKILL between any two of these
                # stores leaves a slot the reader rejects (seq zero or
                # CRC mismatch) — the one torn record the format
                # tolerates.
                self._mm[off:off + 8] = b"\0" * 8
                body = off + _SLOT.size
                self._mm[body:body + len(payload)] = payload
                self._mm[off + 8:off + _SLOT.size] = struct.pack(
                    "<II", len(payload), zlib.crc32(payload))
                self._mm[off:off + 8] = struct.pack("<Q", seq)
            except (OSError, ValueError):
                return False
            self._seq = seq
            self.emitted += 1
            return True

    def _take_token(self, kind: str, now: float,
                    rate_per_s: Optional[float]) -> bool:
        """Per-kind token bucket, held under ``_lock``: sustained rate
        ``rate_per_s``, burst 2x. The cap is the storm guarantee — a
        dispatch-per-token flood costs one dict increment per drop."""
        rate = self.rate_per_s if rate_per_s is None else float(rate_per_s)
        if rate <= 0:
            return True
        b = self._buckets.get(kind)
        if b is None:
            self._buckets[kind] = [2.0 * rate - 1.0, now]
            return True
        b[0] = min(2.0 * rate, b[0] + (now - b[1]) * rate)
        b[1] = now
        if b[0] < 1.0:
            return False
        b[0] -= 1.0
        return True

    def _encode(self, kind: str, mono: float,
                attrs: Dict[str, Any]) -> bytes:
        wall = time.time() + self._wall_skew
        cap = self.slot_size - _SLOT.size
        try:
            payload = pickle.dumps((mono, wall, kind, attrs), protocol=4)
        except Exception:  # noqa: BLE001 - unpicklable attr value
            payload = None
        if payload is None or len(payload) > cap:
            # Too big / unpicklable: keep the correlation ids, drop the
            # rest — a truncated record still joins the timeline.
            self.truncated += 1
            core = {k: attrs[k] for k in ("request", "lane", "epoch")
                    if k in attrs}
            core["truncated"] = True
            payload = pickle.dumps((mono, wall, kind, core), protocol=4)
            payload = payload[:cap] if len(payload) <= cap else \
                pickle.dumps((mono, wall, kind,
                              {"truncated": True}), protocol=4)
        return payload

    # ------------------------------------------------------------ stats
    def fill(self) -> float:
        """Fraction of the ring holding live records (1.0 once the ring
        has wrapped and every slot is a recent event)."""
        return min(self._seq, self.n_slots) / float(self.n_slots)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {"enabled": True, "path": self.path,
                    "ring_fill": round(self.fill(), 4),
                    "emitted": self.emitted,
                    "truncated": self.truncated,
                    "dropped": dict(self.dropped),
                    "dropped_total": sum(self.dropped.values())}

    def flush(self):
        """Best-effort msync — NOT required for kill-durability (the
        page cache survives the process); only narrows the power-loss
        window for tests that want it."""
        try:
            self._mm.flush()
        except (OSError, ValueError):
            pass

    def close(self):
        with self._lock:
            try:
                self._mm.close()
            except (OSError, ValueError):
                pass


# ------------------------------------------------------------- module API
_init_lock = threading.Lock()
_recorder: Optional[Recorder] = None
#: Tri-state fast path: False until the env decision is made, True
#: after. Disabled processes pay exactly one global load + one branch
#: per emit call after the first.
_resolved = False


def ring_path(directory: str, proc: str = "") -> str:
    """Per-process ring file name: process label, pid, and a start
    stamp so a recycled pid never collides with a dead ring."""
    label = (proc or "proc").replace(os.sep, "_")
    return os.path.join(
        directory, f"{label}-{os.getpid()}-{int(time.time() * 1000)}.evr")


def _default_proc_label() -> str:
    import sys

    base = os.path.basename(sys.argv[0] or "py").rsplit(".py", 1)[0]
    return base or "py"


def init(directory: Optional[str] = None, proc: str = "",
         **kw) -> Optional[Recorder]:
    """Explicitly enable the recorder for this process (tests and
    tools; servers normally enable via ``RT_EVENTS_DIR``). Idempotent:
    a second init returns the live recorder."""
    global _recorder, _resolved
    with _init_lock:
        if _recorder is not None:
            return _recorder
        directory = directory or os.environ.get(EVENTS_DIR_ENV)
        if not directory:
            _resolved = True
            return None
        try:
            os.makedirs(directory, exist_ok=True)
            _recorder = Recorder(
                ring_path(directory, proc or _default_proc_label()),
                proc or _default_proc_label(), **kw)
        except Exception:  # noqa: BLE001 - an unwritable events dir
            # must degrade to disabled, never break the host process.
            _recorder = None
        _resolved = True
        return _recorder


def enabled() -> bool:
    return (_recorder if _resolved else init()) is not None


def emit(kind: str, **attrs) -> bool:
    """Structured event emission for control-plane and request-plane
    paths (router, replica, controller, lease table). Rate-capped per
    kind; a true no-op when the recorder is disabled."""
    rec = _recorder
    if rec is None:
        if _resolved:
            return False
        rec = init()
        if rec is None:
            return False
    return rec.emit(kind, attrs)


def driver_emit(kind: str, **attrs) -> bool:
    """THE emission helper for ``owner=driver`` hot loops (rtlint
    RT112): same fast no-op when disabled, tighter sustained rate cap
    when enabled, never raises, never blocks beyond the recorder mutex.
    """
    rec = _recorder
    if rec is None:
        if _resolved:
            return False
        rec = init()
        if rec is None:
            return False
    return rec.emit(kind, attrs, rate_per_s=DRIVER_RATE_PER_S)


def stats() -> Dict[str, Any]:
    """This process's recorder stats — the ``events`` block engines and
    replicas surface (ring fill fraction, per-kind dropped counts)."""
    rec = _recorder
    if rec is None:
        return {"enabled": False}
    return rec.stats()


def recorder() -> Optional[Recorder]:
    return _recorder


def _reset_for_tests():
    """Drop the process-global recorder so a test can re-init against a
    fresh directory (testing only)."""
    global _recorder, _resolved
    with _init_lock:
        if _recorder is not None:
            _recorder.close()
        _recorder = None
        _resolved = False


def _count_dropped(kind: str):
    """Mirror a rate-capped drop into ``rt_events_dropped_total``.
    Called under the recorder lock on the drop path only — the storm
    cost is one counter-dict increment per dropped event."""
    try:
        from .metrics import serve_metrics

        serve_metrics()["events_dropped"].inc(labels={"kind": kind})
    except Exception:  # noqa: BLE001 - metrics must never break emit
        pass


# ------------------------------------------------------------- ring read
def read_ring(path: str) -> Dict[str, Any]:
    """Read one ring file — typically a DEAD process's — back into
    ``{"proc", "pid", "wall_anchor", "mono_anchor", "boot_id",
    "events": [...], "torn": n}``. Events carry ``seq``, ``mono``,
    ``wall``, ``kind``, ``attrs`` and come back seq-ordered. A slot
    whose seq is stamped but whose CRC or pickle does not check out is
    the torn final record the format tolerates: counted, skipped."""
    with open(path, "rb") as f:
        data = f.read()
    if len(data) < _HEADER.size or not data.startswith(_MAGIC):
        raise ValueError(f"{path} is not an rtevents ring file")
    (_, version, slot_size, n_slots, pid, wall_anchor, mono_anchor,
     boot, proc) = _HEADER.unpack_from(data, 0)
    out = {
        "path": path, "version": version,
        "proc": proc.rstrip(b"\0").decode("utf-8", "replace"),
        "pid": pid, "wall_anchor": wall_anchor,
        "mono_anchor": mono_anchor,
        "boot_id": boot.rstrip(b"\0").decode("ascii", "replace"),
        "n_slots": n_slots, "slot_size": slot_size,
        "events": [], "torn": 0,
    }
    for i in range(n_slots):
        off = HEADER_SIZE + i * slot_size
        if off + _SLOT.size > len(data):
            break
        seq, length, crc = _SLOT.unpack_from(data, off)
        if seq == 0:
            continue
        body = data[off + _SLOT.size:off + _SLOT.size + length]
        if length > slot_size - _SLOT.size or len(body) < length \
                or zlib.crc32(body) != crc:
            out["torn"] += 1
            continue
        try:
            mono, wall, kind, attrs = pickle.loads(body)
        except Exception:  # noqa: BLE001 - torn payload, same tolerance
            out["torn"] += 1
            continue
        out["events"].append({"seq": seq, "mono": mono, "wall": wall,
                              "kind": kind, "attrs": attrs})
    out["events"].sort(key=lambda e: e["seq"])
    return out
