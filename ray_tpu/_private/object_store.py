"""Dual-tier object store: in-process memory store + host shared memory.

Capability parity with the reference's split between the core-worker memory
store for small objects and the plasma shared-memory store for large ones
(reference: ``src/ray/core_worker/store_provider/memory_store/memory_store.h:43``
vs ``plasma_store_provider.h:88``, plasma arena ``src/ray/object_manager/plasma/``),
re-designed for this runtime:

- Objects are stored as *frame lists* (pickle-5 header/body + out-of-band
  buffers) so numpy/jax host buffers round-trip zero-copy.
- Small objects (<= ``max_inline_object_size``) live in the owner process and
  travel inline in task specs / replies.
- Large objects are written once into a named POSIX shared-memory segment;
  any process on the host maps it read-only (zero-copy ``np.frombuffer``
  views). On TPU hosts this doubles as the staging area for
  ``jax.device_put``.
- Spilling: segments overflow to disk files under the spill directory when
  the shm budget is exhausted (LRU by insertion order).
"""
from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from multiprocessing import shared_memory, resource_tracker
from typing import Dict, List, Optional

from .ids import ObjectID
from .serialization import pack_frames, unpack_frames


def _open_shm(name: str, create: bool = False, size: int = 0):
    """Open a shm segment WITHOUT resource-tracker registration.

    The stdlib tracker unlinks segments when *any* attaching process exits;
    for a cross-process store only the owner may unlink, so we suppress
    registration entirely (this store manages lifetimes itself).
    """
    orig = resource_tracker.register
    resource_tracker.register = lambda *a, **k: None
    try:
        return shared_memory.SharedMemory(name=name, create=create, size=size)
    finally:
        resource_tracker.register = orig


def sweep_domain_segments(domain: str) -> int:
    """Unlink every shm segment of one shm DOMAIN (its name prefix is
    derived from the domain string). For synthetic per-cluster domains
    this is safe teardown hygiene — SIGKILL chaos leaves segments whose
    creators died without unlinking; nothing outside the owning cluster
    can hold that domain. Never call it for the shared host domain.
    Returns the number of segments removed."""
    import hashlib

    prefix = "rt_" + hashlib.sha1(domain.encode()).hexdigest()[:6] + "_"
    removed = 0
    try:
        for name in os.listdir("/dev/shm"):
            if name.startswith(prefix):
                try:
                    os.unlink(os.path.join("/dev/shm", name))
                    removed += 1
                except OSError:
                    pass
    except OSError:
        pass
    return removed


class MemoryStore:
    """In-process object store with blocking waiters (thread-safe).

    Two wake-up mechanisms: a per-object event for ``get`` blockers, and
    registered multi-object watcher events so ``wait()`` over N refs parks
    on ONE event instead of polling (reference: event-driven
    ``CoreWorker::Wait``, ``core_worker.cc:1735``).
    """

    def __init__(self):
        self._objects: Dict[ObjectID, List[bytes]] = {}
        # RLock: ObjectRef.__del__ (cyclic GC) can re-enter delete() while
        # this thread is inside put()/get() — a plain Lock would self-deadlock.
        self._lock = threading.RLock()
        self._events: Dict[ObjectID, threading.Event] = {}
        self._watchers: Dict[ObjectID, List[threading.Event]] = {}

    def put(self, object_id: ObjectID, frames: List[bytes]) -> None:
        with self._lock:
            self._objects[object_id] = frames
            ev = self._events.pop(object_id, None)
            watchers = self._watchers.pop(object_id, ())
        if ev:
            ev.set()
        for w in watchers:
            w.set()

    def put_many(self, items) -> None:
        """Store ``[(object_id, frames), ...]`` under one lock pass —
        reply ingestion lands whole chunks at once."""
        to_set = []
        with self._lock:
            for object_id, frames in items:
                self._objects[object_id] = frames
                ev = self._events.pop(object_id, None)
                if ev:
                    to_set.append(ev)
                to_set.extend(self._watchers.pop(object_id, ()))
        for ev in to_set:
            ev.set()

    def add_watcher(self, object_id: ObjectID, ev: threading.Event) -> None:
        """Fire ``ev`` when the object arrives (immediately if present)."""
        with self._lock:
            if object_id in self._objects:
                ev.set()
                return
            self._watchers.setdefault(object_id, []).append(ev)

    def remove_watcher(self, object_id: ObjectID, ev: threading.Event) -> None:
        with self._lock:
            ws = self._watchers.get(object_id)
            if ws is not None:
                try:
                    ws.remove(ev)
                except ValueError:
                    pass
                if not ws:
                    self._watchers.pop(object_id, None)

    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            return object_id in self._objects

    def get_many(self, object_ids) -> dict:
        """Snapshot whichever of ``object_ids`` are present — one lock
        pass for a whole ``get([refs])`` burst."""
        out = {}
        with self._lock:
            objs = self._objects
            for oid in object_ids:
                frames = objs.get(oid)
                if frames is not None:
                    out[oid] = frames
        return out

    def get(self, object_id: ObjectID, timeout: Optional[float] = None):
        with self._lock:
            if object_id in self._objects:
                return self._objects[object_id]
            ev = self._events.setdefault(object_id, threading.Event())
        if not ev.wait(timeout):
            return None
        with self._lock:
            return self._objects.get(object_id)

    def delete(self, object_id: ObjectID) -> None:
        with self._lock:
            self._objects.pop(object_id, None)

    def size(self) -> int:
        with self._lock:
            return len(self._objects)


class SharedMemoryStore:
    """Host-wide store of immutable objects in named shm segments.

    The *owner* process creates segments and is responsible for unlinking.
    Reader processes attach by name (zero-copy).
    """

    #: Default lifetime of a pending (create_pending → seal/abort)
    #: reservation. A puller that dies between reserve and seal — the
    #: task cancelled so hard its abort never ran, a thread killed by a
    #: process-level fault — would otherwise pin its reserved bytes
    #: (and squat the segment name) forever; the sweep reclaims on the
    #: same lease-clock discipline as the serve handoff plane
    #: (ISSUE 14 satellite). Generous against the slowest legitimate
    #: transfer: GiB-scale pulls finish in seconds.
    PENDING_TTL_S = 120.0

    def __init__(self, capacity_bytes: int, spill_dir: str = "",
                 domain: str = "", pending_ttl_s: float = 0.0):
        self._capacity = capacity_bytes
        self._used = 0
        # RLock: see MemoryStore — the GC free path may re-enter delete().
        self._lock = threading.RLock()
        # object_id -> (shm handle or None, nbytes, spilled_path or None)
        self._owned: "OrderedDict[ObjectID, tuple]" = OrderedDict()
        self._attached: Dict[ObjectID, shared_memory.SharedMemory] = {}
        # In-progress chunked transfers (create_pending → seal/abort):
        # object_id -> (shm, nbytes, num_frames, reserved_at)
        self._pending: Dict[ObjectID, tuple] = {}
        self._pending_ttl = float(pending_ttl_s) or self.PENDING_TTL_S
        self._spill_dir = spill_dir or os.path.join(
            os.environ.get("TMPDIR", "/tmp"), "rt_spill"
        )
        # Segment names are scoped by shm domain: processes on the same
        # host (same domain) agree on names and attach each other's
        # segments; different domains — real remote hosts, or synthetic
        # test nodes modelling them — cannot see each other's objects
        # and must go through the transfer protocol.
        import hashlib

        self._prefix = (hashlib.sha1(domain.encode()).hexdigest()[:6] + "_"
                        if domain else "")

    def _name(self, object_id: ObjectID) -> str:
        return "rt_" + self._prefix + object_id.hex()[:30]

    @staticmethod
    def _clear_if_stale(name: str) -> bool:
        """True if the named segment was a half-written leftover (frame
        count still 0 — a crashed or in-flight chunked pull) and was
        unlinked. A COMPLETE segment is left alone: task results are
        idempotent, the existing copy is the same value."""
        try:
            shm = _open_shm(name)
        except FileNotFoundError:
            return True  # vanished under us: name is free now
        try:
            stale = bytes(shm.buf[:4]) == b"\x00\x00\x00\x00"
            if stale:
                shm.unlink()
            return stale
        finally:
            shm.close()

    def create(self, object_id: ObjectID, frames: List[bytes]) -> int:
        """Write frames into a new segment. Returns total bytes.

        Frames scatter straight into the mapped segment (native codec)
        — no intermediate packed blob, one copy total."""
        from .serialization import pack_frames_into, packed_size

        n = packed_size(frames)
        with self._lock:
            if self._used + n > self._capacity:
                self._spill_lru(self._used + n - self._capacity)
            try:
                shm = _open_shm(self._name(object_id), create=True, size=n)
            except FileExistsError:
                # A half-written leftover (e.g. a pull racing this
                # producer — lineage recovery while a consumer pulls)
                # must NOT suppress the write: readers would wedge on a
                # count-0 segment that no one will ever finish.
                if not self._clear_if_stale(self._name(object_id)):
                    return n  # complete copy already here (idempotent)
                try:
                    shm = _open_shm(self._name(object_id), create=True,
                                    size=n)
                except FileExistsError:
                    return n  # recreated concurrently: defer to it
            pack_frames_into(shm.buf, 0, frames)
            self._owned[object_id] = (shm, n, None)
            self._used += n
        return n

    def create_pending(self, object_id: ObjectID, frame_sizes):
        """Reserve a segment an incoming chunked transfer writes into
        DIRECTLY (no staging buffer — at GiB sizes a second fresh
        allocation measurably hurts, see benchmarks/broadcast_bench.py).
        The size table is written here (this store owns the packed
        layout, shared with ``serialization.unpack_frames``); the caller
        fills the returned PAYLOAD view, then :meth:`seal` publishes.
        Until then the 4-byte frame count is zero, so concurrent
        attachers see not-ready (the ``_safe_unpack`` contract), never
        torn frames. Returns None if the object already has a segment
        (or pending transfer) here."""
        import struct as _struct

        header = _struct.pack("<I", 0) + b"".join(
            _struct.pack("<Q", s) for s in frame_sizes)
        nbytes = len(header) + sum(frame_sizes)
        # Reclaim abandoned reservations FIRST: a crashed puller's
        # leftover must neither hold capacity against this transfer nor
        # squat the segment name it happens to share.
        self.sweep_pending()
        with self._lock:
            if object_id in self._pending:
                # A transfer of this object is already in flight in THIS
                # process; a second writer would corrupt the first's
                # bookkeeping at seal time.
                return None
            if self._used + nbytes > self._capacity:
                self._spill_lru(self._used + nbytes - self._capacity)
            try:
                shm = _open_shm(self._name(object_id), create=True,
                                size=nbytes)
            except FileExistsError:
                return None
            # Reserve now: concurrent pending transfers must see each
            # other's bytes or the store overcommits its capacity.
            self._used += nbytes
            self._pending[object_id] = (shm, nbytes, len(frame_sizes),
                                        time.monotonic())
        shm.buf[4:len(header)] = header[4:]
        return memoryview(shm.buf)[len(header):]

    def seal(self, object_id: ObjectID, view=None) -> None:
        """Publish a pending segment: the frame count lands LAST.

        ``view`` (the payload view ``create_pending`` returned) lets a
        writer prove the entry is still ITS OWN: a puller that stalled
        past the TTL may find its reservation swept — and possibly
        re-created by a retrying writer. Sealing the NEW writer's
        half-written segment would publish torn bytes, so a mismatched
        (or missing) entry raises instead; the caller re-pulls.

        Plain Python stores publish-after-write — like the pure-Python
        ``pack_frames_into`` path, ordering is guaranteed on TSO
        hardware (x86, every supported TPU VM host); weakly-ordered
        CPUs would need the native codec's release fence here."""
        import struct as _struct

        with self._lock:
            ent = self._pending.get(object_id)
            if ent is None:
                raise RuntimeError(
                    f"pending transfer for {object_id} was swept "
                    f"(TTL) or aborted before seal; retry the pull")
            shm, n, num_frames, _t = ent
            if view is not None and view.obj is not shm._mmap:
                raise RuntimeError(
                    f"pending transfer for {object_id} was swept and "
                    f"re-created by another writer; this writer's "
                    f"bytes are gone — retry the pull")
            del self._pending[object_id]
            shm.buf[:4] = _struct.pack("<I", num_frames)
            self._owned[object_id] = (shm, n, None)

    def sweep_pending(self, ttl_s: Optional[float] = None,
                      now: Optional[float] = None) -> int:
        """Abort pending reservations older than the TTL (crashed or
        wedged pullers that never reached seal/abort): their reserved
        bytes return to the capacity budget and their count-0 segments
        unlink so a new writer can claim the name. Returns how many
        were reclaimed. Runs opportunistically on every
        ``create_pending`` (the lease clock needs no dedicated thread);
        ``ttl_s``/``now`` exist for tests."""
        ttl = self._pending_ttl if ttl_s is None else float(ttl_s)
        now = time.monotonic() if now is None else now
        with self._lock:
            expired = [oid for oid, ent in self._pending.items()
                       if now - ent[3] > ttl]
        # The abort happens outside the scan lock, so the entry may have
        # been aborted by its own writer and re-created by a NEW one in
        # between — ``stamped_before`` makes abort_pending re-check the
        # expiry under ITS lock (a fresh reservation carries a fresh
        # stamp) instead of tearing down the new writer's segment.
        return sum(1 for oid in expired
                   if self.abort_pending(oid, stamped_before=now - ttl))

    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending)

    def clear_stale_segment(self, object_id: ObjectID) -> bool:
        """Unlink a half-written (count-0) segment left by a crashed
        transfer so a new writer can claim the name."""
        return self._clear_if_stale(self._name(object_id))

    def abort_pending(self, object_id: ObjectID, view=None,
                      stamped_before: Optional[float] = None) -> bool:
        """Drop a pending segment after a failed transfer. ``view``
        (see :meth:`seal`) guards the swept-and-re-created race: a
        stale writer's abort must not tear down the NEW writer's
        reservation. ``stamped_before`` is the sweeper's equivalent
        guard — only an entry reserved before that monotonic instant is
        aborted. Returns True if an entry was actually dropped."""
        with self._lock:
            ent = self._pending.get(object_id)
            if ent is None:
                return False
            if view is not None and view.obj is not ent[0]._mmap:
                return False    # someone else's reservation now
            if stamped_before is not None and ent[3] >= stamped_before:
                return False    # re-created after the sweep scan
            del self._pending[object_id]
            shm, n = ent[0], ent[1]
            self._used -= n
        # Unlink FIRST (independent of open mappings): close() raises
        # BufferError while the writer's aborted view is still alive,
        # which must not leave the count-0 segment squatting the name.
        # And only unlink if the name still maps to OUR inode — a
        # clobbering producer may have re-created a complete segment
        # under this name (see _clear_if_stale), which must survive.
        try:
            mine = os.fstat(shm._fd).st_ino == os.stat(
                f"/dev/shm/{shm.name.lstrip('/')}").st_ino
        except OSError:
            mine = False  # name already gone or unreadable
        try:
            if mine:
                shm.unlink()
        except Exception:  # noqa: BLE001 - best-effort cleanup
            pass
        try:
            shm.close()
        except BufferError:
            pass  # writer's view still alive; fd goes with the process
        return True

    @staticmethod
    def _safe_unpack(buf) -> Optional[List[memoryview]]:
        """A reader can attach between the owner's segment create and its
        frame write and observe zeros or a half-written size table.
        Serialized values always carry ≥2 frames (header + pickle body),
        so fewer — or a malformed table — means not-ready → None, letting
        the caller's wait/pull path retry. Only valid on *attach* paths:
        owned/spilled entries are fully written before registration, so
        malformed data there is corruption and must raise."""
        try:
            frames = unpack_frames(buf)
        except ValueError:
            return None
        if len(frames) < 2:
            return None
        return frames

    def get(self, object_id: ObjectID) -> Optional[List[memoryview]]:
        # Read-only views (reference: plasma buffers are immutable):
        # deserialized numpy/jax arrays alias the segment zero-copy, so
        # a writable view would let user code corrupt the stored value
        # for every other reader.
        with self._lock:
            ent = self._owned.get(object_id)
            if ent is not None:
                shm, n, path = ent
                if shm is not None:
                    return unpack_frames(
                        memoryview(shm.buf)[:n].toreadonly())
                with open(path, "rb") as f:  # spilled
                    return unpack_frames(f.read())
            if object_id in self._attached:
                shm = self._attached[object_id]
                frames = self._safe_unpack(
                    memoryview(shm.buf).toreadonly())
                if frames is not None:
                    return frames
                # Not ready. The mapping may be an orphaned inode (the
                # segment was cleared and re-created under this name by
                # a racing writer): drop it so THIS call re-opens by
                # NAME and sees the live segment.
                self._attached.pop(object_id, None)
        # Attach to a segment owned by another process on this host.
        try:
            shm = _open_shm(self._name(object_id))
        except FileNotFoundError:
            return None
        frames = self._safe_unpack(memoryview(shm.buf).toreadonly())
        if frames is None:
            # Mid-write (count 0): don't cache the mapping — a clobber
            # would strand it on an orphaned inode. No views escaped, so
            # closing is safe.
            try:
                shm.close()
            except BufferError:  # pragma: no cover - paranoia
                pass
            return None
        with self._lock:
            self._attached[object_id] = shm
        return frames

    def contains(self, object_id: ObjectID) -> bool:
        if object_id in self._owned or object_id in self._attached:
            return True
        try:
            shm = _open_shm(self._name(object_id))
        except FileNotFoundError:
            return False
        # Probe only — do NOT cache the mapping (rtlint RT101 real
        # finding, sharpened in review): the old unguarded insert could
        # race delete() and resurrect an entry for a deleted object,
        # and even a locked insert can land AFTER a delete() that ran
        # in the open-to-insert window. No views escaped this probe, so
        # closing is safe; get() re-attaches on demand.
        try:
            shm.close()
        except BufferError:  # pragma: no cover - paranoia
            pass
        return True

    def delete(self, object_id: ObjectID) -> None:
        with self._lock:
            ent = self._owned.pop(object_id, None)
            if ent:
                shm, n, path = ent
                if shm is not None:
                    self._used -= n
                    try:
                        shm.close()
                        shm.unlink()
                    except Exception:
                        pass
                elif path:
                    try:
                        os.unlink(path)
                    except OSError:
                        pass
            att = self._attached.pop(object_id, None)
        if att:
            try:
                att.close()
            except Exception:
                pass

    def _spill_lru(self, need_bytes: int) -> None:  # rtlint: holds=_lock
        """Move oldest in-shm objects to disk until need_bytes freed.
        Both call sites (put / create capacity checks) hold _lock."""
        os.makedirs(self._spill_dir, exist_ok=True)
        freed = 0
        for oid in list(self._owned):
            if freed >= need_bytes:
                break
            shm, n, path = self._owned[oid]
            if shm is None:
                continue
            p = os.path.join(self._spill_dir, self._name(oid))
            with open(p, "wb") as f:
                f.write(shm.buf[:n])
            try:
                shm.close()
                shm.unlink()
            except Exception:
                pass
            self._owned[oid] = (None, n, p)
            self._used -= n
            freed += n

    def used_bytes(self) -> int:
        return self._used

    @staticmethod
    def _defuse(shm: shared_memory.SharedMemory):
        """Close if safe; otherwise leak the mapping to the OS.

        User code may still hold zero-copy numpy views into the segment;
        releasing the exported buffer would raise BufferError, so we drop our
        handles and let process exit unmap it.
        """
        try:
            shm.close()
        except BufferError:
            shm._buf = None  # noqa: SLF001 - deliberate leak of the mapping
            shm._mmap = None  # noqa: SLF001

    def shutdown(self) -> None:
        with self._lock:
            for oid, ent in list(self._owned.items()):
                shm, n, path = ent
                if shm is not None:
                    try:
                        shm.unlink()
                    except Exception:
                        pass
                    self._defuse(shm)
            self._owned.clear()
            for shm in self._attached.values():
                self._defuse(shm)
            self._attached.clear()
