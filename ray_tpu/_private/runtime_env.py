"""Runtime environments: per-task/actor env vars, working_dir, py_modules.

Capability parity with the reference's runtime_env subsystem (reference:
``python/ray/_private/runtime_env/`` — working_dir/py_modules packaging
via zip blobs in GCS, env_vars plumbed to worker startup, pip installs),
re-designed for this runtime:

- ``working_dir``/``py_modules`` zip locally, ship through the head KV
  (sha-keyed, deduped) and extract once per worker into session scratch,
- ``env_vars`` apply at worker level: the lease shape key includes the
  runtime-env hash, so tasks with different envs never share a worker
  (the reference isolates the same way — dedicated workers per env),
- ``pip`` installs from a LOCAL WHEELHOUSE into per-env-hash cached
  package dirs (reference: ``runtime_env/pip.py`` virtualenv-per-hash +
  ``uri_cache.py`` eviction, re-designed for zero egress):
  ``pip={"packages": [...], "wheelhouse": "/path/to/wheels"}`` (or the
  ``RT_PIP_WHEELHOUSE`` env var) runs ``pip install --no-index
  --find-links <wheelhouse> --target <cache>/<hash>`` once per env
  hash, then prepends the cached dir to the dedicated worker's
  ``sys.path`` (the lease shape key isolates workers per env, so this
  is the venv-interpreter isolation without a respawn). Without a
  wheelhouse, ``pip`` degrades to import-validation: packages must be
  baked into the image, missing ones raise instead of downloading.
"""
from __future__ import annotations

import hashlib
import importlib.util
import io
import json
import os
import sys
import time
import zipfile
from typing import Any, Dict, Optional, Tuple

_EXCLUDE_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}
MAX_PACKAGE_BYTES = 100 * 1024 * 1024


def validate(runtime_env: Dict[str, Any]) -> Dict[str, Any]:
    allowed = {"env_vars", "working_dir", "py_modules", "pip"}
    unknown = set(runtime_env) - allowed
    if unknown:
        raise ValueError(
            f"unsupported runtime_env keys {sorted(unknown)}; "
            f"supported: {sorted(allowed)}")
    env_vars = runtime_env.get("env_vars") or {}
    if not all(isinstance(k, str) and isinstance(v, str)
               for k, v in env_vars.items()):
        raise ValueError("runtime_env env_vars must be str->str")
    pip = runtime_env.get("pip")
    if pip is not None:
        if isinstance(pip, dict):
            if set(pip) - {"packages", "wheelhouse"}:
                raise ValueError(
                    "runtime_env pip dict accepts only "
                    "'packages' and 'wheelhouse'")
            pkgs = pip.get("packages")
            wh = pip.get("wheelhouse")
            if pkgs is not None and (
                    not isinstance(pkgs, (list, tuple))
                    or not all(isinstance(p, str) for p in pkgs)):
                raise ValueError(
                    "runtime_env pip packages must be a LIST of "
                    "requirement strings (a bare string would be "
                    "split into characters)")
            if wh is not None and not isinstance(wh, str):
                raise ValueError("runtime_env pip wheelhouse must be "
                                 "a directory path string")
        elif isinstance(pip, (list, tuple)):
            if not all(isinstance(p, str) for p in pip):
                raise ValueError(
                    "runtime_env pip must be a list of requirement "
                    "strings")
        else:
            raise ValueError(
                "runtime_env pip must be a list of requirements or "
                "{'packages': [...], 'wheelhouse': <dir>}")
    return runtime_env


def zip_directory(path: str) -> bytes:
    """Deterministic zip of a directory tree (the reference's
    ``package_utils`` blob format, rebuilt)."""
    path = os.path.abspath(os.path.expanduser(path))
    if not os.path.isdir(path):
        raise ValueError(f"working_dir {path!r} is not a directory")
    buf = io.BytesIO()
    total = 0
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for root, dirs, files in os.walk(path):
            dirs[:] = sorted(d for d in dirs if d not in _EXCLUDE_DIRS)
            for name in sorted(files):
                full = os.path.join(root, name)
                rel = os.path.relpath(full, path)
                total += os.path.getsize(full)
                if total > MAX_PACKAGE_BYTES:
                    raise ValueError(
                        f"working_dir exceeds {MAX_PACKAGE_BYTES} bytes")
                zi = zipfile.ZipInfo(rel)  # fixed date → stable sha
                zi.compress_type = zipfile.ZIP_DEFLATED  # ZipInfo defaults
                with open(full, "rb") as f:              # to STORED
                    zf.writestr(zi, f.read())
    return buf.getvalue()


def package_key(blob: bytes, kind: str = "working_dir") -> str:
    return f"runtime_env/{kind}/{hashlib.sha256(blob).hexdigest()[:32]}"


def env_hash(runtime_env: Optional[Dict[str, Any]]) -> str:
    """Stable hash naming the worker-pool partition for this env."""
    if not runtime_env:
        return ""
    return hashlib.sha256(
        json.dumps(runtime_env, sort_keys=True).encode()
    ).hexdigest()[:16]


def prepare(runtime_env: Dict[str, Any], kv_put) -> Dict[str, Any]:
    """Driver side: validate, upload packages, return the wire form."""
    runtime_env = validate(dict(runtime_env))
    out: Dict[str, Any] = {}
    if runtime_env.get("env_vars"):
        out["env_vars"] = dict(runtime_env["env_vars"])
    if runtime_env.get("working_dir"):
        blob = zip_directory(runtime_env["working_dir"])
        key = package_key(blob, "working_dir")
        kv_put(key, blob)
        out["working_dir_key"] = key
    mods = []
    for mod_path in runtime_env.get("py_modules") or []:
        blob = zip_directory(mod_path)
        key = package_key(blob, "py_module")
        kv_put(key, blob)
        mods.append((os.path.basename(mod_path.rstrip("/")), key))
    if mods:
        out["py_module_keys"] = mods
    pip = runtime_env.get("pip")
    if pip:
        if isinstance(pip, dict):
            wh = pip.get("wheelhouse")
            out["pip"] = {
                "packages": list(pip.get("packages") or []),
                "wheelhouse": os.path.abspath(wh) if wh else None,
            }
        else:
            out["pip"] = {"packages": list(pip), "wheelhouse": None}
    return out


def apply(wire_env: Dict[str, Any], kv_get, scratch_dir: str) -> None:
    """Worker side: materialize the env in THIS process (the worker is
    dedicated to this env via the lease shape key)."""
    pip = wire_env.get("pip")
    if pip:
        if isinstance(pip, dict):
            packages = pip.get("packages") or []
            wheelhouse = pip.get("wheelhouse") or \
                os.environ.get("RT_PIP_WHEELHOUSE")
        else:  # legacy wire form: bare list
            packages, wheelhouse = list(pip), \
                os.environ.get("RT_PIP_WHEELHOUSE")
        if wheelhouse and packages:
            env_dir = ensure_pip_env(packages, wheelhouse)
            if env_dir not in sys.path:
                sys.path.insert(0, env_dir)
            importlib.invalidate_caches()
        else:
            for name in packages:
                base = name.split("==")[0].split(">=")[0].split("[")[0]
                base = base.replace("-", "_")
                if importlib.util.find_spec(base) is None:
                    raise RuntimeError(
                        f"runtime_env pip package {name!r} is not "
                        "available and this deployment is zero-egress; "
                        "bake it into the image or provide a "
                        "'wheelhouse' (RT_PIP_WHEELHOUSE)")
    for k, v in (wire_env.get("env_vars") or {}).items():
        os.environ[k] = v
    wd_key = wire_env.get("working_dir_key")
    if wd_key:
        target = _extract(wd_key, kv_get, scratch_dir)
        os.chdir(target)
        if target not in sys.path:
            sys.path.insert(0, target)
    for mod_name, key in wire_env.get("py_module_keys") or []:
        target = _extract(key, kv_get, scratch_dir)
        # a py_module zip IS the module dir: expose its parent
        parent = os.path.dirname(target)
        link = os.path.join(parent, mod_name)
        if not os.path.exists(link):
            os.symlink(target, link)
        if parent not in sys.path:
            sys.path.insert(0, parent)


def _pip_cache_root() -> str:
    return os.path.join(os.environ.get("TMPDIR", "/tmp"), "ray_tpu",
                        "pip_envs")


def ensure_pip_env(packages, wheelhouse: str) -> str:
    """Install ``packages`` from the local wheelhouse into a cached
    per-hash package dir; return it (reference: ``pip.py``'s
    virtualenv-per-hash + ``uri_cache.py``'s eviction). Concurrent
    workers serialize on a file lock; a hit only touches the marker
    (its mtime is the LRU clock)."""
    import fcntl
    import subprocess

    root = _pip_cache_root()
    os.makedirs(root, exist_ok=True)
    h = hashlib.sha256(json.dumps(
        [sorted(packages), os.path.abspath(wheelhouse)]).encode()
    ).hexdigest()[:16]
    env_dir = os.path.join(root, h)
    marker = env_dir + ".ok"
    with open(os.path.join(root, h + ".lock"), "w") as lockf:
        fcntl.flock(lockf, fcntl.LOCK_EX)
        try:
            if os.path.exists(marker):
                os.utime(marker)  # LRU touch
                return env_dir
            # Install into a staging dir and rename: a crash mid-install
            # must not leave a partial env that a retrying pip would
            # "Target directory already exists"-skip yet get markered.
            import shutil

            stage = env_dir + ".staging"
            shutil.rmtree(stage, ignore_errors=True)
            proc = subprocess.run(
                [sys.executable, "-m", "pip", "install", "--quiet",
                 "--no-index", "--find-links", wheelhouse,
                 "--target", stage, *packages],
                capture_output=True, text=True, timeout=600)
            if proc.returncode != 0:
                shutil.rmtree(stage, ignore_errors=True)
                raise RuntimeError(
                    f"pip install from wheelhouse {wheelhouse!r} failed "
                    f"for {list(packages)}: {proc.stderr[-2000:]}")
            shutil.rmtree(env_dir, ignore_errors=True)
            os.replace(stage, env_dir)
            open(marker, "w").close()
        finally:
            fcntl.flock(lockf, fcntl.LOCK_UN)
    _evict_pip_envs(keep=env_dir)
    return env_dir


def _evict_pip_envs(keep: str = "",
                    cap: Optional[int] = None) -> None:
    """Drop least-recently-used cached pip envs beyond the cap
    (``RT_PIP_ENV_CACHE_SIZE``, default 10). Best-effort: an env
    evicted while an old worker still imports from it only affects
    that worker's COLD imports, and the next use reinstalls."""
    import shutil

    root = _pip_cache_root()
    cap = cap if cap is not None else int(
        os.environ.get("RT_PIP_ENV_CACHE_SIZE", "10"))
    listed_at = time.time()
    try:
        markers = sorted(
            (os.path.join(root, f) for f in os.listdir(root)
             if f.endswith(".ok")),
            key=os.path.getmtime)
    except OSError:
        return
    import fcntl

    excess = len(markers) - cap
    for m in markers:
        if excess <= 0:
            break
        env_dir = m[:-3]
        if env_dir == keep:
            continue
        # Evict only under the env's lock (non-blocking): a concurrent
        # ensure_pip_env holding it may be mid-install or about to
        # return this dir to a fresh worker — skip rather than delete
        # a directory someone just adopted.
        try:
            lockf = open(env_dir + ".lock", "w")
        except OSError:
            continue
        try:
            try:
                fcntl.flock(lockf, fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError:
                continue  # in use right now
            # a hit may have touched the marker after we listed it —
            # it is no longer LRU, and its adopter is importing from it
            if os.path.getmtime(m) >= listed_at - 1.0:
                continue
            os.unlink(m)  # marker first: a racing hit re-installs
            shutil.rmtree(env_dir, ignore_errors=True)
            # the .lock file STAYS: unlinking it would let a racing
            # ensure_pip_env flock a fresh inode while another holds
            # the old one — two concurrent installs into one dir
        except OSError:
            pass
        finally:
            lockf.close()
        excess -= 1


def _extract(key: str, kv_get, scratch_dir: str) -> str:
    blob = kv_get(key)
    if blob is None:
        raise RuntimeError(f"runtime_env package {key!r} missing from KV")
    target = os.path.join(scratch_dir, key.replace("/", "_"))
    marker = target + ".ok"
    if not os.path.exists(marker):
        os.makedirs(target, exist_ok=True)
        with zipfile.ZipFile(io.BytesIO(bytes(blob))) as zf:
            zf.extractall(target)
        open(marker, "w").close()
    return target
