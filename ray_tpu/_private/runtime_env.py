"""Runtime environments: per-task/actor env vars, working_dir, py_modules.

Capability parity with the reference's runtime_env subsystem (reference:
``python/ray/_private/runtime_env/`` — working_dir/py_modules packaging
via zip blobs in GCS, env_vars plumbed to worker startup, pip installs),
re-designed for this runtime:

- ``working_dir``/``py_modules`` zip locally, ship through the head KV
  (sha-keyed, deduped) and extract once per worker into session scratch,
- ``env_vars`` apply at worker level: the lease shape key includes the
  runtime-env hash, so tasks with different envs never share a worker
  (the reference isolates the same way — dedicated workers per env),
- ``pip`` installs from a LOCAL WHEELHOUSE into per-env-hash cached
  package dirs (reference: ``runtime_env/pip.py`` virtualenv-per-hash +
  ``uri_cache.py`` eviction, re-designed for zero egress):
  ``pip={"packages": [...], "wheelhouse": "/path/to/wheels"}`` (or the
  ``RT_PIP_WHEELHOUSE`` env var) runs ``pip install --no-index
  --find-links <wheelhouse> --target <cache>/<hash>`` once per env
  hash, then prepends the cached dir to the dedicated worker's
  ``sys.path`` (the lease shape key isolates workers per env, so this
  is the venv-interpreter isolation without a respawn). Without a
  wheelhouse, ``pip`` degrades to import-validation: packages must be
  baked into the image, missing ones raise instead of downloading.
"""
from __future__ import annotations

import hashlib
import importlib.util
import io
import json
import os
import sys
import time
import zipfile
from typing import Any, Dict, Optional, Tuple

_EXCLUDE_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}
MAX_PACKAGE_BYTES = 100 * 1024 * 1024


def validate(runtime_env: Dict[str, Any]) -> Dict[str, Any]:
    """Per-key validation, dispatched to the plugin registry (built-ins
    plus anything registered — reference: plugin.py validate hooks)."""
    from . import runtime_env_plugins as rep

    known = {p.name: p for p in rep.plugins()}
    unknown = set(runtime_env) - set(known)
    if unknown:
        raise ValueError(
            f"unsupported runtime_env keys {sorted(unknown)}; "
            f"supported: {sorted(known)}")
    out = dict(runtime_env)
    for key, value in out.items():
        out[key] = known[key].validate(value)
    return out


def zip_directory(path: str) -> bytes:
    """Deterministic zip of a directory tree (the reference's
    ``package_utils`` blob format, rebuilt)."""
    path = os.path.abspath(os.path.expanduser(path))
    if not os.path.isdir(path):
        raise ValueError(f"working_dir {path!r} is not a directory")
    buf = io.BytesIO()
    total = 0
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for root, dirs, files in os.walk(path):
            dirs[:] = sorted(d for d in dirs if d not in _EXCLUDE_DIRS)
            for name in sorted(files):
                full = os.path.join(root, name)
                rel = os.path.relpath(full, path)
                total += os.path.getsize(full)
                if total > MAX_PACKAGE_BYTES:
                    raise ValueError(
                        f"working_dir exceeds {MAX_PACKAGE_BYTES} bytes")
                zi = zipfile.ZipInfo(rel)  # fixed date → stable sha
                zi.compress_type = zipfile.ZIP_DEFLATED  # ZipInfo defaults
                with open(full, "rb") as f:              # to STORED
                    zf.writestr(zi, f.read())
    return buf.getvalue()


def package_key(blob: bytes, kind: str = "working_dir") -> str:
    return f"runtime_env/{kind}/{hashlib.sha256(blob).hexdigest()[:32]}"


def env_hash(runtime_env: Optional[Dict[str, Any]]) -> str:
    """Stable hash naming the worker-pool partition for this env."""
    if not runtime_env:
        return ""
    return hashlib.sha256(
        json.dumps(runtime_env, sort_keys=True).encode()
    ).hexdigest()[:16]


def prepare(runtime_env: Dict[str, Any], kv_put) -> Dict[str, Any]:
    """Driver side: validate, upload packages, return the wire form.
    Each key's work is its plugin's ``prepare`` (built-ins keep their
    legacy flat wire keys; third-party plugins nest under
    ``plugin:<name>``)."""
    from . import runtime_env_plugins as rep

    runtime_env = validate(dict(runtime_env))
    ctx = rep.PrepareContext(kv_put=kv_put)
    out: Dict[str, Any] = {}
    for plugin in rep.plugins():
        if plugin.name not in runtime_env:
            continue
        value = runtime_env[plugin.name]
        # Built-ins keep the legacy falsy-skip ({} env_vars, empty
        # py_modules list are no-ops); third-party plugins get their
        # prepare for ANY present value — {} or 0 may be a valid
        # all-defaults config, and silently dropping it would make the
        # env never materialize with no error.
        if plugin.skip_empty and not value:
            continue
        plugin._prepare_into(value, out, ctx)
    return out


def apply(wire_env: Dict[str, Any], kv_get, scratch_dir: str) -> None:
    """Worker side: materialize the env in THIS process (the worker is
    dedicated to this env via the lease shape key). Plugins apply in
    priority order — interpreter-level (conda, pip) before path-level
    (working_dir, py_modules), so user code shadows packed packages."""
    from . import runtime_env_plugins as rep

    ctx = rep.ApplyContext(kv_get=kv_get, scratch_dir=scratch_dir)
    for plugin in rep.plugins():
        plugin._apply_from(wire_env, ctx)


def _pip_cache_root() -> str:
    return os.path.join(os.environ.get("TMPDIR", "/tmp"), "ray_tpu",
                        "pip_envs")


def _cached_build(root: str, key: str, build_fn) -> str:
    """Shared per-hash cache discipline (flock + staging dir + atomic
    replace + .ok LRU marker) for materialized envs — one copy of the
    locking/eviction rules for pip installs AND packed-env extraction,
    so fixes cannot drift between them. ``build_fn(stage_dir)``
    populates the staging dir; any exception cleans the stage and
    propagates."""
    import fcntl
    import shutil

    os.makedirs(root, exist_ok=True)
    env_dir = os.path.join(root, key)
    marker = env_dir + ".ok"
    with open(os.path.join(root, key + ".lock"), "w") as lockf:
        fcntl.flock(lockf, fcntl.LOCK_EX)
        try:
            if os.path.exists(marker):
                os.utime(marker)  # LRU touch
                return env_dir
            # Build into staging and rename: a crash mid-build must not
            # leave a partial env that a retry would adopt and marker.
            stage = env_dir + ".staging"
            shutil.rmtree(stage, ignore_errors=True)
            try:
                build_fn(stage)
            except BaseException:
                shutil.rmtree(stage, ignore_errors=True)
                raise
            shutil.rmtree(env_dir, ignore_errors=True)
            os.replace(stage, env_dir)
            open(marker, "w").close()
        finally:
            fcntl.flock(lockf, fcntl.LOCK_UN)
    return env_dir


def ensure_pip_env(packages, wheelhouse: str) -> str:
    """Install ``packages`` from the local wheelhouse into a cached
    per-hash package dir; return it (reference: ``pip.py``'s
    virtualenv-per-hash + ``uri_cache.py``'s eviction). Concurrent
    workers serialize on a file lock; a hit only touches the marker
    (its mtime is the LRU clock)."""
    import subprocess

    root = _pip_cache_root()
    # The cache key covers the wheelhouse CONTENTS (filename+size+mtime),
    # not just its path: with unpinned requirements, dropping a newer
    # wheel into the same wheelhouse must invalidate the cached env
    # instead of silently serving the stale install forever.
    try:
        wheels = sorted(
            (e.name, e.stat().st_size, int(e.stat().st_mtime))
            for e in os.scandir(wheelhouse) if e.is_file())
    except OSError:
        wheels = []
    h = hashlib.sha256(json.dumps(
        [sorted(packages), os.path.abspath(wheelhouse), wheels]).encode()
    ).hexdigest()[:16]

    def build(stage):
        proc = subprocess.run(
            [sys.executable, "-m", "pip", "install", "--quiet",
             "--no-index", "--find-links", wheelhouse,
             "--target", stage, *packages],
            capture_output=True, text=True, timeout=600)
        if proc.returncode != 0:
            raise RuntimeError(
                f"pip install from wheelhouse {wheelhouse!r} failed "
                f"for {list(packages)}: {proc.stderr[-2000:]}")

    env_dir = _cached_build(root, h, build)
    _evict_pip_envs(keep=env_dir)
    return env_dir


def _evict_pip_envs(keep: str = "",
                    cap: Optional[int] = None) -> None:
    """Drop least-recently-used cached pip envs beyond the cap
    (``RT_PIP_ENV_CACHE_SIZE``, default 10). Best-effort: an env
    evicted while an old worker still imports from it only affects
    that worker's COLD imports, and the next use reinstalls."""
    import shutil

    root = _pip_cache_root()
    cap = cap if cap is not None else int(
        os.environ.get("RT_PIP_ENV_CACHE_SIZE", "10"))
    listed_at = time.time()
    try:
        markers = sorted(
            (os.path.join(root, f) for f in os.listdir(root)
             if f.endswith(".ok")),
            key=os.path.getmtime)
    except OSError:
        return
    import fcntl

    excess = len(markers) - cap
    for m in markers:
        if excess <= 0:
            break
        env_dir = m[:-3]
        if env_dir == keep:
            continue
        # Evict only under the env's lock (non-blocking): a concurrent
        # ensure_pip_env holding it may be mid-install or about to
        # return this dir to a fresh worker — skip rather than delete
        # a directory someone just adopted.
        try:
            lockf = open(env_dir + ".lock", "w")
        except OSError:
            continue
        try:
            try:
                fcntl.flock(lockf, fcntl.LOCK_EX | fcntl.LOCK_NB)
            except OSError:
                continue  # in use right now
            # a hit may have touched the marker after we listed it —
            # it is no longer LRU, and its adopter is importing from it
            if os.path.getmtime(m) >= listed_at - 1.0:
                continue
            os.unlink(m)  # marker first: a racing hit re-installs
            shutil.rmtree(env_dir, ignore_errors=True)
            # the .lock file STAYS: unlinking it would let a racing
            # ensure_pip_env flock a fresh inode while another holds
            # the old one — two concurrent installs into one dir
        except OSError:
            pass
        finally:
            lockf.close()
        excess -= 1


def _extract(key: str, kv_get, scratch_dir: str) -> str:
    blob = kv_get(key)
    if blob is None:
        raise RuntimeError(f"runtime_env package {key!r} missing from KV")
    target = os.path.join(scratch_dir, key.replace("/", "_"))
    marker = target + ".ok"
    if not os.path.exists(marker):
        os.makedirs(target, exist_ok=True)
        with zipfile.ZipFile(io.BytesIO(bytes(blob))) as zf:
            zf.extractall(target)
        open(marker, "w").close()
    return target


# --------------------------------------------------------------- conda
def _conda_cache_root() -> str:
    return os.path.join(os.environ.get("TMPDIR", "/tmp"), "ray_tpu",
                        "conda_envs")


def ensure_extracted_env(tarball: str) -> str:
    """Extract a conda-pack-style tarball into a per-hash cached dir
    (reference: ``conda.py``'s env-per-hash, re-designed egress-free for
    packed envs). Cache discipline shared with pip via
    :func:`_cached_build`."""
    import tarfile

    tarball = os.path.abspath(tarball)
    st = os.stat(tarball)
    h = hashlib.sha256(json.dumps(
        [tarball, st.st_size, int(st.st_mtime)]).encode()).hexdigest()[:16]

    def build(stage):
        os.makedirs(stage)
        with tarfile.open(tarball) as tf:
            # "data" filter: refuse absolute paths / traversal /
            # device nodes from untrusted archives
            tf.extractall(stage, filter="data")

    return _cached_build(_conda_cache_root(), h, build)


def _activate_env_prefix(prefix: str) -> None:
    """Put an env prefix's site-packages on sys.path and its bin on
    PATH — the packed-env equivalent of conda activate."""
    import glob as _glob

    sites = _glob.glob(os.path.join(prefix, "lib", "python*",
                                    "site-packages"))
    for site in sites:
        if site not in sys.path:
            sys.path.insert(0, site)
    bin_dir = os.path.join(prefix, "bin")
    if os.path.isdir(bin_dir):
        parts = os.environ.get("PATH", "").split(os.pathsep)
        if bin_dir not in parts:
            os.environ["PATH"] = bin_dir + os.pathsep + \
                os.environ.get("PATH", "")
    importlib.invalidate_caches()


# ------------------------------------------------- built-in plugins
from . import runtime_env_plugins as _rep  # noqa: E402


class _EnvVarsPlugin(_rep.RuntimeEnvPlugin):
    skip_empty = True
    name = "env_vars"
    priority = 8

    def validate(self, value):
        if not isinstance(value, dict) or not all(
                isinstance(k, str) and isinstance(v, str)
                for k, v in value.items()):
            raise ValueError("runtime_env env_vars must be str->str")
        return value

    def _prepare_into(self, value, out, ctx):
        out["env_vars"] = dict(value)

    def _apply_from(self, wire, ctx):
        for k, v in (wire.get("env_vars") or {}).items():
            os.environ[k] = v


class _WorkingDirPlugin(_rep.RuntimeEnvPlugin):
    skip_empty = True
    name = "working_dir"
    priority = 10

    def _prepare_into(self, value, out, ctx):
        blob = zip_directory(value)
        key = package_key(blob, "working_dir")
        ctx.kv_put(key, blob)
        out["working_dir_key"] = key

    def _apply_from(self, wire, ctx):
        wd_key = wire.get("working_dir_key")
        if not wd_key:
            return
        target = _extract(wd_key, ctx.kv_get, ctx.scratch_dir)
        os.chdir(target)
        if target not in sys.path:
            sys.path.insert(0, target)

    def uris(self, wire):
        return [wire["working_dir_key"]] if wire.get(
            "working_dir_key") else []


class _PyModulesPlugin(_rep.RuntimeEnvPlugin):
    skip_empty = True
    name = "py_modules"
    priority = 11

    def validate(self, value):
        if not isinstance(value, (list, tuple)) or not all(
                isinstance(p, str) for p in value):
            raise ValueError(
                "runtime_env py_modules must be a list of paths")
        return list(value)

    def _prepare_into(self, value, out, ctx):
        mods = []
        for mod_path in value:
            blob = zip_directory(mod_path)
            key = package_key(blob, "py_module")
            ctx.kv_put(key, blob)
            mods.append((os.path.basename(mod_path.rstrip("/")), key))
        if mods:
            out["py_module_keys"] = mods

    def _apply_from(self, wire, ctx):
        for mod_name, key in wire.get("py_module_keys") or []:
            target = _extract(key, ctx.kv_get, ctx.scratch_dir)
            # a py_module zip IS the module dir: expose its parent
            parent = os.path.dirname(target)
            link = os.path.join(parent, mod_name)
            if not os.path.exists(link):
                os.symlink(target, link)
            if parent not in sys.path:
                sys.path.insert(0, parent)

    def uris(self, wire):
        return [k for _, k in wire.get("py_module_keys") or []]


class _PipPlugin(_rep.RuntimeEnvPlugin):
    skip_empty = True
    name = "pip"
    priority = 6

    def validate(self, value):
        if isinstance(value, dict):
            if set(value) - {"packages", "wheelhouse"}:
                raise ValueError(
                    "runtime_env pip dict accepts only "
                    "'packages' and 'wheelhouse'")
            pkgs = value.get("packages")
            wh = value.get("wheelhouse")
            if pkgs is not None and (
                    not isinstance(pkgs, (list, tuple))
                    or not all(isinstance(p, str) for p in pkgs)):
                raise ValueError(
                    "runtime_env pip packages must be a LIST of "
                    "requirement strings (a bare string would be "
                    "split into characters)")
            if wh is not None and not isinstance(wh, str):
                raise ValueError("runtime_env pip wheelhouse must be "
                                 "a directory path string")
        elif isinstance(value, (list, tuple)):
            if not all(isinstance(p, str) for p in value):
                raise ValueError(
                    "runtime_env pip must be a list of requirement "
                    "strings")
        else:
            raise ValueError(
                "runtime_env pip must be a list of requirements or "
                "{'packages': [...], 'wheelhouse': <dir>}")
        return value

    def _prepare_into(self, value, out, ctx):
        if isinstance(value, dict):
            wh = value.get("wheelhouse")
            out["pip"] = {
                "packages": list(value.get("packages") or []),
                "wheelhouse": os.path.abspath(wh) if wh else None,
            }
        else:
            out["pip"] = {"packages": list(value), "wheelhouse": None}

    def _apply_from(self, wire, ctx):
        pip = wire.get("pip")
        if not pip:
            return
        if isinstance(pip, dict):
            packages = pip.get("packages") or []
            wheelhouse = pip.get("wheelhouse") or \
                os.environ.get("RT_PIP_WHEELHOUSE")
        else:  # legacy wire form: bare list
            packages, wheelhouse = list(pip), \
                os.environ.get("RT_PIP_WHEELHOUSE")
        if wheelhouse and packages:
            env_dir = ensure_pip_env(packages, wheelhouse)
            if env_dir not in sys.path:
                sys.path.insert(0, env_dir)
            importlib.invalidate_caches()
        else:
            for name in packages:
                base = name.split("==")[0].split(">=")[0].split("[")[0]
                base = base.replace("-", "_")
                if importlib.util.find_spec(base) is None:
                    raise RuntimeError(
                        f"runtime_env pip package {name!r} is not "
                        "available and this deployment is zero-egress; "
                        "bake it into the image or provide a "
                        "'wheelhouse' (RT_PIP_WHEELHOUSE)")


class _CondaPlugin(_rep.RuntimeEnvPlugin):
    """Packed-env conda (reference: ``runtime_env/conda.py``,
    re-designed egress-free): ``{"packed": <conda-pack tarball>}``
    extracts into a per-hash cache, ``{"prefix": <env dir>}`` uses an
    existing env in place. Interpreter-level, so it applies before the
    path-level plugins."""

    name = "conda"
    priority = 5
    skip_empty = True

    def validate(self, value):
        if not isinstance(value, dict):
            raise ValueError(
                "runtime_env conda must be {'packed': <tarball>} or "
                "{'prefix': <env dir>}")
        keys = set(value)
        if keys - {"packed", "prefix"} or len(keys) != 1:
            raise ValueError(
                "runtime_env conda takes exactly one of 'packed' or "
                "'prefix'")
        (v,) = value.values()
        if not isinstance(v, str):
            raise ValueError("runtime_env conda paths must be strings")
        return value

    def _prepare_into(self, value, out, ctx):
        out["conda"] = {k: os.path.abspath(v) for k, v in value.items()}

    def _apply_from(self, wire, ctx):
        conda = wire.get("conda")
        if not conda:
            return
        if conda.get("packed"):
            prefix = ensure_extracted_env(conda["packed"])
        else:
            prefix = conda["prefix"]
            if not os.path.isdir(prefix):
                raise RuntimeError(
                    f"runtime_env conda prefix {prefix!r} does not "
                    "exist on this node")
        _activate_env_prefix(prefix)


for _p in (_CondaPlugin(), _PipPlugin(), _EnvVarsPlugin(),
           _WorkingDirPlugin(), _PyModulesPlugin()):
    _rep.register_plugin(_p, allow_override=True)
del _p
