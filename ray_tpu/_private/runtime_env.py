"""Runtime environments: per-task/actor env vars, working_dir, py_modules.

Capability parity with the reference's runtime_env subsystem (reference:
``python/ray/_private/runtime_env/`` — working_dir/py_modules packaging
via zip blobs in GCS, env_vars plumbed to worker startup, pip installs),
re-designed for this runtime:

- ``working_dir``/``py_modules`` zip locally, ship through the head KV
  (sha-keyed, deduped) and extract once per worker into session scratch,
- ``env_vars`` apply at worker level: the lease shape key includes the
  runtime-env hash, so tasks with different envs never share a worker
  (the reference isolates the same way — dedicated workers per env),
- ``pip`` is validated import-only: this deployment is zero-egress, so
  packages must already be present; missing ones raise a clear error
  instead of silently downloading.
"""
from __future__ import annotations

import hashlib
import importlib.util
import io
import json
import os
import sys
import zipfile
from typing import Any, Dict, Optional, Tuple

_EXCLUDE_DIRS = {"__pycache__", ".git", ".venv", "node_modules"}
MAX_PACKAGE_BYTES = 100 * 1024 * 1024


def validate(runtime_env: Dict[str, Any]) -> Dict[str, Any]:
    allowed = {"env_vars", "working_dir", "py_modules", "pip"}
    unknown = set(runtime_env) - allowed
    if unknown:
        raise ValueError(
            f"unsupported runtime_env keys {sorted(unknown)}; "
            f"supported: {sorted(allowed)}")
    env_vars = runtime_env.get("env_vars") or {}
    if not all(isinstance(k, str) and isinstance(v, str)
               for k, v in env_vars.items()):
        raise ValueError("runtime_env env_vars must be str->str")
    return runtime_env


def zip_directory(path: str) -> bytes:
    """Deterministic zip of a directory tree (the reference's
    ``package_utils`` blob format, rebuilt)."""
    path = os.path.abspath(os.path.expanduser(path))
    if not os.path.isdir(path):
        raise ValueError(f"working_dir {path!r} is not a directory")
    buf = io.BytesIO()
    total = 0
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for root, dirs, files in os.walk(path):
            dirs[:] = sorted(d for d in dirs if d not in _EXCLUDE_DIRS)
            for name in sorted(files):
                full = os.path.join(root, name)
                rel = os.path.relpath(full, path)
                total += os.path.getsize(full)
                if total > MAX_PACKAGE_BYTES:
                    raise ValueError(
                        f"working_dir exceeds {MAX_PACKAGE_BYTES} bytes")
                zi = zipfile.ZipInfo(rel)  # fixed date → stable sha
                zi.compress_type = zipfile.ZIP_DEFLATED  # ZipInfo defaults
                with open(full, "rb") as f:              # to STORED
                    zf.writestr(zi, f.read())
    return buf.getvalue()


def package_key(blob: bytes, kind: str = "working_dir") -> str:
    return f"runtime_env/{kind}/{hashlib.sha256(blob).hexdigest()[:32]}"


def env_hash(runtime_env: Optional[Dict[str, Any]]) -> str:
    """Stable hash naming the worker-pool partition for this env."""
    if not runtime_env:
        return ""
    return hashlib.sha256(
        json.dumps(runtime_env, sort_keys=True).encode()
    ).hexdigest()[:16]


def prepare(runtime_env: Dict[str, Any], kv_put) -> Dict[str, Any]:
    """Driver side: validate, upload packages, return the wire form."""
    runtime_env = validate(dict(runtime_env))
    out: Dict[str, Any] = {}
    if runtime_env.get("env_vars"):
        out["env_vars"] = dict(runtime_env["env_vars"])
    if runtime_env.get("working_dir"):
        blob = zip_directory(runtime_env["working_dir"])
        key = package_key(blob, "working_dir")
        kv_put(key, blob)
        out["working_dir_key"] = key
    mods = []
    for mod_path in runtime_env.get("py_modules") or []:
        blob = zip_directory(mod_path)
        key = package_key(blob, "py_module")
        kv_put(key, blob)
        mods.append((os.path.basename(mod_path.rstrip("/")), key))
    if mods:
        out["py_module_keys"] = mods
    if runtime_env.get("pip"):
        out["pip"] = list(runtime_env["pip"])
    return out


def apply(wire_env: Dict[str, Any], kv_get, scratch_dir: str) -> None:
    """Worker side: materialize the env in THIS process (the worker is
    dedicated to this env via the lease shape key)."""
    for name in wire_env.get("pip") or []:
        base = name.split("==")[0].split(">=")[0].split("[")[0]
        base = base.replace("-", "_")
        if importlib.util.find_spec(base) is None:
            raise RuntimeError(
                f"runtime_env pip package {name!r} is not available and "
                "this deployment is zero-egress; bake it into the image")
    for k, v in (wire_env.get("env_vars") or {}).items():
        os.environ[k] = v
    wd_key = wire_env.get("working_dir_key")
    if wd_key:
        target = _extract(wd_key, kv_get, scratch_dir)
        os.chdir(target)
        if target not in sys.path:
            sys.path.insert(0, target)
    for mod_name, key in wire_env.get("py_module_keys") or []:
        target = _extract(key, kv_get, scratch_dir)
        # a py_module zip IS the module dir: expose its parent
        parent = os.path.dirname(target)
        link = os.path.join(parent, mod_name)
        if not os.path.exists(link):
            os.symlink(target, link)
        if parent not in sys.path:
            sys.path.insert(0, parent)


def _extract(key: str, kv_get, scratch_dir: str) -> str:
    blob = kv_get(key)
    if blob is None:
        raise RuntimeError(f"runtime_env package {key!r} missing from KV")
    target = os.path.join(scratch_dir, key.replace("/", "_"))
    marker = target + ".ok"
    if not os.path.exists(marker):
        os.makedirs(target, exist_ok=True)
        with zipfile.ZipFile(io.BytesIO(bytes(blob))) as zf:
            zf.extractall(target)
        open(marker, "w").close()
    return target
