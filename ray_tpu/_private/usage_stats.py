"""Usage stats: anonymous feature-usage counters, local-file only.

Capability parity with the reference's usage-stats subsystem (reference:
``python/ray/_private/usage/usage_lib.py`` — feature counters + cluster
metadata reported once per session), re-designed for zero egress: the
report is WRITTEN to the session directory (``usage_stats.json``) and
never leaves the machine. Disable entirely with RT_USAGE_STATS_DISABLED=1
(mirrors RAY_USAGE_STATS_ENABLED=0).
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import Counter
from typing import Dict

_lock = threading.Lock()
_features: Counter = Counter()
_start = time.time()


def enabled() -> bool:
    return os.environ.get("RT_USAGE_STATS_DISABLED", "") != "1"


def record_feature(name: str) -> None:
    """Count a library/API touchpoint (e.g. 'train', 'serve', 'tune')."""
    if not enabled():
        return
    with _lock:
        _features[name] += 1


def report() -> Dict:
    with _lock:
        feats = dict(_features)
    import ray_tpu

    return {
        "version": ray_tpu.__version__,
        "uptime_s": round(time.time() - _start, 1),
        "features": feats,
        "schema_version": 1,
    }


def write_report(session_dir: str) -> str:
    """Persist the local report; returns its path ('' when disabled)."""
    if not enabled():
        return ""
    path = os.path.join(session_dir, "usage_stats.json")
    try:
        with open(path, "w") as f:
            json.dump(report(), f, indent=1)
    except OSError:
        return ""
    return path
