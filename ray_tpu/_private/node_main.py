"""Node daemon entry point (reference capability: ``ray start`` joining an
existing cluster — ``python/ray/scripts/scripts.py`` ``ray start
--address=...``).

Run on each host of a multi-node cluster:

    python -m ray_tpu._private.node_main \
        --head 10.0.0.1:6379 --num-cpus 8 --resources '{"TPU": 4}'
"""
from __future__ import annotations

import argparse
import asyncio
import json
import signal


def main(argv=None):
    parser = argparse.ArgumentParser()
    parser.add_argument("--head", required=True,
                        help="head TCP address host:port")
    parser.add_argument("--session-dir", required=True)
    parser.add_argument("--num-cpus", type=float, default=1.0)
    parser.add_argument("--num-tpus", type=float, default=0.0)
    parser.add_argument("--resources", default="{}",
                        help="extra resources as JSON")
    parser.add_argument("--shm-domain", default=None)
    parser.add_argument("--private-shm-domain", action="store_true",
                        help="this daemon's shm domain is exclusively "
                             "its own: sweep leftover segments on stop "
                             "(cluster_utils sets this for its "
                             "synthetic per-node domains)")
    parser.add_argument("--labels", default="{}")
    parser.add_argument("--die-with-parent", action="store_true",
                        help="SIGKILL this daemon when its spawner dies "
                             "(test harnesses; operators omit it)")
    args = parser.parse_args(argv)

    from ray_tpu._private import reaper
    from ray_tpu._private.node import NodeService

    # Workers we spawn re-parent to us (not init) if an intermediate
    # shell dies, so our stop() can always reach them.
    reaper.become_subreaper()
    if args.die_with_parent:
        reaper.die_with_parent()
        reaper.start_orphan_watchdog()

    host, _, port = args.head.rpartition(":")
    resources = {"CPU": args.num_cpus}
    if args.num_tpus:
        resources["TPU"] = args.num_tpus
    resources.update(json.loads(args.resources))

    async def run():
        node = NodeService(
            head_address=(host, int(port)),
            session_dir=args.session_dir,
            resources=resources,
            shm_domain=args.shm_domain,
            private_domain=args.private_shm_domain,
            labels=json.loads(args.labels),
        )
        await node.start()
        loop = asyncio.get_running_loop()
        stop = asyncio.Event()
        loop.add_signal_handler(signal.SIGTERM, stop.set)
        loop.add_signal_handler(signal.SIGINT, stop.set)
        waiter = loop.create_task(node.run_forever())
        stopper = loop.create_task(stop.wait())
        await asyncio.wait([waiter, stopper],
                           return_when=asyncio.FIRST_COMPLETED)
        await node.stop()

    asyncio.run(run())
    return 0


if __name__ == "__main__":
    main()
