"""Binary IDs for jobs, tasks, objects, actors, nodes, placement groups.

Capability parity with the reference's ID scheme (reference:
``src/ray/common/id.h``, ``id_def.h``) but designed fresh: every ID is a
fixed-width random byte string with a 1-byte type tag, so IDs are
self-describing on the wire and sortable by creation when the time prefix is
enabled.
"""
from __future__ import annotations

import os
import threading
import time

_ID_LEN = 16  # bytes, excluding the 1-byte type tag

_TYPE_JOB = 0x01
_TYPE_TASK = 0x02
_TYPE_OBJECT = 0x03
_TYPE_ACTOR = 0x04
_TYPE_NODE = 0x05
_TYPE_PLACEMENT_GROUP = 0x06
_TYPE_WORKER = 0x07

_counter_lock = threading.Lock()
_counter = 0


# Burst-submission hot path: os.urandom per ID costs ~0.1ms via a
# syscall. A per-process random seed + atomic counter keeps IDs unique
# at ~no cost per ID. Layout matters: ``ObjectID.for_task_return``
# truncates the FINAL 2 bytes, so both the counter (intra-process
# uniqueness) and the seed (cross-process uniqueness, 4 bytes + pid mixed
# in) must sit in the first 8 of these 10 bytes.
_proc_seed = bytes(a ^ b for a, b in zip(
    os.urandom(6), os.getpid().to_bytes(6, "big", signed=False)))
# itertools.count.__next__ is a single C call — atomic under the GIL, so
# the hot path needs no lock (a lock acquire/release pair costs more
# than the whole ID otherwise).
import itertools as _itertools

_seq_iter = _itertools.count(1)


# (ms, 6-byte big-endian prefix) as ONE atomically-assigned tuple:
# concurrent submitters read it with a single load, so a reader can
# never pair one thread's ms with another thread's byte string (the
# torn read two separate globals allowed).
_ts_cache = (0, b"\x00" * 6)


def _rand_bytes(n: int) -> bytes:
    if n == 10:
        s = next(_seq_iter) & 0xFFFFFFFF
        return _proc_seed[:4] + s.to_bytes(4, "big") + _proc_seed[4:6]
    return os.urandom(n)


class BaseID:
    """A fixed-width binary identifier. Immutable and hashable."""

    _type_tag = 0x00
    __slots__ = ("_bytes",)

    def __init__(self, id_bytes: bytes):
        if len(id_bytes) != _ID_LEN + 1:
            raise ValueError(
                f"{type(self).__name__} requires {_ID_LEN + 1} bytes, got {len(id_bytes)}"
            )
        if id_bytes[0] != self._type_tag:
            raise ValueError(
                f"Wrong type tag for {type(self).__name__}: {id_bytes[0]:#x}"
            )
        self._bytes = id_bytes

    @classmethod
    def from_random(cls) -> "BaseID":
        # 6-byte coarse timestamp prefix keeps IDs roughly creation-ordered,
        # which makes store scans and debugging nicer; the remaining bytes are
        # cryptographically random. The prefix is CACHED per millisecond:
        # submission bursts mint thousands of IDs per ms and the
        # int->to_bytes pair showed up in the submit-path profile.
        global _ts_cache
        now = int(time.time() * 1000)
        ms, prefix = _ts_cache
        if now != ms:
            prefix = now.to_bytes(6, "big", signed=False)[-6:]
            _ts_cache = (now, prefix)
        return cls(bytes([cls._type_tag]) + prefix
                   + _rand_bytes(_ID_LEN - 6))

    @classmethod
    def from_hex(cls, h: str) -> "BaseID":
        return cls(bytes.fromhex(h))

    @classmethod
    def nil(cls) -> "BaseID":
        return cls(bytes([cls._type_tag]) + b"\x00" * _ID_LEN)

    def is_nil(self) -> bool:
        return self._bytes[1:] == b"\x00" * _ID_LEN

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def __hash__(self):
        return hash(self._bytes)

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __lt__(self, other):
        return self._bytes < other._bytes

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()[:14]}…)"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class JobID(BaseID):
    _type_tag = _TYPE_JOB
    __slots__ = ()


class TaskID(BaseID):
    _type_tag = _TYPE_TASK
    __slots__ = ()


class ObjectID(BaseID):
    _type_tag = _TYPE_OBJECT
    __slots__ = ()

    @classmethod
    def for_task_return(cls, task_id: TaskID, index: int) -> "ObjectID":
        """Deterministically derive the i-th return object ID of a task."""
        body = task_id.binary()[1 : 1 + _ID_LEN - 2] + index.to_bytes(2, "big")
        return cls(bytes([cls._type_tag]) + body)


class ActorID(BaseID):
    _type_tag = _TYPE_ACTOR
    __slots__ = ()


class NodeID(BaseID):
    _type_tag = _TYPE_NODE
    __slots__ = ()


class WorkerID(BaseID):
    _type_tag = _TYPE_WORKER
    __slots__ = ()


class PlacementGroupID(BaseID):
    _type_tag = _TYPE_PLACEMENT_GROUP
    __slots__ = ()
