"""Host memory monitor (reference: ``src/ray/common/memory_monitor.h:52``).

Samples node memory usage the way the reference does — cgroup-aware, so
a container sees its own limit rather than the host's — and reports
whether usage crossed the kill threshold. The kill POLICY lives at the
head (``head.py`` ``_handle_memory_pressure``), which knows every
worker's assignment; daemons only sample and report, the same split as
raylet's MemoryMonitor callback → WorkerKillingPolicy.

``RT_MEMORY_LIMIT_BYTES`` caps the detected total — the test hook and
the escape hatch for partial-host deployments.
"""
from __future__ import annotations

import os
from dataclasses import dataclass


@dataclass
class MemorySnapshot:
    used_bytes: int
    total_bytes: int

    @property
    def used_fraction(self) -> float:
        return self.used_bytes / max(1, self.total_bytes)


_CGV2 = "/sys/fs/cgroup"
_CGV1 = "/sys/fs/cgroup/memory"


def _read_int(path: str):
    try:
        with open(path) as f:
            v = f.read().strip()
        return None if v == "max" else int(v)
    except (OSError, ValueError):
        return None


def _read_stat_key(path: str, key: str):
    try:
        with open(path) as f:
            for line in f:
                parts = line.split()
                if len(parts) == 2 and parts[0] == key:
                    return int(parts[1])
    except (OSError, ValueError):
        pass
    return None


def _host_meminfo() -> MemorySnapshot:
    total = avail = None
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    total = int(line.split()[1]) * 1024
                elif line.startswith("MemAvailable:"):
                    avail = int(line.split()[1]) * 1024
                if total is not None and avail is not None:
                    break
    except OSError:
        pass
    if total is None:
        return MemorySnapshot(0, 1)
    if avail is None:
        avail = total
    return MemorySnapshot(total - avail, total)


def sample_memory() -> MemorySnapshot:
    """Current node memory usage. Cgroup v2 → v1 → /proc/meminfo, like
    the reference's MemoryMonitor (``memory_monitor.h`` cgroup paths);
    file-cache pages (inactive_file/active_file) are excluded from
    usage — they are reclaimable, and counting them would kill workers
    for the page cache's sins."""
    host = _host_meminfo()
    used, total = host.used_bytes, host.total_bytes
    # cgroup v2
    limit = _read_int(os.path.join(_CGV2, "memory.max"))
    current = _read_int(os.path.join(_CGV2, "memory.current"))
    stat = os.path.join(_CGV2, "memory.stat")
    if current is None:
        # cgroup v1
        limit = _read_int(os.path.join(_CGV1, "memory.limit_in_bytes"))
        current = _read_int(os.path.join(_CGV1, "memory.usage_in_bytes"))
        stat = os.path.join(_CGV1, "memory.stat")
        inactive = _read_stat_key(stat, "total_inactive_file")
        active = _read_stat_key(stat, "total_active_file")
    else:
        inactive = _read_stat_key(stat, "inactive_file")
        active = _read_stat_key(stat, "active_file")
    if current is not None and limit is not None and \
            0 < limit < host.total_bytes:
        used = current - (inactive or 0) - (active or 0)
        total = limit
    env_cap = os.environ.get("RT_MEMORY_LIMIT_BYTES")
    if env_cap:
        total = min(total, int(env_cap))
    return MemorySnapshot(max(0, used), max(1, total))


def kill_threshold_bytes(snapshot: MemorySnapshot,
                         usage_threshold: float,
                         min_free_bytes: int = -1) -> int:
    """Bytes of usage above which workers are killed.

    ``min_free_bytes >= 0`` additionally requires that much free memory
    (the reference's ``min_memory_free_bytes``), tightening the
    fraction-based threshold on huge-memory hosts."""
    t = int(snapshot.total_bytes * usage_threshold)
    if min_free_bytes >= 0:
        t = min(t, snapshot.total_bytes - min_free_bytes)
    return max(0, t)
