"""Per-node dashboard agent (reference: ``dashboard/agent.py:28`` —
the DashboardAgent process every raylet hosts, serving node-local
stats and logs that the dashboard head aggregates).

Re-designed for this runtime: instead of a separate agent process per
node, the agent is a tiny asyncio HTTP server INSIDE the node daemon
(and the head, for its own host) — same endpoints, one fewer process
to babysit:

- ``GET /api/stats``   → host cpu/mem/load + per-worker pid/rss/cpu
- ``GET /api/workers`` → worker ids + pids this daemon owns
- ``GET /api/logs``    → log file list / tail (``worker_id=``, ``bytes=``)

The head additionally proxies every node's stats/logs over its
existing daemon RPC connections (``/api/node?node_id=…`` on the head
dashboard), so one URL serves the whole cluster on multi-host
deployments where agent ports may not be reachable from outside.
"""
from __future__ import annotations

import asyncio
import json
import os
import time
from typing import Callable, Dict, Optional


def collect_node_stats(worker_pids: Dict[str, int]) -> dict:
    """Node-local stats snapshot (psutil-backed, like the reference's
    agent ``node_stats``)."""
    import psutil

    vm = psutil.virtual_memory()
    try:
        load1, load5, load15 = os.getloadavg()
    except OSError:
        load1 = load5 = load15 = 0.0
    workers = []
    for hexid, pid in worker_pids.items():
        try:
            p = psutil.Process(pid)
            with p.oneshot():
                workers.append({
                    "worker_id": hexid[:12], "pid": pid,
                    "rss_bytes": p.memory_info().rss,
                    "cpu_percent": p.cpu_percent(interval=None),
                    "status": p.status(),
                })
        except (psutil.NoSuchProcess, psutil.AccessDenied):
            workers.append({"worker_id": hexid[:12], "pid": pid,
                            "status": "gone"})
    return {
        "time": time.time(),
        "cpu_percent": psutil.cpu_percent(interval=None),
        "cpu_count": psutil.cpu_count(),
        "mem_total_bytes": vm.total,
        "mem_available_bytes": vm.available,
        "mem_percent": vm.percent,
        "load_avg": [load1, load5, load15],
        "num_workers": len(worker_pids),
        "workers": workers,
    }


class NodeAgentServer:
    """The agent's HTTP face: dependency-free GET-only asyncio server
    (same parser discipline as the head's dashboard-lite)."""

    def __init__(self, stats_fn: Callable[[], dict],
                 workers_fn: Callable[[], list],
                 log_fn: Callable[[dict], dict],
                 host: str = "127.0.0.1", port: int = 0):
        self._stats_fn = stats_fn
        self._workers_fn = workers_fn
        self._log_fn = log_fn
        self._host = host
        self._port = port
        self._server: Optional[asyncio.AbstractServer] = None

    async def start(self):
        self._server = await asyncio.start_server(
            self._serve, host=self._host, port=self._port)
        self._port = self._server.sockets[0].getsockname()[1]
        return self

    @property
    def port(self) -> int:
        return self._port

    async def stop(self):
        if self._server:
            self._server.close()
            try:
                await self._server.wait_closed()
            except Exception:  # noqa: BLE001
                pass

    async def _serve(self, reader, writer):
        from .dashboard import read_get_request, respond

        try:
            parsed = await read_get_request(reader)
            if parsed is None:
                await respond(writer, 405, "application/json",
                              b'{"error":"GET only"}')
                return
            path, q = parsed
            if path == "/api/stats":
                body = json.dumps(self._stats_fn()).encode()
            elif path == "/api/workers":
                body = json.dumps(self._workers_fn()).encode()
            elif path == "/api/logs":
                try:
                    body = json.dumps(self._log_fn(q)).encode()
                except Exception as e:  # noqa: BLE001 - missing file
                    await respond(writer, 404, "application/json",
                                  json.dumps({"error": str(e)}).encode())
                    return
            elif path == "/":
                body = json.dumps({"endpoints": [
                    "/api/stats", "/api/workers", "/api/logs"]}).encode()
            else:
                await respond(writer, 404, "application/json",
                              b'{"error":"not found"}')
                return
            await respond(writer, 200, "application/json", body)
        except Exception:  # noqa: BLE001 - bad client mustn't kill daemon
            pass
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:  # noqa: BLE001
                pass
