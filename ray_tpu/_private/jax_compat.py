"""Version-bridging shims for jax APIs the repo relies on.

The repo targets the modern spelling (``jax.shard_map(..., check_vma=)``);
older jax releases ship the same primitive as
``jax.experimental.shard_map.shard_map`` with the check named
``check_rep``. Resolve the spelling once here so every call site stays
on the modern one.
"""
import jax

if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:  # pre-0.6 jax: experimental spelling, check_vma named check_rep
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True, **kw):
        # check_rep stays off: the legacy replication checker rejects
        # valid cond-under-shard_map programs (its own error message
        # says to pass check_rep=False as the workaround).
        del check_vma
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=False, **kw)
