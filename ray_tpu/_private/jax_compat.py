"""Version-bridging shims for jax APIs the repo relies on.

The repo targets the modern spelling (``jax.shard_map(..., check_vma=)``);
older jax releases ship the same primitive as
``jax.experimental.shard_map.shard_map`` with the check named
``check_rep``. Resolve the spelling once here so every call site stays
on the modern one.

Also home to :func:`decode_mesh`, the one place a tensor-parallel
DecodeEngine turns ``tp=N`` into a device mesh: every sharded jit
factory in ``models/gpt_decode.py`` and every cache allocator keys off
the mesh built here, so tp=2 on an 8-way forced-host-device CPU run
and tp=8 on a TPU slice go through the identical code path.
"""
import functools

import jax
import numpy as np

if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:  # pre-0.6 jax: experimental spelling, check_vma named check_rep
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True, **kw):
        # check_rep stays off: the legacy replication checker rejects
        # valid cond-under-shard_map programs (its own error message
        # says to pass check_rep=False as the workaround).
        del check_vma
        return _shard_map(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, check_rep=False, **kw)


@functools.lru_cache(maxsize=8)
def decode_mesh(tp: int = 1) -> jax.sharding.Mesh:
    """The 1-D ``("tp",)`` mesh a tensor-parallel decode engine shards
    over: the first ``tp`` local devices, cached so every factory and
    cache allocator asking for the same ``tp`` shares one Mesh object
    (Mesh identity is part of shard_map's trace key — a fresh Mesh per
    call would defeat the compiled-program budget).

    On CPU hosts tier-1 forces virtual devices via
    ``XLA_FLAGS=--xla_force_host_platform_device_count=N`` (conftest
    does this before importing jax), so a tp=2 mesh here is a REAL
    2-device mesh, not a stub — the same shard_map programs that run
    on a TPU slice run in the test suite.
    """
    tp = int(tp)
    if tp < 1:
        raise ValueError(f"decode_mesh: tp must be >= 1, got {tp}")
    devs = jax.devices()
    if len(devs) < tp:
        raise RuntimeError(
            f"decode_mesh(tp={tp}) needs {tp} devices but only "
            f"{len(devs)} are visible; on CPU set "
            f"XLA_FLAGS=--xla_force_host_platform_device_count={tp} "
            f"(before jax import) to fake a host-platform mesh")
    return jax.sharding.Mesh(np.asarray(devs[:tp]), ("tp",))
