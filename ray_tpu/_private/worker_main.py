"""Worker process entry point (reference capability: default_worker.py).

Spawned by the head's worker pool (UDS, head-local) or by a node daemon
(TCP, remote node); registers back with the head and then serves
``push_task`` / ``create_actor`` RPCs until terminated.
"""
from __future__ import annotations

import argparse
import os
import signal
import sys
import threading
import time


def main():
    # Runtime sanitizer (tools/rtsan, ISSUE 13): RT_SAN=1 in the
    # spawning environment sanitizes worker processes too — replica
    # engines live HERE, not in the test process. Runs as early as
    # main() can: everything constructed from here on (CoreWorker,
    # engines, controllers — all instance locks) goes through the
    # patched factories. The package-import chain that `-m` already
    # executed (ray_tpu/__init__ -> api/ids) created its few
    # module-level locks raw; those are outside rtsan's coverage in
    # workers. Gated: a deployment without the tools/ tree just runs
    # unsanitized (the sanitizer is a dev/CI harness, not a runtime
    # dependency).
    if os.environ.get("RT_SAN") == "1":
        try:
            import tools.rtsan as _rtsan

            _rtsan.enable(active=True)
        except Exception:  # noqa: BLE001 - tools/ tree absent: run plain
            pass

    import faulthandler

    # `kill -USR1 <worker pid>` dumps thread stacks to the worker log —
    # the debugging hook for distributed hangs.
    faulthandler.register(signal.SIGUSR1, all_threads=True)

    # A worker must never outlive its spawner (head / node daemon) — a
    # SIGKILL'd parent gets no graceful-stop hook, so the kernel-level
    # death signal plus re-parent watchdog do the reaping (reference
    # capability: ``src/ray/util/subreaper.h`` orphan policy).
    if not os.environ.get("RT_NO_PDEATHSIG"):
        from ray_tpu._private import reaper

        reaper.die_with_parent()
        reaper.start_orphan_watchdog()

    parser = argparse.ArgumentParser()
    parser.add_argument("--session-dir", required=True)
    parser.add_argument("--worker-id", required=True)
    parser.add_argument("--head-sock", default=None,
                        help="head UDS socket path (head-local workers)")
    parser.add_argument("--head-tcp", default=None,
                        help="head TCP address host:port (remote nodes)")
    parser.add_argument("--node-id", default=None)
    parser.add_argument("--shm-domain", default=None)
    parser.add_argument("--tcp", action="store_true",
                        help="serve on TCP so other nodes can pull objects")
    args = parser.parse_args()

    # Import after arg parsing to keep failure messages clean.
    from ray_tpu._private.config import Config
    from ray_tpu._private.ids import WorkerID
    from ray_tpu.core.worker import CoreWorker

    if args.head_tcp:
        host, _, port = args.head_tcp.rpartition(":")
        head_address = (host, int(port))
    else:
        head_address = args.head_sock

    core = CoreWorker(
        session_dir=args.session_dir,
        head_sock=head_address,
        mode="worker",
        config=Config(),
        worker_id=WorkerID.from_hex(args.worker_id),
        listen_tcp=args.tcp,
        node_id=args.node_id,
        shm_domain=args.shm_domain,
    )
    core.start()

    # Register with the head: announce our serving address + home node.
    core.head_call("register_worker", {
        "worker_id": args.worker_id,
        "address": core.address,
        "node_id": args.node_id,
        "pid": os.getpid(),
    }, timeout=30)

    stop = threading.Event()

    def _term(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)

    try:
        while not stop.is_set():
            stop.wait(1.0)
            core.flush_task_events()
    finally:
        core.shutdown()


if __name__ == "__main__":
    main()
