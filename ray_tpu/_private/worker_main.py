"""Worker process entry point (reference capability: default_worker.py).

Spawned by the head's worker pool; registers back over the head socket and
then serves ``push_task`` / ``create_actor`` RPCs until terminated.
"""
from __future__ import annotations

import argparse
import os
import signal
import sys
import threading
import time


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--session-dir", required=True)
    parser.add_argument("--worker-id", required=True)
    parser.add_argument("--head-sock", required=True)
    args = parser.parse_args()

    # Import after arg parsing to keep failure messages clean.
    from ray_tpu._private.config import Config
    from ray_tpu._private.ids import WorkerID
    from ray_tpu.core.worker import CoreWorker

    core = CoreWorker(
        session_dir=args.session_dir,
        head_sock=args.head_sock,
        mode="worker",
        config=Config(),
        worker_id=WorkerID.from_hex(args.worker_id),
    )
    core.start()

    # Register with the head: announce our serving socket.
    core.head_call("register_worker", {
        "worker_id": args.worker_id,
        "address": core.sock_path,
        "pid": os.getpid(),
    }, timeout=30)

    stop = threading.Event()

    def _term(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)

    try:
        while not stop.is_set():
            stop.wait(1.0)
            core.flush_task_events()
    finally:
        core.shutdown()


if __name__ == "__main__":
    main()
