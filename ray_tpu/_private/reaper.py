"""Orphan-proofing for cluster processes.

The reference keeps worker trees from outliving a killed raylet with a
child-subreaper (reference: ``src/ray/util/subreaper.h``); the same
problem here is a SIGKILL'd driver/head/node daemon leaving workers
alive forever (and skewing every benchmark on a shared machine). Three
layers, all Linux-first with safe no-op fallbacks:

- ``die_with_parent()`` — prctl(PR_SET_PDEATHSIG, SIGKILL): the kernel
  kills us the instant the spawning thread's process exits, covering
  SIGKILL where no atexit hook can run.
- an orphan watchdog thread — polls ``os.getppid()``; re-parenting to
  init (or to a subreaper we did not start under) means the parent died
  in the exec window before prctl took effect.
- ``become_subreaper()`` — prctl(PR_SET_CHILD_SUBREAPER, 1) in heads and
  node daemons, so grandchildren re-parent to us (not init) and get
  reaped/killed on our shutdown instead of leaking.
"""
from __future__ import annotations

import ctypes
import ctypes.util
import os
import signal
import threading

PR_SET_PDEATHSIG = 1
PR_SET_CHILD_SUBREAPER = 36

_libc = None


def _prctl(option: int, arg: int) -> bool:
    global _libc
    if os.name != "posix":
        return False
    try:
        if _libc is None:
            _libc = ctypes.CDLL(ctypes.util.find_library("c") or "libc.so.6",
                                use_errno=True)
        return _libc.prctl(option, arg, 0, 0, 0) == 0
    except Exception:  # noqa: BLE001 - non-Linux libc; degrade to no-op
        return False


EXPECTED_PPID_ENV = "RT_EXPECTED_PPID"


def die_with_parent(sig: int = signal.SIGKILL) -> bool:
    """Ask the kernel to deliver ``sig`` when our parent process dies.

    Must be called early in the child (after exec). Returns True if the
    prctl took effect. The exec-window race (parent died before this
    call → signal never fires) is only detectable against an explicit
    spawner pid: spawners put their pid in ``RT_EXPECTED_PPID``; a bare
    ``getppid()==1`` check would SIGKILL healthy workers whenever the
    spawner legitimately runs as PID 1 (container entrypoint).
    """
    ok = _prctl(PR_SET_PDEATHSIG, sig)
    expected = os.environ.get(EXPECTED_PPID_ENV)
    if expected and os.getppid() != int(expected):
        # Parent died in the exec window; the death signal missed.
        os.kill(os.getpid(), sig)
    return ok


def become_subreaper() -> bool:
    """Adopt orphaned grandchildren instead of letting init take them."""
    return _prctl(PR_SET_CHILD_SUBREAPER, 1)


def start_orphan_watchdog(interval: float = 2.0,
                          sig: int = signal.SIGKILL) -> threading.Thread:
    """Kill this process if it gets re-parented away from its spawner.

    Belt for the pdeathsig braces: catches the exec-window race and
    platforms where prctl is unavailable. The legitimate parent is the
    spawner-provided ``RT_EXPECTED_PPID`` when present (immune to the
    exec-window race), else the initial ``getppid``; any change (init, a
    systemd user reaper, ...) means that parent is gone.
    """
    expected = os.environ.get(EXPECTED_PPID_ENV)
    original_ppid = int(expected) if expected else os.getppid()
    stop = threading.Event()

    def watch():
        while not stop.wait(interval):
            if os.getppid() != original_ppid:
                os.kill(os.getpid(), sig)
                return

    t = threading.Thread(target=watch, name="orphan-watchdog", daemon=True)
    t.start()
    return t
