"""Append-only write-ahead log for head control-plane mutations.

Closes the snapshot-cadence loss window (reference: the GCS persists
every metadata mutation synchronously to Redis,
``src/ray/gcs/store_client/redis_store_client.h``; here the periodic
snapshot is the checkpoint and this WAL covers the mutations since).

Records are appended and flushed BEFORE the head replies to a mutating
RPC: a SIGKILLed head loses nothing the client was told succeeded —
flush() puts frames in the kernel page cache, which survives process
death (power loss is out of scope, matching a local-Redis deployment).

Generation scheme: appends go to ``wal/wal.<gen>``. Taking a snapshot
ROLLS to a fresh generation first, so the snapshot (stamped with the
new generation) covers every record in older files, which are deleted
once the snapshot hits disk. Restore = load snapshot, then replay all
generations >= its stamp, tolerating a torn final frame (kill mid-
append)."""
from __future__ import annotations

import os
import pickle
import struct
from typing import Iterator, List


class HeadWAL:
    def __init__(self, session_dir: str):
        self.dir = os.path.join(session_dir, "wal")
        os.makedirs(self.dir, exist_ok=True)
        self.gen = 0
        self._f = None

    def _path(self, gen: int) -> str:
        return os.path.join(self.dir, f"wal.{gen:08d}")

    def existing_gens(self) -> List[int]:
        out = []
        try:
            for name in os.listdir(self.dir):
                if name.startswith("wal."):
                    try:
                        out.append(int(name[4:]))
                    except ValueError:
                        pass
        except OSError:
            pass
        return sorted(out)

    def open_active(self):
        """Begin appending to a fresh generation above every existing
        one (older files await replay or the next snapshot's cleanup)."""
        gens = self.existing_gens()
        self.gen = (gens[-1] + 1) if gens else 1
        self._f = open(self._path(self.gen), "ab")

    def roll(self) -> int:
        """Switch appends to the next generation (snapshot capture
        runs between roll() and the next append, on the event loop, so
        the snapshot covers exactly gens < the new one)."""
        if self._f is not None:
            self._f.close()
        self.gen += 1
        self._f = open(self._path(self.gen), "ab")
        return self.gen

    def append(self, rec: dict):
        if self._f is None:
            return
        payload = pickle.dumps(rec, protocol=5)
        pos = self._f.tell()
        try:
            self._f.write(struct.pack("<I", len(payload)) + payload)
            self._f.flush()
        except OSError:
            # A partial frame mid-file would silently END replay there,
            # shadowing every later (acknowledged!) record. Truncate
            # back to the known-good offset before letting the RPC
            # fail unacknowledged.
            try:
                self._f.close()
            except OSError:
                pass
            try:
                self._f = open(self._path(self.gen), "ab")
                self._f.truncate(pos)
            except OSError:
                # Damaged file unrepairable: abandon it for a fresh
                # generation — replay treats its torn tail as that
                # file's end and CONTINUES with later generations, so
                # subsequent acked records stay reachable.
                if self._f is not None:
                    try:
                        self._f.close()  # don't leak the damaged fd
                    except OSError:
                        pass
                try:
                    self._f = open(self._path(self.gen + 1), "ab")
                    self.gen += 1
                except OSError:
                    self._f = None  # no durability until next roll
            raise

    def replay_from(self, first_gen: int) -> Iterator[dict]:
        """Records of every generation >= ``first_gen``, in append
        order. A torn tail (kill -9 mid-append, or a file abandoned
        after an unrepairable failed append) ends that file's replay;
        later generations still replay."""
        for g in self.existing_gens():
            if g < first_gen:
                continue
            try:
                with open(self._path(g), "rb") as f:
                    data = f.read()
            except OSError:
                continue
            off = 0
            while off + 4 <= len(data):
                (n,) = struct.unpack_from("<I", data, off)
                if off + 4 + n > len(data):
                    break  # torn final frame
                try:
                    yield pickle.loads(data[off + 4:off + 4 + n])
                except Exception:  # noqa: BLE001 - corrupt frame ends file
                    break
                off += 4 + n

    def drop_below(self, gen: int):
        """Delete generations fully covered by a persisted snapshot."""
        for g in self.existing_gens():
            if g < gen and g != self.gen:
                try:
                    os.unlink(self._path(g))
                except OSError:
                    pass

    def close(self):
        if self._f is not None:
            self._f.close()
            self._f = None
