"""Runtime-env plugin protocol: extensible per-key env materialization.

Capability parity with the reference's plugin architecture (reference:
``python/ray/_private/runtime_env/plugin.py:1`` — ``RuntimeEnvPlugin``
ABC with per-key validate/create/modify-context hooks, priority ordering,
and ``RAY_RUNTIME_ENV_PLUGINS`` third-party loading), re-designed for
this runtime's driver/worker split:

- ``validate(value)``   — driver, raise on malformed config
- ``prepare(value, ctx)``— driver: upload blobs via ``ctx.kv_put``,
  return the JSON-safe wire form shipped in the task/actor spec
- ``apply(wire, ctx)``  — worker: materialize (extract/install/chdir/
  sys.path) using ``ctx.kv_get`` + ``ctx.scratch_dir``

Built-ins (env_vars, working_dir, py_modules, pip, conda) are instances
of the same protocol, registered at import; third-party plugins load
from the ``RT_RUNTIME_ENV_PLUGINS`` env var (comma-separated
``module:Class`` refs — the reference's ``RAY_RUNTIME_ENV_PLUGINS``
mechanism) or programmatically via :func:`register_plugin`.

Ordering: plugins apply sorted by ``priority`` (lower first), matching
the reference's ``RuntimeEnvPlugin.priority`` semantics — e.g. ``conda``
(interpreter-level, priority 5) applies before ``working_dir`` /
``py_modules`` (path-level, 10) so user code shadows packed packages.
"""
from __future__ import annotations

import importlib
import os
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional


@dataclass
class PrepareContext:
    """Driver-side services available to ``prepare``."""
    kv_put: Callable[[str, bytes], None]


@dataclass
class ApplyContext:
    """Worker-side services available to ``apply``."""
    kv_get: Callable[[str], Optional[bytes]]
    scratch_dir: str


class RuntimeEnvPlugin:
    """One runtime_env key's lifecycle (reference ``plugin.py:30``)."""

    #: the runtime_env dict key this plugin owns
    name: str = ""
    #: apply order, lower first (reference: ``priority``, default 10)
    priority: int = 10
    #: skip prepare() for falsy values ({} env_vars is a no-op). Leave
    #: False for third-party plugins: {} / 0 may be valid configs.
    skip_empty: bool = False

    def validate(self, value: Any) -> Any:
        """Raise ValueError on malformed config; return (possibly
        normalized) value."""
        return value

    def prepare(self, value: Any, ctx: PrepareContext) -> Any:
        """Driver side: upload any blobs, return the wire form (must be
        JSON/pickle-safe and stable — it participates in env_hash)."""
        return value

    def apply(self, wire: Any, ctx: ApplyContext) -> None:
        """Worker side: materialize the env in this process."""

    def uris(self, wire: Any) -> List[str]:
        """Cache URIs this wire form pins (for eviction accounting)."""
        return []

    # -- wire-dict adapters (built-ins override to keep their legacy
    # flat wire keys; third-party plugins live under "plugin:<name>") --
    def _prepare_into(self, value: Any, out: dict,
                      ctx: PrepareContext) -> None:
        out[f"plugin:{self.name}"] = self.prepare(value, ctx)

    def _apply_from(self, wire: dict, ctx: ApplyContext) -> None:
        w = wire.get(f"plugin:{self.name}")
        if w is not None:
            self.apply(w, ctx)


_registry: Dict[str, RuntimeEnvPlugin] = {}
_env_loaded = False


def register_plugin(plugin: RuntimeEnvPlugin, *,
                    allow_override: bool = False) -> None:
    if not plugin.name:
        raise ValueError("plugin needs a non-empty .name")
    if plugin.name in _registry and not allow_override:
        raise ValueError(f"runtime_env plugin {plugin.name!r} already "
                         "registered")
    _registry[plugin.name] = plugin


def unregister_plugin(name: str) -> None:
    _registry.pop(name, None)


def _load_env_plugins() -> None:
    """Load third-party plugins named in RT_RUNTIME_ENV_PLUGINS
    (``module:Class`` comma-separated), once per process."""
    global _env_loaded
    if _env_loaded:
        return
    _env_loaded = True
    spec = os.environ.get("RT_RUNTIME_ENV_PLUGINS", "")
    for ref in filter(None, (s.strip() for s in spec.split(","))):
        mod_name, _, cls_name = ref.partition(":")
        cls = getattr(importlib.import_module(mod_name), cls_name)
        register_plugin(cls(), allow_override=True)


def plugins() -> List[RuntimeEnvPlugin]:
    """Registered plugins in apply order (priority, then name)."""
    _load_env_plugins()
    return sorted(_registry.values(), key=lambda p: (p.priority, p.name))


def get_plugin(name: str) -> Optional[RuntimeEnvPlugin]:
    _load_env_plugins()
    return _registry.get(name)
