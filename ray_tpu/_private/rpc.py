"""Minimal high-throughput RPC over unix-domain / TCP sockets.

Capability-parity stand-in for the reference's gRPC wrapper layer
(reference: ``src/ray/rpc/grpc_server.h``, ``client_call.h``) designed fresh
for this runtime: asyncio streams, length-prefixed multi-frame messages,
pipelined request/response with 8-byte request ids, and a push (one-way)
mode for data-plane transfers. Control payloads are pickled python objects;
data frames ride as raw buffers (no copy into the pickle stream).

Wire format per message:
    <u32 nframes> <u64 size_0> ... <u64 size_{n-1}> frame_0 ... frame_{n-1}
frame_0 is always the pickled tuple (kind, req_id, method, payload_meta);
remaining frames are out-of-band buffers.
"""
from __future__ import annotations

import asyncio
import itertools
import pickle
import struct
from collections import deque
from typing import Any, Awaitable, Callable, Dict, List, Optional

KIND_REQUEST = 0
KIND_RESPONSE = 1
KIND_ERROR = 2
KIND_PUSH = 3  # one-way, no response

_req_counter = itertools.count(1)

# Strong references for fire-and-forget tasks. asyncio's event loop keeps
# only WEAK references to tasks (documented in ``loop.create_task``): a
# task whose coroutine is suspended with no other referent can be garbage
# collected mid-execution. For a serve task that means the reply is simply
# never sent — the peer blocks forever with the connection healthy. This
# was the root cause of the round-4 cold-suite hang (a ``list_nodes``
# reply vanished while the head kept running). Every fire-and-forget task
# in the runtime must go through ``spawn``.
_background_tasks: set = set()


def spawn(coro, loop=None) -> asyncio.Task:
    """``create_task`` with a strong reference held until the task ends."""
    task = (loop or asyncio.get_running_loop()).create_task(coro)
    _background_tasks.add(task)
    task.add_done_callback(_background_tasks.discard)
    if len(_background_tasks) > 512:
        # A loop closed with tasks still pending never runs their done
        # callbacks — prune those so the strong-ref set can't grow
        # without bound across cluster create/teardown cycles.
        for t in [t for t in _background_tasks if t.get_loop().is_closed()]:
            _background_tasks.discard(t)
    return task


class RpcError(Exception):
    pass


class ConnectionLost(RpcError):
    pass


async def _read_msg(reader: asyncio.StreamReader) -> List[bytes]:
    head = await reader.readexactly(4)
    (n,) = struct.unpack("<I", head)
    sizes = struct.unpack(f"<{n}Q", await reader.readexactly(8 * n))
    frames = []
    for s in sizes:
        frames.append(await reader.readexactly(s))
    return frames


def _write_msg(writer: asyncio.StreamWriter, frames: List[bytes]) -> None:
    head = struct.pack("<I", len(frames)) + b"".join(
        struct.pack("<Q", len(f)) for f in frames
    )
    writer.write(head)
    for f in frames:
        writer.write(bytes(f) if not isinstance(f, (bytes, bytearray)) else f)


Handler = Callable[[str, Any, List[bytes], "Connection"], Awaitable[Any]]


class Connection:
    """One duplex connection carrying pipelined requests in both directions.

    All outbound traffic funnels through a single writer task that
    streams each message in bounded pieces with flow control. Two
    reasons: (a) asyncio transports compact their write buffer with an
    O(buffered) memmove per socket send, so letting a 64MB reply sit in
    the buffer costs QUADRATIC memmove time (measured: 2 concurrent
    64MB replies = 5s vs 0.4s); (b) senders on different tasks can
    never interleave bytes inside one another's frames."""

    # Max bytes handed to the transport per piece / drain threshold.
    _WRITE_PIECE = 1 << 20
    _WRITE_HIGH = 4 << 20

    def __init__(self, reader, writer, handler: Optional[Handler] = None):
        self._reader = reader
        self._writer = writer
        self._handler = handler
        self._pending: Dict[int, asyncio.Future] = {}
        self._closed = False
        self._recv_task: Optional[asyncio.Task] = None
        self._send_q: "deque" = deque()
        self._send_wake: Optional[asyncio.Event] = None
        self._send_task: Optional[asyncio.Task] = None
        self._send_busy = False  # writer mid-message (cancel = truncation)
        self.on_close: Optional[Callable[[], None]] = None
        try:
            writer.transport.set_write_buffer_limits(
                high=self._WRITE_HIGH, low=self._WRITE_PIECE)
        except Exception:  # noqa: BLE001 - non-standard transport
            pass

    def start(self):
        loop = asyncio.get_running_loop()
        self._send_wake = asyncio.Event()
        self._recv_task = loop.create_task(self._recv_loop())
        self._send_task = loop.create_task(self._send_loop())

    def _enqueue(self, frames: List[bytes]) -> None:
        """Queue one message for the writer task (callers must already
        be on the loop thread; FIFO order == submission order)."""
        self._send_q.append(frames)
        if self._send_wake is not None:
            self._send_wake.set()

    async def _send_loop(self):
        tr = self._writer.transport
        try:
            while True:
                while not self._send_q:
                    self._send_wake.clear()
                    await self._send_wake.wait()
                frames = self._send_q.popleft()
                self._send_busy = True
                views = []
                for f in frames:
                    v = memoryview(f)
                    if v.format != "B" or not v.contiguous:
                        v = memoryview(bytes(f))
                    views.append(v)
                head = struct.pack("<I", len(views)) + b"".join(
                    struct.pack("<Q", v.nbytes) for v in views)
                self._writer.write(head)
                for view in views:
                    for off in range(0, view.nbytes, self._WRITE_PIECE):
                        self._writer.write(view[off:off + self._WRITE_PIECE])
                        if tr.get_write_buffer_size() > self._WRITE_HIGH:
                            await self._writer.drain()
                if tr.get_write_buffer_size() > self._WRITE_HIGH:
                    await self._writer.drain()
                self._send_busy = False
        except asyncio.CancelledError:
            raise
        except (ConnectionResetError, OSError):
            pass

    async def _recv_loop(self):
        try:
            while True:
                frames = await _read_msg(self._reader)
                kind, req_id, method, payload = pickle.loads(frames[0])
                bufs = frames[1:]
                if kind == KIND_REQUEST:
                    spawn(self._serve_one(req_id, method, payload, bufs))
                elif kind == KIND_PUSH:
                    spawn(self._serve_push(method, payload, bufs))
                elif kind == KIND_RESPONSE:
                    fut = self._pending.pop(req_id, None)
                    if fut is not None and not fut.done():
                        fut.set_result((payload, bufs))
                elif kind == KIND_ERROR:
                    fut = self._pending.pop(req_id, None)
                    if fut is not None and not fut.done():
                        fut.set_exception(RpcError(payload))
        except (asyncio.IncompleteReadError, ConnectionResetError, OSError):
            pass
        finally:
            self._fail_all(ConnectionLost("connection closed"))
            if self._send_task is not None:
                self._send_task.cancel()
            if self.on_close:
                self.on_close()

    async def _serve_one(self, req_id, method, payload, bufs):
        try:
            result = await self._handler(method, payload, bufs, self)
            if isinstance(result, tuple) and len(result) == 2 and isinstance(
                result[1], list
            ):
                meta, out_bufs = result
            else:
                meta, out_bufs = result, []
            frames = [pickle.dumps((KIND_RESPONSE, req_id, method, meta))] + out_bufs
            self._enqueue(frames)
        except Exception as e:  # noqa: BLE001 - errors cross the wire
            import traceback

            msg = f"{type(e).__name__}: {e}\n{traceback.format_exc()}"
            try:
                self._enqueue(
                    [pickle.dumps((KIND_ERROR, req_id, method, msg))])
            except Exception:
                pass

    async def _serve_push(self, method, payload, bufs):
        try:
            await self._handler(method, payload, bufs, self)
        except Exception:
            import traceback

            traceback.print_exc()

    def _fail_all(self, exc):
        self._closed = True
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(exc)
        self._pending.clear()

    def send_request(self, method: str, payload: Any = None,
                     bufs: List[bytes] = ()) -> asyncio.Future:
        """Write the request synchronously (ordering!) and return the reply
        future. Must be called from the event-loop thread."""
        if self._closed:
            raise ConnectionLost("connection closed")
        req_id = next(_req_counter)
        fut = asyncio.get_running_loop().create_future()
        fut.rt_req_id = req_id  # lets a timed-out call drop its entry O(1)
        self._pending[req_id] = fut
        frames = [pickle.dumps((KIND_REQUEST, req_id, method, payload))] + list(bufs)
        self._enqueue(frames)
        return fut

    async def call(self, method: str, payload: Any = None,
                   bufs: List[bytes] = (), timeout: Optional[float] = None):
        fut = self.send_request(method, payload, bufs)
        if timeout is not None:
            try:
                payload, out_bufs = await asyncio.wait_for(fut, timeout)
            except asyncio.TimeoutError:
                self._pending.pop(getattr(fut, "rt_req_id", None), None)
                raise RpcError(
                    f"rpc '{method}' got no reply within {timeout}s "
                    f"(connection still open — peer lost the request?)")
        else:
            payload, out_bufs = await fut
        return (payload, out_bufs) if out_bufs else (payload, [])

    async def call_simple(self, method: str, payload: Any = None,
                          timeout: Optional[float] = None):
        meta, _ = await self.call(method, payload, timeout=timeout)
        return meta

    def push(self, method: str, payload: Any = None, bufs: List[bytes] = ()):
        if self._closed:
            raise ConnectionLost("connection closed")
        frames = [pickle.dumps((KIND_PUSH, 0, method, payload))] + list(bufs)
        self._enqueue(frames)

    async def close(self):
        self._closed = True
        # Flush BEFORE cancelling the recv task: its finally-block
        # cancels the writer, which would drop queued replies (the peer
        # would see ConnectionLost instead of its result). Wait for the
        # in-flight message too — cancelling mid-message truncates a
        # frame on the wire, corrupting everything already flushed.
        if self._send_task and (self._send_q or self._send_busy):
            for _ in range(200):
                if not self._send_q and not self._send_busy:
                    break
                await asyncio.sleep(0.01)
        if self._recv_task:
            self._recv_task.cancel()
        if self._send_task:
            self._send_task.cancel()
        try:
            self._writer.close()
            await self._writer.wait_closed()
        except Exception:
            pass


class RpcServer:
    def __init__(self, handler: Handler, path: Optional[str] = None,
                 host: Optional[str] = None, port: int = 0):
        self._handler = handler
        self._path = path
        self._host = host
        self._port = port
        self._server: Optional[asyncio.AbstractServer] = None
        self.connections: List[Connection] = []
        self.on_connect: Optional[Callable[[Connection], None]] = None

    async def start(self):
        if self._path:
            self._server = await asyncio.start_unix_server(
                self._on_client, path=self._path
            )
        else:
            self._server = await asyncio.start_server(
                self._on_client, host=self._host or "127.0.0.1", port=self._port
            )
            self._port = self._server.sockets[0].getsockname()[1]
        return self

    @property
    def address(self):
        return self._path or ("127.0.0.1", self._port)

    async def _on_client(self, reader, writer):
        conn = Connection(reader, writer, self._handler)
        self.connections.append(conn)
        conn.on_close = lambda: self.connections.remove(conn) if conn in self.connections else None
        conn.start()
        if self.on_connect:
            self.on_connect(conn)

    async def stop(self):
        # Order matters on 3.12 where ``Server.wait_closed`` blocks until
        # every connection handler finishes: first stop ACCEPTING (so no
        # connection can slip in mid-drain), then close live connections,
        # then wait (timeout as a backstop for handlers that ignore the
        # close). The old drain-after-wait order deadlocked shutdown
        # whenever a client had attached.
        if self._server:
            self._server.close()
        for c in list(self.connections):
            await c.close()
        if self._server:
            try:
                await asyncio.wait_for(self._server.wait_closed(),
                                       timeout=5.0)
            except Exception:
                pass


async def connect(address, handler: Optional[Handler] = None,
                  timeout: float = 10.0) -> Connection:
    async def _null_handler(method, payload, bufs, conn):
        raise RpcError(f"no handler for {method}")

    if isinstance(address, str):
        reader, writer = await asyncio.wait_for(
            asyncio.open_unix_connection(address), timeout
        )
    else:
        host, port = address
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(host, port), timeout
        )
    conn = Connection(reader, writer, handler or _null_handler)
    conn.start()
    return conn
