"""TPU detection and gang-scheduling resources.

Capability parity with the reference's TPU accelerator manager
(reference: ``python/ray/_private/accelerators/tpu.py:75``
TPUAcceleratorManager; ``:363`` documents the ``TPU-v4-16-head`` gang
pattern): every node advertises its chip count as ``TPU``, and worker 0 of
a slice additionally advertises ``TPU-{pod_type}-head: 1`` so a gang can
anchor itself to exactly one slice and fan out over its hosts.

Zero-egress redesign: the reference polls GCE instance metadata over HTTP;
here detection is purely env-var + device-file based (the same variables
the TPU runtime/GKE injects), with ``RT_TPU_TOPOLOGY`` as an explicit
override for tests and air-gapped machines.
"""
from __future__ import annotations

import os
from typing import Dict, Optional, Tuple

# Long-form GCE accelerator types → short version names.
_VERSION_ALIASES = {
    "v5litepod": "v5e",
    "v5lite": "v5e",
    "v6litepod": "v6e",
    "v6lite": "v6e",
}

# Chips per host per TPU generation (v5e pods come in 4- and 8-chip host
# shapes; override with RT_TPU_CHIPS_PER_HOST when needed).
_CHIPS_PER_HOST = {"v2": 4, "v3": 4, "v4": 4, "v5e": 4, "v5p": 4, "v6e": 4}


def normalize_pod_type(raw: str) -> str:
    """'v5litepod-16' → 'v5e-16'; already-short names pass through."""
    version, _, chips = raw.partition("-")
    version = _VERSION_ALIASES.get(version, version)
    return f"{version}-{chips}" if chips else version


def parse_topology(topology: str) -> Tuple[str, int]:
    """'v5e-16' → ('v5e', 16). Raises ValueError on malformed input."""
    topology = normalize_pod_type(topology)
    version, _, chips = topology.partition("-")
    if not chips or not chips.isdigit():
        raise ValueError(
            f"malformed TPU topology {topology!r}; expected "
            "'<version>-<chips>' like 'v5e-16'")
    return version, int(chips)


def chips_per_host(version: str) -> int:
    env = os.environ.get("RT_TPU_CHIPS_PER_HOST")
    if env:
        return int(env)
    return _CHIPS_PER_HOST.get(version, 4)


def num_hosts(topology: str) -> int:
    version, chips = parse_topology(topology)
    per = chips_per_host(version)
    return max(1, chips // per)


def detect_pod_type() -> Optional[str]:
    """The slice this host belongs to, e.g. 'v5e-16' (None off-TPU)."""
    raw = (os.environ.get("RT_TPU_TOPOLOGY")
           or os.environ.get("TPU_ACCELERATOR_TYPE"))
    return normalize_pod_type(raw) if raw else None


def detect_worker_id() -> int:
    """This host's index within its slice (0 on single-host)."""
    return int(os.environ.get("TPU_WORKER_ID", "0") or 0)


def head_resource_name(pod_type: str) -> str:
    return f"TPU-{normalize_pod_type(pod_type)}-head"


def gang_resources(num_chips: float, pod_type: Optional[str] = None,
                   worker_id: Optional[int] = None) -> Dict[str, float]:
    """Extra node resources advertised alongside ``TPU: num_chips``.

    Worker 0 of a slice gets the ``TPU-{pod}-head`` anchor; every worker
    gets the ``accelerator_type:TPU-{VERSION}`` label-style resource.
    ``pod_type``/``worker_id`` default to env detection (a real TPU VM
    host); explicit values let provisioners (the autoscaler's slice
    provider) mint the same shape for hosts they are about to launch.
    """
    pod = normalize_pod_type(pod_type) if pod_type else detect_pod_type()
    if not pod or not num_chips:
        return {}
    version, _ = parse_topology(pod)
    res: Dict[str, float] = {
        f"accelerator_type:TPU-{version.upper()}": float(num_chips)}
    wid = detect_worker_id() if worker_id is None else worker_id
    if wid == 0:
        res[head_resource_name(pod)] = 1.0
    return res
