"""Head service: cluster control plane (GCS + raylet equivalent, single daemon).

Capability parity with the reference's GCS server (actor/node/job/KV/PG
managers — reference: ``src/ray/gcs/gcs_server/gcs_server.cc:138-236``) and
the raylet's worker pool + lease protocol (reference:
``src/ray/raylet/worker_pool.h:83``, ``node_manager.cc:1780``), re-designed
as one asyncio daemon per cluster for this runtime. Multi-host clusters
attach remote node daemons over TCP with the same protocol.

Responsibilities:
- worker pool: spawn/reuse/kill worker processes, prestart
- leases: resource-aware worker leases for normal tasks (hybrid policy)
- actors: dedicated-worker placement, restarts, named actor registry
- placement groups: bundle reservation with PACK/SPREAD/STRICT_* semantics
- KV store: function exports, library checkpoints
- pubsub: topic fan-out to subscriber connections
- health: worker process liveness -> actor death notifications
"""
from __future__ import annotations

import asyncio
import os
import subprocess
import sys
import time
from collections import defaultdict, deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set, Tuple

from . import rpc
from .config import Config
from .ids import ActorID, NodeID, PlacementGroupID, WorkerID


@dataclass
class WorkerInfo:
    worker_id: WorkerID
    address: str
    pid: int
    proc: Optional[subprocess.Popen] = None
    conn: Optional[rpc.Connection] = None
    # None = idle pool worker; "lease" = leased for normal tasks;
    # ActorID = dedicated actor worker.
    assignment: Any = None
    resources: Dict[str, float] = field(default_factory=dict)
    started_at: float = field(default_factory=time.time)


@dataclass
class ActorInfo:
    actor_id: ActorID
    name: str
    state: str  # PENDING | ALIVE | RESTARTING | DEAD
    worker: Optional[WorkerInfo]
    resources: Dict[str, float]
    max_restarts: int
    restarts_used: int = 0
    creation_spec_meta: Any = None  # for restarts
    death_cause: str = ""
    registered_at: float = 0.0
    creation_started: bool = False


@dataclass
class Bundle:
    index: int
    resources: Dict[str, float]


@dataclass
class PlacementGroupInfo:
    pg_id: PlacementGroupID
    bundles: List[Bundle]
    strategy: str
    state: str  # PENDING | CREATED | REMOVED
    name: str = ""
    # per-bundle remaining capacity
    remaining: List[Dict[str, float]] = field(default_factory=list)


class HeadService:
    def __init__(self, session_dir: str, config: Config,
                 resources: Dict[str, float]):
        self.session_dir = session_dir
        self.config = config
        self.node_id = NodeID.from_random()
        self.total_resources = dict(resources)
        self.available = dict(resources)
        self.sock_path = os.path.join(session_dir, "head.sock")
        self._server: Optional[rpc.RpcServer] = None
        self.workers: Dict[WorkerID, WorkerInfo] = {}
        self.idle: deque = deque()  # WorkerInfo, reusable pool
        self.actors: Dict[ActorID, ActorInfo] = {}
        self.named_actors: Dict[str, ActorID] = {}
        self.pgs: Dict[PlacementGroupID, PlacementGroupInfo] = {}
        self.kv: Dict[str, Dict[str, bytes]] = defaultdict(dict)  # namespace->k->v
        self._pending_leases: deque = deque()  # (resources, future)
        self._registration_waiters: Dict[WorkerID, asyncio.Future] = {}
        self._subs: Dict[str, List[rpc.Connection]] = defaultdict(list)
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._reaper_task = None
        self.job_counter = 0
        self._spawn_env = dict(os.environ)
        # Workers must be able to import ray_tpu no matter the driver's cwd
        # (the driver may have put the package on sys.path manually).
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        pp = self._spawn_env.get("PYTHONPATH", "")
        if pkg_root not in pp.split(os.pathsep):
            self._spawn_env["PYTHONPATH"] = (
                pkg_root + (os.pathsep + pp if pp else ""))
        self.task_events: deque = deque(maxlen=100_000)
        self._shutting_down = False

    # ------------------------------------------------------------- lifecycle
    async def start(self):
        self._loop = asyncio.get_running_loop()
        os.makedirs(os.path.join(self.session_dir, "workers"), exist_ok=True)
        os.makedirs(os.path.join(self.session_dir, "logs"), exist_ok=True)
        self._server = rpc.RpcServer(self._handle, path=self.sock_path)
        await self._server.start()
        self._reaper_task = self._loop.create_task(self._reap_loop())
        return self

    async def stop(self):
        self._shutting_down = True
        if self._reaper_task:
            self._reaper_task.cancel()
        for w in list(self.workers.values()):
            if w.proc is not None:
                try:
                    w.proc.terminate()
                except Exception:
                    pass
        # Give children a moment, then hard-kill.
        deadline = time.time() + 2.0
        for w in list(self.workers.values()):
            if w.proc is None:
                continue
            try:
                w.proc.wait(timeout=max(0.05, deadline - time.time()))
            except Exception:
                try:
                    w.proc.kill()
                except Exception:
                    pass
        if self._server:
            await self._server.stop()

    async def _reap_loop(self):
        period = self.config.health_check_period_s
        while True:
            await asyncio.sleep(period)
            for w in list(self.workers.values()):
                if w.proc is not None and w.proc.poll() is not None:
                    await self._on_worker_death(w, f"exit code {w.proc.returncode}")
            # Registered-but-never-created actors (client died between the
            # register and create RPCs) would otherwise pin their name
            # forever; expire them after the lease timeout.
            ttl = self.config.worker_lease_timeout_s
            now = time.time()
            for a in list(self.actors.values()):
                if (a.state == "PENDING" and not a.creation_started
                        and a.registered_at
                        and now - a.registered_at > ttl):
                    self._mark_actor_dead(a, "registration expired: "
                                             "creation never requested")

    async def _on_worker_death(self, w: WorkerInfo, cause: str):
        self.workers.pop(w.worker_id, None)
        try:
            self.idle.remove(w)
        except ValueError:
            pass
        self._release_charged(w.resources)
        w.resources = {}
        if isinstance(w.assignment, ActorID):
            actor = self.actors.get(w.assignment)
            if actor and actor.state != "DEAD":
                await self._handle_actor_failure(actor, cause)
        self._pump_leases()

    async def _handle_actor_failure(self, actor: ActorInfo, cause: str):
        if actor.restarts_used < actor.max_restarts:
            actor.restarts_used += 1
            actor.state = "RESTARTING"
            self.publish(f"actor:{actor.actor_id.hex()}",
                         {"state": "RESTARTING", "cause": cause})
            try:
                await self._place_actor(actor)
                self.publish(f"actor:{actor.actor_id.hex()}",
                             {"state": "ALIVE",
                              "address": actor.worker.address,
                              "restarts": actor.restarts_used})
            except Exception as e:  # noqa: BLE001
                self._mark_actor_dead(actor, f"restart failed: {e}")
        else:
            self._mark_actor_dead(actor, cause)

    def _mark_actor_dead(self, actor: ActorInfo, cause: str):
        actor.state = "DEAD"
        actor.death_cause = cause
        actor.worker = None
        if actor.name:
            self.named_actors.pop(actor.name, None)
        self.publish(f"actor:{actor.actor_id.hex()}",
                     {"state": "DEAD", "cause": cause})

    # ------------------------------------------------------------- resources
    def _can_fit(self, req: Dict[str, float]) -> bool:
        return all(self.available.get(k, 0.0) + 1e-9 >= v for k, v in req.items())

    def _acquire_resources(self, req: Dict[str, float]):
        for k, v in req.items():
            self.available[k] = self.available.get(k, 0.0) - v

    def _release_resources(self, req: Dict[str, float]):
        for k, v in req.items():
            self.available[k] = self.available.get(k, 0.0) + v

    def _release_charged(self, charged: Dict[str, Any]):
        """Release either node resources or a placement-group bundle charge."""
        if not charged:
            return
        if "__pg__" in charged:
            pg_id, idx, req = charged["__pg__"]
            pg = self.pgs.get(pg_id)
            if pg is not None and pg.state == "CREATED":
                rem = pg.remaining[idx]
                for k, v in req.items():
                    rem[k] = rem.get(k, 0.0) + v
        else:
            self._release_resources(charged)

    # ------------------------------------------------------------- workers
    async def _spawn_worker(self) -> WorkerInfo:
        worker_id = WorkerID.from_random()
        log = open(os.path.join(self.session_dir, "logs",
                                f"worker-{worker_id.hex()[:12]}.log"), "ab")
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.worker_main",
             "--session-dir", self.session_dir,
             "--worker-id", worker_id.hex(),
             "--head-sock", self.sock_path],
            stdout=log, stderr=subprocess.STDOUT,
            env=self._spawn_env,
            cwd=os.getcwd(),
        )
        fut = self._loop.create_future()
        self._registration_waiters[worker_id] = fut
        try:
            info: WorkerInfo = await asyncio.wait_for(
                fut, timeout=self.config.worker_lease_timeout_s
            )
        except asyncio.TimeoutError:
            proc.kill()
            raise RuntimeError("worker failed to register in time")
        finally:
            self._registration_waiters.pop(worker_id, None)
        info.proc = proc
        return info

    async def _get_worker(self) -> WorkerInfo:
        while self.idle:
            w = self.idle.popleft()
            if w.worker_id in self.workers:
                return w
        return await self._spawn_worker()

    def _return_worker(self, w: WorkerInfo):
        if w.worker_id in self.workers:
            w.assignment = None
            self.idle.append(w)

    # ------------------------------------------------------------- leases
    def _try_grant(self, req: Dict[str, float], pg_meta) -> bool:
        if pg_meta is not None:
            pg_id, bundle_index = pg_meta
            pg = self.pgs.get(pg_id)
            if pg is None or pg.state != "CREATED":
                return False
            return self._bundle_can_fit(pg, bundle_index, req)
        return self._can_fit(req)

    def _bundle_can_fit(self, pg: PlacementGroupInfo, bundle_index: int,
                        req: Dict[str, float]) -> bool:
        idxs = [bundle_index] if bundle_index >= 0 else range(len(pg.bundles))
        for i in idxs:
            rem = pg.remaining[i]
            if all(rem.get(k, 0.0) + 1e-9 >= v for k, v in req.items()):
                return True
        return False

    def _bundle_acquire(self, pg: PlacementGroupInfo, bundle_index: int,
                        req: Dict[str, float]) -> int:
        idxs = [bundle_index] if bundle_index >= 0 else range(len(pg.bundles))
        for i in idxs:
            rem = pg.remaining[i]
            if all(rem.get(k, 0.0) + 1e-9 >= v for k, v in req.items()):
                for k, v in req.items():
                    rem[k] = rem.get(k, 0.0) - v
                return i
        raise RuntimeError("bundle cannot fit request")

    async def _grant_lease(self, req: Dict[str, float], pg_meta) -> dict:
        if pg_meta is not None:
            pg = self.pgs[pg_meta[0]]
            idx = self._bundle_acquire(pg, pg_meta[1], req)
            charged = {"__pg__": (pg.pg_id, idx, dict(req))}
        else:
            self._acquire_resources(req)
            charged = dict(req)
        w = await self._get_worker()
        w.assignment = "lease"
        w.resources = charged
        return {"worker_id": w.worker_id.hex(), "address": w.address}

    def _pump_leases(self):
        """Grant queued lease requests that now fit."""
        still = deque()
        while self._pending_leases:
            req, pg_meta, fut = self._pending_leases.popleft()
            if fut.done():
                continue
            if self._try_grant(req, pg_meta):
                self._loop.create_task(self._grant_into(req, pg_meta, fut))
            else:
                still.append((req, pg_meta, fut))
        self._pending_leases = still

    async def _grant_into(self, req, pg_meta, fut):
        try:
            res = await self._grant_lease(req, pg_meta)
            if not fut.done():
                fut.set_result(res)
        except Exception as e:  # noqa: BLE001
            if not fut.done():
                fut.set_exception(e)

    # ------------------------------------------------------------- actors
    async def _place_actor(self, actor: ActorInfo):
        w = await self._get_worker()
        w.assignment = actor.actor_id
        actor.worker = w
        # Ask the worker to instantiate the actor.
        meta, _ = await w.conn.call("create_actor", actor.creation_spec_meta)
        actor.state = "ALIVE"
        return w

    # ------------------------------------------------------------- pubsub
    def publish(self, topic: str, msg: Any):
        dead = []
        for conn in self._subs.get(topic, []):
            try:
                conn.push("pubsub", {"topic": topic, "msg": msg})
            except Exception:
                dead.append(conn)
        for c in dead:
            try:
                self._subs[topic].remove(c)
            except ValueError:
                pass

    # ------------------------------------------------------------- handler
    async def _handle(self, method: str, payload: Any, bufs: List[bytes],
                      conn: rpc.Connection):
        if method == "subscribe":
            topic = payload["topic"]
            self._subs[topic].append(conn)
            return {}
        if method == "unsubscribe":
            topic = payload["topic"]
            try:
                self._subs[topic].remove(conn)
            except ValueError:
                pass
            return {}
        if method == "publish":
            self.publish(payload["topic"], payload["msg"])
            return {}
        fn = getattr(self, "_rpc_" + method, None)
        if fn is None:
            raise rpc.RpcError(f"head: unknown method {method}")
        return await fn(payload, bufs)

    async def _rpc_register_worker(self, payload, bufs):
        worker_id = WorkerID.from_hex(payload["worker_id"])
        info = WorkerInfo(worker_id=worker_id, address=payload["address"],
                          pid=payload["pid"])
        # The registering connection is the one this call arrived on; we
        # instead open a dedicated control connection to the worker.
        info.conn = await rpc.connect(payload["address"], self._handle)
        self.workers[worker_id] = info
        fut = self._registration_waiters.get(worker_id)
        if fut is not None and not fut.done():
            fut.set_result(info)
        else:
            self.idle.append(info)  # adopted externally-started worker
        return {"node_id": self.node_id.hex(),
                "config": self.config.to_dict()}

    async def _rpc_lease_worker(self, payload, bufs):
        req: Dict[str, float] = payload.get("resources") or {}
        strategy = payload.get("strategy") or {}
        pg_meta = None
        if strategy.get("kind") == "PLACEMENT_GROUP":
            pg_meta = (PlacementGroupID.from_hex(strategy["pg_id"]),
                       strategy.get("bundle_index", -1))
        if self._try_grant(req, pg_meta):
            return await self._grant_lease(req, pg_meta)
        fut = self._loop.create_future()
        self._pending_leases.append((req, pg_meta, fut))
        timeout = payload.get("timeout", self.config.worker_lease_timeout_s)
        try:
            return await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            raise rpc.RpcError(
                f"lease timed out after {timeout}s: requested {req}, "
                f"available {self.available}"
            )

    async def _rpc_return_lease(self, payload, bufs):
        worker_id = WorkerID.from_hex(payload["worker_id"])
        w = self.workers.get(worker_id)
        if w is not None:
            charged = w.resources
            w.resources = {}
            self._release_charged(charged)
            if payload.get("kill"):
                try:
                    w.proc and w.proc.terminate()
                except Exception:
                    pass
                self.workers.pop(worker_id, None)
            else:
                self._return_worker(w)
        self._pump_leases()
        return {}

    def _register_actor(self, payload) -> ActorInfo:
        """Record actor metadata + name (state PENDING). Mirrors the sync
        half of the reference's split (``gcs_actor_manager.cc:311``
        RegisterActor vs :340 CreateActor)."""
        actor_id = ActorID.from_hex(payload["actor_id"])
        existing = self.actors.get(actor_id)
        if existing is not None and existing.state != "DEAD":
            return existing
        # DEAD records (e.g. a failed earlier placement) are rebuilt so a
        # retried create re-registers the name it lost in _mark_actor_dead.
        name = payload.get("name") or ""
        if name and name in self.named_actors:
            raise rpc.RpcError(f"actor name '{name}' already taken")
        actor = ActorInfo(
            actor_id=actor_id, name=name, state="PENDING", worker=None,
            resources=payload.get("resources") or {},
            max_restarts=payload.get("max_restarts", 0),
            creation_spec_meta=payload["spec_meta"],
            registered_at=time.time(),
        )
        self.actors[actor_id] = actor
        if name:
            self.named_actors[name] = actor_id
        return actor

    async def _rpc_register_actor(self, payload, bufs):
        self._register_actor(payload)
        return {}

    async def _rpc_create_actor(self, payload, bufs):
        actor = self._register_actor(payload)
        actor.creation_started = True
        req = payload.get("resources") or {}
        strategy = payload.get("strategy") or {}
        pg_meta = None
        if strategy.get("kind") == "PLACEMENT_GROUP":
            pg_meta = (PlacementGroupID.from_hex(strategy["pg_id"]),
                       strategy.get("bundle_index", -1))
        deadline = time.time() + self.config.worker_lease_timeout_s
        while not self._try_grant(req, pg_meta):
            if time.time() > deadline:
                self._mark_actor_dead(actor, "resources unavailable")
                raise rpc.RpcError(
                    f"cannot place actor: requested {req}, available "
                    f"{self.available}")
            await asyncio.sleep(0.02)
        if pg_meta is not None:
            pg = self.pgs[pg_meta[0]]
            idx = self._bundle_acquire(pg, pg_meta[1], req)
            charged = {"__pg__": (pg.pg_id, idx, dict(req))}
        else:
            self._acquire_resources(req)
            charged = dict(req)
        try:
            w = await self._place_actor(actor)
        except Exception as e:  # noqa: BLE001
            self._release_charged(charged)
            self._mark_actor_dead(actor, f"creation failed: {e}")
            raise
        w.resources = charged
        return {"address": w.address, "worker_id": w.worker_id.hex()}

    async def _rpc_get_actor(self, payload, bufs):
        actor_id = ActorID.from_hex(payload["actor_id"])
        a = self.actors.get(actor_id)
        if a is None:
            raise rpc.RpcError(f"no such actor {actor_id}")
        return {"state": a.state,
                "address": a.worker.address if a.worker else None,
                "death_cause": a.death_cause,
                "name": a.name}

    async def _rpc_get_named_actor(self, payload, bufs):
        name = payload["name"]
        actor_id = self.named_actors.get(name)
        if actor_id is None:
            raise rpc.RpcError(f"no actor named '{name}'")
        a = self.actors[actor_id]
        return {"actor_id": actor_id.hex(), "state": a.state,
                "address": a.worker.address if a.worker else None}

    async def _rpc_list_actors(self, payload, bufs):
        out = []
        for a in self.actors.values():
            out.append({"actor_id": a.actor_id.hex(), "name": a.name,
                        "state": a.state,
                        "resources": a.resources,
                        "restarts": a.restarts_used,
                        "death_cause": a.death_cause})
        return out

    async def _rpc_kill_actor(self, payload, bufs):
        actor_id = ActorID.from_hex(payload["actor_id"])
        a = self.actors.get(actor_id)
        if a is None or a.state == "DEAD":
            return {}
        a.max_restarts = 0 if payload.get("no_restart", True) else a.max_restarts
        w = a.worker
        self._mark_actor_dead(a, "killed via kill_actor")
        if w is not None:
            try:
                w.proc and w.proc.terminate()
            except Exception:
                pass
            self.workers.pop(w.worker_id, None)
            self._release_charged(w.resources)
            w.resources = {}
        self._pump_leases()
        return {}

    # ------------------------------------------------------------- KV
    async def _rpc_kv_put(self, payload, bufs):
        ns = payload.get("ns", "default")
        overwrite = payload.get("overwrite", True)
        k = payload["key"]
        store = self.kv[ns]
        if not overwrite and k in store:
            return {"added": False}
        store[k] = bufs[0] if bufs else payload.get("value", b"")
        return {"added": True}

    async def _rpc_kv_get(self, payload, bufs):
        ns = payload.get("ns", "default")
        v = self.kv[ns].get(payload["key"])
        if v is None:
            return {"found": False}
        return ({"found": True}, [bytes(v)])

    async def _rpc_kv_del(self, payload, bufs):
        ns = payload.get("ns", "default")
        existed = self.kv[ns].pop(payload["key"], None) is not None
        return {"deleted": existed}

    async def _rpc_kv_keys(self, payload, bufs):
        ns = payload.get("ns", "default")
        prefix = payload.get("prefix", "")
        return [k for k in self.kv[ns] if k.startswith(prefix)]

    # ------------------------------------------------------------- PGs
    async def _rpc_create_placement_group(self, payload, bufs):
        pg_id = PlacementGroupID.from_hex(payload["pg_id"])
        bundles = [Bundle(i, dict(b)) for i, b in enumerate(payload["bundles"])]
        strategy = payload.get("strategy", "PACK")
        total_req: Dict[str, float] = defaultdict(float)
        for b in bundles:
            for k, v in b.resources.items():
                total_req[k] += v
        pg = PlacementGroupInfo(pg_id=pg_id, bundles=bundles, strategy=strategy,
                                state="PENDING", name=payload.get("name", ""))
        self.pgs[pg_id] = pg
        deadline = time.time() + payload.get(
            "timeout", self.config.worker_lease_timeout_s)
        # Single-node: STRICT_SPREAD cannot be satisfied with >1 bundle on one
        # node; all other strategies degenerate to fitting total resources.
        if strategy == "STRICT_SPREAD" and len(bundles) > 1:
            # Honest failure until multi-node attach exists.
            self.pgs.pop(pg_id)
            raise rpc.RpcError(
                "STRICT_SPREAD with >1 bundle requires multiple nodes")
        while not self._can_fit(dict(total_req)):
            if time.time() > deadline or self._shutting_down:
                self.pgs.pop(pg_id, None)
                raise rpc.RpcError(
                    f"placement group infeasible: need {dict(total_req)}, "
                    f"total {self.total_resources}")
            await asyncio.sleep(0.02)
        self._acquire_resources(dict(total_req))
        pg.remaining = [dict(b.resources) for b in bundles]
        pg.state = "CREATED"
        return {"state": "CREATED"}

    async def _rpc_remove_placement_group(self, payload, bufs):
        pg_id = PlacementGroupID.from_hex(payload["pg_id"])
        pg = self.pgs.get(pg_id)
        if pg is None or pg.state == "REMOVED":
            return {}
        if pg.state == "CREATED":
            total: Dict[str, float] = defaultdict(float)
            for b in pg.bundles:
                for k, v in b.resources.items():
                    total[k] += v
            self._release_resources(dict(total))
        pg.state = "REMOVED"
        self._pump_leases()
        return {}

    async def _rpc_pg_state(self, payload, bufs):
        pg_id = PlacementGroupID.from_hex(payload["pg_id"])
        pg = self.pgs.get(pg_id)
        return {"state": pg.state if pg else "REMOVED"}

    # ------------------------------------------------------------- cluster
    async def _rpc_cluster_resources(self, payload, bufs):
        return dict(self.total_resources)

    async def _rpc_available_resources(self, payload, bufs):
        return dict(self.available)

    async def _rpc_report_task_events(self, payload, bufs):
        self.task_events.extend(payload)
        return {}

    async def _rpc_get_task_events(self, payload, bufs):
        limit = payload.get("limit", 10000)
        return list(self.task_events)[-limit:]

    async def _rpc_ping(self, payload, bufs):
        return {"ok": True, "time": time.time()}

    async def _rpc_new_job_id(self, payload, bufs):
        self.job_counter += 1
        return {"job_index": self.job_counter}

    async def _rpc_prestart_workers(self, payload, bufs):
        n = payload.get("n", 1)
        created = []
        for _ in range(n):
            w = await self._spawn_worker()
            self._return_worker(w)
            created.append(w.worker_id.hex())
        return created
