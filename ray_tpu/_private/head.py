"""Head service: cluster control plane (GCS + per-node raylet equivalent).

Capability parity with the reference's GCS server (actor/node/job/KV/PG
managers — reference: ``src/ray/gcs/gcs_server/gcs_server.cc:138-236``), the
raylet's worker pool + lease protocol (reference:
``src/ray/raylet/worker_pool.h:83``, ``node_manager.cc:1780``), and the
cluster scheduling policies (reference:
``src/ray/raylet/scheduling/policy/hybrid_scheduling_policy.h:50``,
``bundle_scheduling_policy.h:82-106``), re-designed as one asyncio daemon
for this runtime. The head owns all resource accounting (so placement-group
"two-phase commit" degenerates to one atomic multi-node reservation), while
remote **node daemons** (``_private/node.py``) attach over TCP, spawn
workers on their host, and report worker deaths.

Responsibilities:
- node registry: head-local node + TCP-attached remote nodes, health
- worker pool: spawn/reuse/kill worker processes per node, prestart
- leases: resource-aware worker leases (hybrid/spread/affinity policies)
- actors: dedicated-worker placement, restarts, named actor registry
- placement groups: multi-node bundle placement with PACK/SPREAD/STRICT_*
- KV store: function exports, library checkpoints
- pubsub: topic fan-out to subscriber connections
- health: worker/node liveness -> actor death notifications
"""
from __future__ import annotations

import asyncio
import json
import os
import socket
import subprocess
import sys
import time
from collections import defaultdict, deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set, Tuple

from . import reaper, rpc
from .config import Config
from .ids import ActorID, NodeID, PlacementGroupID, WorkerID
from .utils import spawn_env_with_pkg_root
from .wal import HeadWAL


@dataclass
class WorkerInfo:
    worker_id: WorkerID
    address: Any  # UDS path (local) or (host, port) tuple (remote)
    pid: int
    node: str = ""  # node_id hex
    proc: Optional[subprocess.Popen] = None
    conn: Optional[rpc.Connection] = None
    # None = idle pool worker; "lease" = leased for normal tasks;
    # ActorID = dedicated actor worker.
    assignment: Any = None
    # charge tuple: ("node", node_hex, req) | ("pg", pg_id, idx, req) | None
    charge: Any = None
    started_at: float = field(default_factory=time.time)
    leased_at: Optional[float] = None  # last lease grant (OOM ranking)


@dataclass
class NodeInfo:
    node_id: str  # hex
    hostname: str
    total: Dict[str, float]
    available: Dict[str, float]
    address: Any = None  # remote daemon address, None for head-local
    conn: Optional[rpc.Connection] = None  # daemon conn (remote only)
    idle: deque = field(default_factory=deque)
    state: str = "ALIVE"  # ALIVE | DEAD
    is_head: bool = False
    labels: Dict[str, str] = field(default_factory=dict)
    # Physical host (gethostname): co-hosted nodes share one memory
    # pool, so OOM kill grace is keyed on this, not the node id.
    # Assumes hostnames are unique across machines in one cluster (the
    # usual case; containers sharing a fixed hostname would couple
    # their kill grace windows — conservative, never unsafe).
    phys_host: str = ""
    # Per-node dashboard agent endpoint (reference dashboard/agent.py)
    agent_url: Optional[str] = None

    def utilization(self) -> float:
        fracs = [1.0 - self.available.get(k, 0.0) / v
                 for k, v in self.total.items() if v > 0]
        return max(fracs) if fracs else 0.0


@dataclass
class ActorInfo:
    actor_id: ActorID
    name: str
    state: str  # PENDING | ALIVE | RESTARTING | DEAD
    worker: Optional[WorkerInfo]
    resources: Dict[str, float]
    max_restarts: int
    restarts_used: int = 0
    creation_spec_meta: Any = None  # for restarts
    strategy: Any = None  # for restarts on another node
    death_cause: str = ""
    registered_at: float = 0.0
    creation_started: bool = False
    # Handle GC (reference: GCS kills actors when all handles go out of
    # scope). Detached actors — explicit lifetime="detached" or named —
    # opt out; handle_refs aggregates per-process inc/dec pushes.
    detached: bool = False
    handle_refs: int = 0
    pending_gc: Any = None  # asyncio task for the grace-period kill
    restart_inflight: bool = False  # _restart_actor placement running


@dataclass
class Bundle:
    index: int
    resources: Dict[str, float]


@dataclass
class PlacementGroupInfo:
    pg_id: PlacementGroupID
    bundles: List[Bundle]
    strategy: str
    state: str  # PENDING | CREATED | RESCHEDULING | REMOVED
    name: str = ""
    # per-bundle remaining capacity
    remaining: List[Dict[str, float]] = field(default_factory=list)
    # per-bundle node assignment (node_id hex, or None while lost)
    bundle_nodes: List[Optional[str]] = field(default_factory=list)
    # tombstone timestamp once state hits REMOVED (reaper prunes later)
    removed_at: Optional[float] = None


class HeadService:
    def __init__(self, session_dir: str, config: Config,
                 resources: Dict[str, float]):
        self.session_dir = session_dir
        self.config = config
        self.node_id = NodeID.from_random()
        self.sock_path = os.path.join(session_dir, "head.sock")
        self._server: Optional[rpc.RpcServer] = None
        self._tcp_server: Optional[rpc.RpcServer] = None
        local = NodeInfo(node_id=self.node_id.hex(),
                         hostname=socket.gethostname(),
                         total=dict(resources), available=dict(resources),
                         is_head=True, phys_host=socket.gethostname())
        self.nodes: Dict[str, NodeInfo] = {local.node_id: local}
        self.local_node = local
        self.workers: Dict[WorkerID, WorkerInfo] = {}
        self.actors: Dict[ActorID, ActorInfo] = {}
        self.named_actors: Dict[str, ActorID] = {}
        self.pgs: Dict[PlacementGroupID, PlacementGroupInfo] = {}
        # pg_state polls for ids with no entry: id -> first-seen time
        # (grace window for the async-create race)
        self._pg_unknown_since: Dict[PlacementGroupID, float] = {}
        self.kv: Dict[str, Dict[str, bytes]] = defaultdict(dict)
        # Object copy directory (reference capability:
        # ``ownership_based_object_directory.h`` — which nodes hold a
        # copy): oid hex -> {location key -> (address, shm_domain)}.
        # Pullers use it to spread big pulls over every live copy.
        self.object_locations: Dict[str, Dict[str, tuple]] = {}
        # (object hex, domain) -> (claimer key, ts): one cross-domain
        # pull per domain at a time.
        self._pull_claims: Dict[tuple, tuple] = {}
        self._pending_leases: deque = deque()  # (req, pg_meta, strategy, fut)
        self._registration_waiters: Dict[WorkerID, asyncio.Future] = {}
        # Workers killed after a registration timeout whose in-flight
        # register RPC may still arrive; insertion-ordered for pruning.
        self._doomed_workers: Dict[WorkerID, None] = {}
        self._subs: Dict[str, List[rpc.Connection]] = defaultdict(list)
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._reaper_task = None
        self.job_counter = 0
        self._spread_rr = 0
        # Workers must be able to import ray_tpu no matter the driver's cwd
        # (the driver may have put the package on sys.path manually).
        self._spawn_env = spawn_env_with_pkg_root()
        self.task_events: deque = deque(maxlen=100_000)
        # Finished tracing spans reported by workers/drivers
        # (ray_tpu/util/tracing.py), plus the cluster-wide count of
        # spans processes dropped at buffer capacity before flushing.
        self.spans: deque = deque(maxlen=100_000)
        self.spans_dropped_total = 0
        self._shutting_down = False
        # Observability: per-process metric snapshots (worker_id → snap)
        # merged on demand; dashboard server started in start().
        self.metrics_snapshots: Dict[str, dict] = {}
        self.dashboard = None
        # Job submission (reference: dashboard/modules/job JobManager):
        # job_id → {entrypoint, status, proc, log_path, ...}
        self.jobs: Dict[str, dict] = {}
        # OOM kill ledger (reference: raylet worker-killing events in the
        # state API): newest-first visibility for debugging memory kills.
        self.oom_kills: deque = deque(maxlen=1000)
        self._last_oom_kill: Dict[str, float] = {}  # node hex -> ts
        self._memmon_task = None
        # Mutation WAL: actor/PG/KV/job changes are appended (and
        # flushed) before the RPC reply, so a kill -9 between periodic
        # snapshots loses nothing a client saw acknowledged.
        self.wal = HeadWAL(session_dir)
        # One persist at a time: two concurrent roll+write+drop cycles
        # could delete a WAL generation covered only by the NEWER
        # snapshot and then overwrite it with the older one.
        self._persist_lock = asyncio.Lock()

    # ------------------------------------------------------------- lifecycle
    async def start(self):
        self._loop = asyncio.get_running_loop()
        os.makedirs(os.path.join(self.session_dir, "workers"), exist_ok=True)
        os.makedirs(os.path.join(self.session_dir, "logs"), exist_ok=True)
        self._sweep_dead_sessions()
        # Head restart on an existing session dir adopts the durable
        # control-plane state (GCS-restart analogue).
        state_path = os.path.join(self.session_dir, "head_state.pkl")
        self._restored_tcp_port = None
        restored = False
        if os.path.exists(state_path):
            try:
                self.restore_state(state_path)
                restored = True
            except Exception:  # noqa: BLE001 - a bad snapshot can't brick
                pass
        else:
            # Killed before the first snapshot: the WAL alone is the
            # durable state, and the predecessor's session.json is the
            # only record of the TCP port remote peers keep redialing.
            try:
                if self._replay_wal(0):
                    restored = True
                    with open(os.path.join(self.session_dir,
                                           "session.json")) as f:
                        self._restored_tcp_port = json.load(
                            f)["tcp_address"][1]
            except Exception:  # noqa: BLE001
                pass
        self.wal.open_active()
        # A SIGKILL'd predecessor leaves its socket file behind; the new
        # head must re-bind the same path (workers reconnect to it). But
        # NEVER steal the socket of a LIVE head — probe it first, or a
        # double-start would silently split-brain the session.
        if os.path.exists(self.sock_path):
            probe = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            probe.settimeout(1.0)
            try:
                probe.connect(self.sock_path)
                probe.close()
                raise RuntimeError(
                    f"a head is already serving {self.sock_path}; refusing "
                    "to start a second one on the same session")
            except (ConnectionRefusedError, FileNotFoundError,
                    socket.timeout, OSError):
                probe.close()
            try:
                os.unlink(self.sock_path)
            except OSError:
                pass
        self._server = rpc.RpcServer(self._handle, path=self.sock_path)
        await self._server.start()
        # TCP listener for remote node daemons / workers / drivers
        # (reference: GCS listens on a TCP port for raylet registration).
        # On restart, reclaim the predecessor's port so remote peers'
        # reconnect loops find us at the address they already know.
        try:
            self._tcp_server = rpc.RpcServer(
                self._handle, host="0.0.0.0",
                port=self._restored_tcp_port or 0)
            await self._tcp_server.start()
        except OSError:
            self._tcp_server = rpc.RpcServer(self._handle, host="0.0.0.0")
            await self._tcp_server.start()
        if restored:
            rpc.spawn(self._reconcile_after_restart(), self._loop)
        self._reaper_task = self._loop.create_task(self._reap_loop())
        if self.config.memory_monitor_refresh_ms > 0:
            self._memmon_task = self._loop.create_task(
                self._memory_monitor_loop())
        if getattr(self.config, "dashboard_port", 0) >= 0:
            from .dashboard import DashboardServer

            self.dashboard = DashboardServer(
                self.state_listing, self.metrics_text, self.chrome_trace,
                log_fn=lambda q: self._rpc_worker_log(q, []),
                node_fn=lambda q: self._rpc_node_stats(q, []),
                jobs_fn=lambda: self._rpc_list_jobs({}, []),
                job_logs_fn=lambda q: self._rpc_job_logs(q, []),
                port=getattr(self.config, "dashboard_port", 0))
            await self.dashboard.start()
        # Discovery file for the CLI (`python -m ray_tpu status`).
        with open(os.path.join(self.session_dir, "session.json"), "w") as f:
            json.dump({
                "head_sock": self.sock_path,
                "tcp_address": list(self.tcp_address),
                "dashboard_url": self.dashboard.url if self.dashboard
                else None,
                "pid": os.getpid(),
                "started_at": time.time(),
            }, f)
        return self

    @property
    def tcp_address(self) -> Tuple[str, int]:
        return ("127.0.0.1", self._tcp_server._port)

    async def stop(self):
        self._shutting_down = True
        try:
            await self.persist_state(offload=False)
        except Exception:  # noqa: BLE001
            pass
        if self.dashboard is not None:
            await self.dashboard.stop()
        if self._reaper_task:
            self._reaper_task.cancel()
        if self._memmon_task:
            self._memmon_task.cancel()
        for w in list(self.workers.values()):
            if w.proc is not None:
                try:
                    w.proc.terminate()
                except Exception:
                    pass
            elif w.conn is not None:
                try:
                    w.conn.push("shutdown", {})
                except Exception:
                    pass
        # Give children a moment, then hard-kill.
        deadline = time.time() + 2.0
        for w in list(self.workers.values()):
            if w.proc is None:
                continue
            try:
                w.proc.wait(timeout=max(0.05, deadline - time.time()))
            except Exception:
                try:
                    w.proc.kill()
                except Exception:
                    pass
        if self._server:
            await self._server.stop()
        if self._tcp_server:
            await self._tcp_server.stop()
        # Last act of the session on this host: sweep the session's shm
        # domain. Segment names are session-scoped (session_shm_domain),
        # so only THIS session's leftovers — e.g. from SIGKILLed chaos
        # workers, which never ran unlink — can match. Live mmaps held
        # elsewhere stay valid (POSIX unlink semantics).
        from .object_store import sweep_domain_segments
        from .utils import session_shm_domain

        sweep_domain_segments(session_shm_domain(self.session_dir))
        self.wal.close()

    def _sweep_dead_sessions(self):
        """Reclaim shm segments of SESSIONS THAT DIED WITHOUT CLEANUP
        (SIGKILLed heads skip the clean-stop sweep). Session domains are
        derivable from the discovery-root session dirs, and a recorded
        head pid that no longer runs proves the session is over. Our own
        session dir is skipped — a crash-RESTARTED head adopts its live
        segments (failover), it doesn't reclaim them."""
        import glob as _glob

        from .object_store import sweep_domain_segments
        from .utils import process_exited, session_shm_domain

        root = os.path.join(os.environ.get("TMPDIR", "/tmp"), "ray_tpu")
        own = os.path.abspath(self.session_dir)
        for path in _glob.glob(os.path.join(root, "*", "session.json")):
            sdir = os.path.dirname(path)
            if os.path.abspath(sdir) == own:
                continue
            try:
                with open(path) as f:
                    pid = json.load(f)["pid"]
            except (OSError, KeyError, ValueError, json.JSONDecodeError):
                pid = None
            # process_exited (not signal-0): a zombie head — dead but
            # unreaped by its parent — still answers kill(pid, 0), and
            # its session must be swept like any other dead one.
            if pid is not None and not process_exited(pid):
                continue
            try:
                sweep_domain_segments(session_shm_domain(sdir))
            except Exception:  # noqa: BLE001 - hygiene only
                pass

    # --------------------------------------------------- memory monitor
    async def _memory_monitor_loop(self):
        """Sample the HEAD host's memory and run the kill policy on
        breach (node daemons sample their own hosts and report via
        ``memory_pressure``). Reference: ``memory_monitor.h:52`` —
        monitor fires a callback per interval; the raylet kills via a
        WorkerKillingPolicy."""
        from .memory_monitor import kill_threshold_bytes, sample_memory

        period = self.config.memory_monitor_refresh_ms / 1000.0
        while True:
            await asyncio.sleep(period)
            try:
                snap = await self._loop.run_in_executor(None, sample_memory)
                thr = kill_threshold_bytes(
                    snap, self.config.memory_usage_threshold,
                    self.config.memory_monitor_min_free_bytes)
                if snap.used_bytes > thr:
                    await self._handle_memory_pressure(
                        self.local_node.node_id, snap.used_bytes,
                        snap.total_bytes, thr)
            except Exception:  # noqa: BLE001 - keep the monitor alive
                pass

    def _select_oom_victim(self, node_hex: str):
        """Retriable-newest-first policy (reference:
        ``worker_killing_policy.h:1``): prefer the NEWEST leased task
        worker — its task loses the least progress and retries via the
        normal ConnectionLost path (lineage recovery rebuilds its lost
        objects) — then the newest actor worker that still has restart
        budget. Leased workers are presumed retriable (leases are
        task-agnostic here; a max_retries=0 task on a killed lease
        surfaces WorkerCrashedError to its caller, the reference's
        OutOfMemoryError analog). Actors without restart budget and
        idle pool workers are never killed — better to let the kernel
        OOM killer make that call than to silently destroy
        unrestartable state."""
        cands = [w for w in self.workers.values() if w.node == node_hex]
        leased = [w for w in cands if w.assignment == "lease"]
        if leased:
            return (max(leased,
                        key=lambda w: w.leased_at or w.started_at),
                    "leased task")
        restartable = []
        for w in cands:
            if isinstance(w.assignment, ActorID):
                a = self.actors.get(w.assignment)
                if a and a.state != "DEAD" and \
                        a.restarts_used < a.max_restarts:
                    restartable.append(w)
        if restartable:
            return (max(restartable, key=lambda w: w.started_at),
                    "restartable actor")
        return None, None

    async def _handle_memory_pressure(self, node_hex: str, used: int,
                                      total: int, threshold: int):
        now = time.time()
        # Grace keyed by PHYSICAL host: a co-hosted head + daemons all
        # observe the same breach within one sampling period, and one
        # kill must cover all of them.
        n = self.nodes.get(node_hex)
        grace_key = (n.phys_host if n is not None and n.phys_host
                     else node_hex)
        if now - self._last_oom_kill.get(grace_key, 0.0) < \
                self.config.memory_monitor_kill_grace_s:
            return  # let the previous kill actually release memory
        w, kind = self._select_oom_victim(node_hex)
        if w is None:
            return
        self._last_oom_kill[grace_key] = now
        cause = (f"OOM-killed by the memory monitor: node {node_hex[:12]} "
                 f"used {used / 2**30:.2f}GiB of {total / 2**30:.2f}GiB "
                 f"(threshold {threshold / 2**30:.2f}GiB); policy chose "
                 f"the newest {kind}")
        self.oom_kills.append({
            "time": now, "node_id": node_hex,
            "worker_id": w.worker_id.hex(), "pid": w.pid, "kind": kind,
            "used_bytes": used, "total_bytes": total,
            "threshold_bytes": threshold,
        })
        from .metrics import core_metrics

        core_metrics()["oom_workers_killed"].inc()
        if w.proc is not None:  # head-local: SIGKILL releases NOW
            try:
                w.proc.kill()
            except Exception:  # noqa: BLE001
                pass
        else:
            node = self.nodes.get(node_hex)
            if node is not None and node.conn is not None:
                try:
                    await node.conn.call_simple(
                        "kill_worker",
                        {"worker_id": w.worker_id.hex(), "force": True},
                        timeout=10.0)
                except Exception:  # noqa: BLE001 - daemon reap covers it
                    pass
        await self._on_worker_death(w, cause)

    async def _rpc_memory_pressure(self, payload, bufs):
        """Pushed by a node daemon whose host crossed the threshold."""
        await self._handle_memory_pressure(
            payload["node_id"], int(payload["used_bytes"]),
            int(payload["total_bytes"]), int(payload["threshold_bytes"]))
        return {}

    async def _reap_loop(self):
        period = self.config.health_check_period_s
        last_persist = time.time()
        while True:
            await asyncio.sleep(period)
            self._poll_jobs()
            # Prune REMOVED placement-group tombstones: kept long enough
            # for stale ready() polls to observe the terminal state, not
            # for the head's lifetime (unbounded growth under retry
            # loops). pg_state's unknown-id grace covers pruned ids.
            now = time.time()
            for pid, pg in list(self.pgs.items()):
                if pg.state == "REMOVED" and pg.removed_at is not None \
                        and now - pg.removed_at > 600.0:
                    del self.pgs[pid]
            # Unknown-pg grace entries are normally cleared by the next
            # poll, but a client that polled once and went away would
            # pin its entry forever. Sweep on the tombstone horizon:
            # any re-poll within 600s still gets its fail-fast REMOVED
            # verdict (entries older than the 10s grace answer REMOVED
            # on sight); only a poller with a >600s gap between polls
            # restarts its grace clock — accepted, ready() loops poll
            # sub-second — in exchange for a bounded dict.
            for ugid, t0 in list(self._pg_unknown_since.items()):
                if now - t0 > 600.0:
                    del self._pg_unknown_since[ugid]
            if time.time() - last_persist > 10.0:
                last_persist = time.time()
                try:
                    # Dict walk on the loop (no concurrent mutation);
                    # only pickle+write leave the thread.
                    await self.persist_state()
                except Exception:  # noqa: BLE001 - keep the reaper alive
                    import traceback as _tb

                    print("head: state persist failed:",
                          _tb.format_exc(limit=2), file=sys.stderr)
            for w in list(self.workers.values()):
                if w.proc is not None and w.proc.poll() is not None:
                    await self._on_worker_death(
                        w, f"exit code {w.proc.returncode}")
            # Registered-but-never-created actors (client died between the
            # register and create RPCs) would otherwise pin their name
            # forever; expire them after the lease timeout.
            ttl = self.config.worker_lease_timeout_s
            now = time.time()
            for a in list(self.actors.values()):
                if (a.state == "PENDING" and not a.creation_started
                        and a.registered_at
                        and now - a.registered_at > ttl):
                    self._mark_actor_dead(a, "registration expired: "
                                             "creation never requested")

    # ------------------------------------------------------------- nodes
    async def _on_node_death(self, node: NodeInfo, cause: str):
        """A node daemon's connection dropped: everything on it is gone
        (reference: ``gcs_node_manager.cc`` OnNodeFailure ->
        ``gcs_actor_manager.cc`` OnNodeDead)."""
        if node.state == "DEAD":
            return
        node.state = "DEAD"
        self.nodes.pop(node.node_id, None)
        self.publish("nodes", {"event": "DEAD", "node_id": node.node_id,
                               "cause": cause})
        for w in list(self.workers.values()):
            if w.node == node.node_id:
                await self._on_worker_death(w, f"node died: {cause}",
                                            node_dead=True)
        # Bundles placed on the dead node are lost; try to re-place them
        # (reference: gcs_placement_group_manager reschedules bundles).
        for pg in self.pgs.values():
            if pg.state != "CREATED":
                continue
            for i, nid in enumerate(pg.bundle_nodes):
                if nid == node.node_id:
                    pg.bundle_nodes[i] = None
                    pg.remaining[i] = {}
                    pg.state = "RESCHEDULING"
        self._replace_lost_bundles()
        self._pump_leases()

    def _replace_lost_bundles(self):
        for pg in self.pgs.values():
            if pg.state != "RESCHEDULING":
                continue
            lost = [i for i, nid in enumerate(pg.bundle_nodes) if nid is None]
            ok = True
            survivors = {nid for nid in pg.bundle_nodes if nid}
            for i in lost:
                b = pg.bundles[i]
                cands = [n for n in self._alive_nodes()
                         if self._node_fits(n, b.resources)]
                if pg.strategy == "STRICT_SPREAD":
                    cands = [n for n in cands if n.node_id not in survivors]
                elif pg.strategy == "STRICT_PACK":
                    # Colocation guarantee: lost bundles may only rejoin the
                    # node hosting the surviving bundles (or, if everything
                    # was lost, any single node that fits them all).
                    if survivors:
                        cands = [n for n in cands if n.node_id in survivors]
                    else:
                        need = self._sum_bundles([pg.bundles[j] for j in lost])
                        cands = [n for n in cands
                                 if self._node_fits(n, need)]
                elif pg.strategy == "PACK" and survivors:
                    packed = [n for n in cands if n.node_id in survivors]
                    if packed:
                        cands = packed
                if not cands:
                    ok = False
                    continue
                n = min(cands, key=lambda n: n.utilization())
                self._node_acquire(n, b.resources)
                pg.bundle_nodes[i] = n.node_id
                pg.remaining[i] = dict(b.resources)
                survivors.add(n.node_id)
            if ok and all(nid is not None for nid in pg.bundle_nodes):
                pg.state = "CREATED"

    def _alive_nodes(self) -> List[NodeInfo]:
        return [n for n in self.nodes.values() if n.state == "ALIVE"]

    async def _on_worker_death(self, w: WorkerInfo, cause: str,
                               node_dead: bool = False):
        self.workers.pop(w.worker_id, None)
        self.metrics_snapshots.pop(w.worker_id.hex(), None)
        # A dead worker's object copies are gone: drop its directory
        # entries so pullers stop picking it as a source, and free any
        # pull claims it held so peers take over immediately.
        wkey = repr(w.address)
        for oid in list(self.object_locations):
            locs = self.object_locations[oid]
            if wkey in locs:
                locs.pop(wkey, None)
                if not locs:
                    self.object_locations.pop(oid, None)
        for ckey in list(self._pull_claims):
            if self._pull_claims[ckey][0] == wkey:
                self._pull_claims.pop(ckey, None)
        node = self.nodes.get(w.node)
        if node is not None:
            try:
                node.idle.remove(w)
            except ValueError:
                pass
        self._release_charged(w.charge)
        w.charge = None
        if isinstance(w.assignment, ActorID):
            actor = self.actors.get(w.assignment)
            if actor and actor.state != "DEAD":
                await self._handle_actor_failure(actor, cause)
        self._pump_leases()

    async def _reconcile_after_restart(self):
        """Grace window after a head restart: actors whose workers have
        not reattached by then go through the normal failure path
        (restart from creation spec or DEAD). Reference:
        ``gcs_failover_worker_reconnect_timeout`` (``ray_config_def.h:60``)."""
        grace = float(os.environ.get("RT_HEAD_RECONNECT_GRACE_S", "10"))
        await asyncio.sleep(grace)
        for a in list(self.actors.values()):
            if a.state == "RESTARTING" and a.worker is None:
                await self._handle_actor_failure(
                    a, "worker did not reconnect after head restart")

    async def _handle_actor_failure(self, actor: ActorInfo, cause: str):
        if actor.restarts_used < actor.max_restarts:
            actor.restarts_used += 1
            actor.state = "RESTARTING"
            # Gate against the reattach path: a worker reconnecting
            # mid-restart must not flip this actor ALIVE on the old
            # process while a new instance is being placed (two live
            # instances with divergent state).
            actor.restart_inflight = True
            self.publish(f"actor:{actor.actor_id.hex()}",
                         {"state": "RESTARTING", "cause": cause})
            try:
                await self._restart_actor(actor)
                self.publish(f"actor:{actor.actor_id.hex()}",
                             {"state": "ALIVE",
                              "address": actor.worker.address,
                              "restarts": actor.restarts_used})
            except Exception as e:  # noqa: BLE001
                self._mark_actor_dead(actor, f"restart failed: {e}")
            finally:
                actor.restart_inflight = False
        else:
            self._mark_actor_dead(actor, cause)

    async def _restart_actor(self, actor: ActorInfo):
        req = actor.resources
        strategy = actor.strategy or {}
        pg_meta = None
        if strategy.get("kind") == "PLACEMENT_GROUP":
            # Restart back into the actor's own bundle, not raw node
            # resources (the bundle charge was released on worker death).
            pg_meta = (PlacementGroupID.from_hex(strategy["pg_id"]),
                       strategy.get("bundle_index", -1))
        deadline = time.time() + self.config.worker_lease_timeout_s
        while True:
            found = self._find_grant(req, pg_meta, strategy)
            if found is not None:
                break
            if time.time() > deadline:
                raise RuntimeError("no node can host the restarted actor")
            await asyncio.sleep(0.02)
        node, charge = found
        self._apply_charge(charge)
        try:
            w = await self._place_actor(actor, node)
        except Exception:
            self._release_charged(charge)
            raise
        w.charge = charge

    def _mark_actor_dead(self, actor: ActorInfo, cause: str):
        actor.state = "DEAD"
        actor.death_cause = cause
        actor.worker = None
        if actor.name:
            self.named_actors.pop(actor.name, None)
        self.wal.append({"op": "actor_dead",
                         "actor_id": actor.actor_id.hex(), "cause": cause})
        self.publish(f"actor:{actor.actor_id.hex()}",
                     {"state": "DEAD", "cause": cause})

    # ------------------------------------------------------------- resources
    @staticmethod
    def _node_fits(node: NodeInfo, req: Dict[str, float]) -> bool:
        return all(node.available.get(k, 0.0) + 1e-9 >= v
                   for k, v in req.items())

    @staticmethod
    def _node_acquire(node: NodeInfo, req: Dict[str, float]):
        for k, v in req.items():
            node.available[k] = node.available.get(k, 0.0) - v

    @staticmethod
    def _node_release(node: NodeInfo, req: Dict[str, float]):
        for k, v in req.items():
            node.available[k] = node.available.get(k, 0.0) + v

    def _release_charged(self, charge):
        """Release a node-resource or placement-group bundle charge."""
        if not charge:
            return
        kind = charge[0]
        if kind == "pg":
            _, pg_id, idx, req = charge
            pg = self.pgs.get(pg_id)
            if pg is not None and pg.state in ("CREATED", "RESCHEDULING"):
                rem = pg.remaining[idx]
                for k, v in req.items():
                    rem[k] = rem.get(k, 0.0) + v
        else:  # ("node", node_hex, req)
            _, node_hex, req = charge
            node = self.nodes.get(node_hex)
            if node is not None:
                self._node_release(node, req)

    # ------------------------------------------------------- scheduling policy
    def _pick_node(self, req: Dict[str, float], strategy) -> Optional[NodeInfo]:
        """Choose a node for a lease/actor under the given strategy.

        - DEFAULT: hybrid — prefer the head-local node while its utilization
          stays under ``scheduler_spread_threshold``, then least-utilized
          (reference: ``hybrid_scheduling_policy.h:50``).
        - SPREAD: round-robin over feasible nodes
          (reference: ``spread_scheduling_policy.h``).
        - NODE_AFFINITY: the named node; ``soft`` falls back to hybrid
          (reference: ``node_affinity_scheduling_policy.h``).
        - NODE_LABEL: nodes carrying every hard label; soft-label
          matches preferred among them (reference:
          ``node_label_scheduling_policy.h``).
        """
        kind = (strategy or {}).get("kind", "DEFAULT") if isinstance(
            strategy, dict) else "DEFAULT"
        nodes = self._alive_nodes()
        fitting = [n for n in nodes if self._node_fits(n, req)]
        if not fitting:
            return None
        if kind == "NODE_AFFINITY":
            want = strategy.get("node_id")
            target = self.nodes.get(want)
            if target is not None and target.state == "ALIVE" and \
                    self._node_fits(target, req):
                return target
            if not strategy.get("soft"):
                return None
            kind = "DEFAULT"
        if kind == "NODE_LABEL":
            hard = strategy.get("hard_labels") or {}
            soft = strategy.get("soft_labels") or {}
            feasible = [n for n in fitting
                        if all(n.labels.get(k) == v
                               for k, v in hard.items())]
            if not feasible:
                return None
            preferred = [n for n in feasible
                         if all(n.labels.get(k) == v
                                for k, v in soft.items())]
            pool = preferred or feasible
            return min(pool, key=lambda n: n.utilization())
        if kind == "SPREAD":
            self._spread_rr += 1
            order = sorted(fitting, key=lambda n: n.node_id)
            return order[self._spread_rr % len(order)]
        # DEFAULT hybrid
        threshold = getattr(self.config, "scheduler_spread_threshold", 0.5)
        local = self.nodes.get(self.node_id.hex())
        if (local is not None and local in fitting
                and local.utilization() < threshold):
            return local
        return min(fitting, key=lambda n: n.utilization())

    # ------------------------------------------------------------- workers
    async def _spawn_worker(self, node: NodeInfo) -> WorkerInfo:
        """Spawn with one retry on registration timeout: under heavy
        host load a fresh interpreter can miss the lease window while
        importing — a transient condition that must not fail the user's
        task when a second attempt would land (the stuck first process
        is killed before the retry)."""
        try:
            return await self._spawn_worker_once(node)
        except RuntimeError as e:
            if "failed to register" not in str(e):
                raise
            return await self._spawn_worker_once(node)

    async def _spawn_worker_once(self, node: NodeInfo) -> WorkerInfo:
        worker_id = WorkerID.from_random()
        fut = self._loop.create_future()
        self._registration_waiters[worker_id] = fut
        proc = None
        try:
            if node.is_head:
                log = open(os.path.join(self.session_dir, "logs",
                                        f"worker-{worker_id.hex()[:12]}.log"),
                           "ab")
                try:
                    proc = subprocess.Popen(
                        [sys.executable, "-m",
                         "ray_tpu._private.worker_main",
                         "--session-dir", self.session_dir,
                         "--worker-id", worker_id.hex(),
                         "--node-id", self.node_id.hex(),
                         "--head-sock", self.sock_path],
                        stdout=log, stderr=subprocess.STDOUT,
                        env={**self._spawn_env,
                             reaper.EXPECTED_PPID_ENV: str(os.getpid())},
                        cwd=os.getcwd(),
                    )
                finally:
                    log.close()  # the child holds its own dup of the fd
            else:
                await node.conn.call_simple(
                    "spawn_worker", {"worker_id": worker_id.hex()},
                    timeout=self.config.worker_lease_timeout_s)
            info: WorkerInfo = await asyncio.wait_for(
                fut, timeout=self.config.worker_lease_timeout_s
            )
        except asyncio.TimeoutError:
            # A late register RPC from this (now killed) worker must not
            # be adopted into the idle pool as a corpse.
            self._doomed_workers[worker_id] = None
            while len(self._doomed_workers) > 1024:
                self._doomed_workers.pop(
                    next(iter(self._doomed_workers)), None)
            if proc is not None:
                proc.kill()
                try:
                    # SIGKILL'd child reaps near-instantly; waiting here
                    # avoids accumulating zombies for the head's life.
                    await self._loop.run_in_executor(
                        None, lambda: proc.wait(timeout=5))
                except Exception:  # noqa: BLE001
                    pass
            elif node.conn is not None:
                # Remote spawn: tell the node daemon to reap the stuck
                # process so it doesn't linger unregistered.
                try:
                    node.conn.push("kill_worker",
                                   {"worker_id": worker_id.hex()})
                except Exception:
                    pass
            raise RuntimeError("worker failed to register in time")
        finally:
            self._registration_waiters.pop(worker_id, None)
        info.proc = proc
        return info

    async def _get_worker(self, node: NodeInfo) -> WorkerInfo:
        while node.idle:
            w = node.idle.popleft()
            if w.worker_id in self.workers:
                return w
        return await self._spawn_worker(node)

    def _return_worker(self, w: WorkerInfo):
        if w.worker_id in self.workers:
            w.assignment = None
            node = self.nodes.get(w.node)
            if node is not None and node.state == "ALIVE":
                node.idle.append(w)

    def _kill_worker(self, w: WorkerInfo):
        if w.proc is not None:
            try:
                w.proc.terminate()
            except Exception:
                pass
        else:
            # Remote worker: tell it to exit; its node daemon reaps it.
            try:
                if w.conn is not None:
                    w.conn.push("shutdown", {})
            except Exception:
                pass
        self.workers.pop(w.worker_id, None)
        self.metrics_snapshots.pop(w.worker_id.hex(), None)

    # ------------------------------------------------------------- leases
    def _find_grant(self, req: Dict[str, float], pg_meta, strategy
                    ) -> Optional[Tuple[NodeInfo, Any]]:
        """Find (node, charge) for a request, or None if infeasible now."""
        if pg_meta is not None:
            pg_id, bundle_index = pg_meta
            pg = self.pgs.get(pg_id)
            if pg is None or pg.state != "CREATED":
                return None
            idxs = ([bundle_index] if bundle_index >= 0
                    else range(len(pg.bundles)))
            for i in idxs:
                rem = pg.remaining[i]
                nid = pg.bundle_nodes[i]
                node = self.nodes.get(nid) if nid else None
                if node is None or node.state != "ALIVE":
                    continue
                if all(rem.get(k, 0.0) + 1e-9 >= v for k, v in req.items()):
                    return node, ("pg", pg_id, i, dict(req))
            return None
        node = self._pick_node(req, strategy)
        if node is None:
            return None
        return node, ("node", node.node_id, dict(req))

    def _apply_charge(self, charge):
        if charge[0] == "pg":
            _, pg_id, idx, req = charge
            rem = self.pgs[pg_id].remaining[idx]
            for k, v in req.items():
                rem[k] = rem.get(k, 0.0) - v
        else:
            _, node_hex, req = charge
            self._node_acquire(self.nodes[node_hex], req)

    async def _grant_lease(self, node: NodeInfo, charge) -> dict:
        """Spawn/reuse a worker for an ALREADY-APPLIED charge (callers must
        call ``_apply_charge`` synchronously right after ``_find_grant`` so
        concurrent grants can't double-book the same capacity)."""
        try:
            w = await self._get_worker(node)
        except Exception:
            self._release_charged(charge)
            raise
        w.assignment = "lease"
        w.leased_at = time.time()  # OOM policy ranks by LEASE age —
        # pooled workers' process age says nothing about task progress
        w.charge = charge
        from .metrics import core_metrics

        core_metrics()["leases_granted"].inc()
        return {"worker_id": w.worker_id.hex(), "address": w.address}

    def _pump_leases(self):
        """Grant queued lease requests that now fit."""
        still = deque()
        self._replace_lost_bundles()
        while self._pending_leases:
            req, pg_meta, strategy, fut = self._pending_leases.popleft()
            if fut.done():
                continue
            found = self._find_grant(req, pg_meta, strategy)
            if found is not None:
                node, charge = found
                self._apply_charge(charge)
                rpc.spawn(self._grant_into(node, charge, fut), self._loop)
            else:
                still.append((req, pg_meta, strategy, fut))
        self._pending_leases = still

    async def _grant_into(self, node, charge, fut):
        try:
            res = await self._grant_lease(node, charge)
            if not fut.done():
                fut.set_result(res)
        except Exception as e:  # noqa: BLE001
            if not fut.done():
                fut.set_exception(e)

    # ------------------------------------------------------------- actors
    async def _place_actor(self, actor: ActorInfo, node: NodeInfo):
        w = await self._get_worker(node)
        w.assignment = actor.actor_id
        actor.worker = w
        # Ask the worker to instantiate the actor.
        meta, _ = await w.conn.call("create_actor", actor.creation_spec_meta)
        actor.state = "ALIVE"
        return w

    # ------------------------------------------------------------- pubsub
    def publish(self, topic: str, msg: Any):
        dead = []
        for conn in self._subs.get(topic, []):
            try:
                conn.push("pubsub", {"topic": topic, "msg": msg})
            except Exception:
                dead.append(conn)
        for c in dead:
            try:
                self._subs[topic].remove(c)
            except ValueError:
                pass

    # ------------------------------------------------------------- handler
    async def _handle(self, method: str, payload: Any, bufs: List[bytes],
                      conn: rpc.Connection):
        if method == "subscribe":
            topic = payload["topic"]
            self._subs[topic].append(conn)
            return {}
        if method == "unsubscribe":
            topic = payload["topic"]
            try:
                self._subs[topic].remove(conn)
            except ValueError:
                pass
            return {}
        if method == "publish":
            self.publish(payload["topic"], payload["msg"])
            return {}
        if method == "register_node":
            return await self._register_node(payload, conn)
        fn = getattr(self, "_rpc_" + method, None)
        if fn is None:
            raise rpc.RpcError(f"head: unknown method {method}")
        return await fn(payload, bufs)

    async def _register_node(self, payload, conn: rpc.Connection):
        """A node daemon attached over TCP; its connection IS its liveness
        (reference: raylet registration + health checks,
        ``gcs_node_manager.cc`` HandleRegisterNode)."""
        node = NodeInfo(
            node_id=payload["node_id"],
            hostname=payload.get("hostname") or "?",
            total=dict(payload["resources"]),
            available=dict(payload["resources"]),
            conn=conn,
            labels=dict(payload.get("labels") or {}),
            phys_host=payload.get("host") or payload.get("hostname") or "?",
            agent_url=payload.get("agent_url"),
        )
        self.nodes[node.node_id] = node
        prev_close = conn.on_close

        def _closed():
            if prev_close:
                prev_close()
            rpc.spawn(self._on_node_death(node, "node connection lost"),
                      self._loop)

        conn.on_close = _closed
        self.publish("nodes", {"event": "ALIVE", "node_id": node.node_id})
        self._pump_leases()
        return {"node_id": node.node_id, "config": self.config.to_dict(),
                "head_node_id": self.node_id.hex()}

    async def _rpc_register_worker(self, payload, bufs):
        worker_id = WorkerID.from_hex(payload["worker_id"])
        if worker_id in self._doomed_workers:
            # Registered after its spawn timed out and it was killed:
            # the process is (about to be) dead — adopting it into the
            # idle pool would hand tasks to a corpse.
            del self._doomed_workers[worker_id]
            raise rpc.RpcError(
                f"worker {worker_id.hex()[:12]} was reaped after a "
                f"registration timeout; not adopting")
        address = payload["address"]
        if isinstance(address, list):
            address = tuple(address)
        node_hex = payload.get("node_id") or self.node_id.hex()
        info = WorkerInfo(worker_id=worker_id, address=address,
                          pid=payload["pid"], node=node_hex)
        # The registering connection is the one this call arrived on; we
        # instead open a dedicated control connection to the worker.
        info.conn = await rpc.connect(address, self._handle)
        if worker_id in self._doomed_workers:
            # The spawn timed out (and the process was killed) WHILE we
            # were connecting — same corpse, later window.
            del self._doomed_workers[worker_id]
            try:
                await info.conn.close()
            except Exception:  # noqa: BLE001
                pass
            raise rpc.RpcError(
                f"worker {worker_id.hex()[:12]} was reaped after a "
                f"registration timeout; not adopting")
        self.workers[worker_id] = info
        # Reattach after a head restart: the worker announces the actors
        # it still hosts; RESTARTING records flip back to ALIVE. An
        # actor whose restart placement is already in flight (transient
        # disconnect, not a head crash) must NOT reattach — the restart
        # wins, and the stale instance is told to drop itself.
        reattached = False
        stale = []
        for ahex in payload.get("hosting_actors") or ():
            a = self.actors.get(ActorID.from_hex(ahex))
            can_attach = a is not None and not a.restart_inflight and (
                a.state in ("RESTARTING", "PENDING")
                # Asymmetric disconnect: the head never saw a failure
                # (actor still ALIVE, recorded at this same worker
                # address) — the SAME healthy process re-registering
                # must reattach, not be told it is stale.
                or (a.state == "ALIVE" and a.worker is not None
                    and a.worker.address == address))
            if can_attach:
                a.state = "ALIVE"
                a.worker = info
                a.death_cause = ""
                info.assignment = a.actor_id
                reattached = True
                self.publish(f"actor:{ahex}",
                             {"state": "ALIVE", "address": address})
            else:
                stale.append(ahex)
        fut = self._registration_waiters.get(worker_id)
        if fut is not None and not fut.done():
            fut.set_result(info)
        elif not reattached:
            node = self.nodes.get(node_hex)
            if node is not None:
                node.idle.append(info)  # adopted externally-started worker
        return {"node_id": node_hex,
                "stale_actors": stale,
                "config": self.config.to_dict()}

    async def _rpc_lease_worker(self, payload, bufs):
        req: Dict[str, float] = payload.get("resources") or {}
        strategy = payload.get("strategy") or {}
        pg_meta = None
        if strategy.get("kind") == "PLACEMENT_GROUP":
            pg_meta = (PlacementGroupID.from_hex(strategy["pg_id"]),
                       strategy.get("bundle_index", -1))
        found = self._find_grant(req, pg_meta, strategy)
        if found is not None:
            node, charge = found
            self._apply_charge(charge)
            return await self._grant_lease(node, charge)
        fut = self._loop.create_future()
        self._pending_leases.append((req, pg_meta, strategy, fut))
        timeout = payload.get("timeout", self.config.worker_lease_timeout_s)
        try:
            return await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            raise rpc.RpcError(
                f"lease timed out after {timeout}s: requested {req}, "
                f"available {self._available_summary()}"
            )

    def _available_summary(self) -> Dict[str, float]:
        total: Dict[str, float] = defaultdict(float)
        for n in self._alive_nodes():
            for k, v in n.available.items():
                total[k] += v
        return dict(total)

    async def _rpc_return_lease(self, payload, bufs):
        worker_id = WorkerID.from_hex(payload["worker_id"])
        w = self.workers.get(worker_id)
        if w is not None:
            self._release_charged(w.charge)
            w.charge = None
            if payload.get("kill"):
                self._kill_worker(w)
            else:
                self._return_worker(w)
        self._pump_leases()
        return {}

    def _register_actor(self, payload) -> ActorInfo:
        """Record actor metadata + name (state PENDING). Mirrors the sync
        half of the reference's split (``gcs_actor_manager.cc:311``
        RegisterActor vs :340 CreateActor)."""
        actor_id = ActorID.from_hex(payload["actor_id"])
        existing = self.actors.get(actor_id)
        if existing is not None and existing.state != "DEAD":
            return existing
        # DEAD records (e.g. a failed earlier placement) are rebuilt so a
        # retried create re-registers the name it lost in _mark_actor_dead.
        name = payload.get("name") or ""
        if name and name in self.named_actors:
            raise rpc.RpcError(f"actor name '{name}' already taken")
        actor = ActorInfo(
            actor_id=actor_id, name=name, state="PENDING", worker=None,
            resources=payload.get("resources") or {},
            max_restarts=payload.get("max_restarts", 0),
            creation_spec_meta=payload["spec_meta"],
            strategy=payload.get("strategy") or {},
            registered_at=time.time(),
            detached=bool(name) or payload.get("lifetime") == "detached",
        )
        self.actors[actor_id] = actor
        if name:
            self.named_actors[name] = actor_id
        self.wal.append({"op": "actor", "rec": self._actor_record(actor)})
        return actor

    async def _rpc_register_actor(self, payload, bufs):
        self._register_actor(payload)
        return {}

    async def _rpc_create_actor(self, payload, bufs):
        actor = self._register_actor(payload)
        actor.creation_started = True
        req = payload.get("resources") or {}
        strategy = payload.get("strategy") or {}
        pg_meta = None
        if strategy.get("kind") == "PLACEMENT_GROUP":
            pg_meta = (PlacementGroupID.from_hex(strategy["pg_id"]),
                       strategy.get("bundle_index", -1))
        deadline = time.time() + self.config.worker_lease_timeout_s
        while True:
            found = self._find_grant(req, pg_meta, strategy)
            if found is not None:
                break
            if time.time() > deadline:
                self._mark_actor_dead(actor, "resources unavailable")
                raise rpc.RpcError(
                    f"cannot place actor: requested {req}, available "
                    f"{self._available_summary()}")
            await asyncio.sleep(0.02)
        node, charge = found
        self._apply_charge(charge)
        try:
            w = await self._place_actor(actor, node)
        except Exception as e:  # noqa: BLE001
            self._release_charged(charge)
            self._mark_actor_dead(actor, f"creation failed: {e}")
            raise
        w.charge = charge
        return {"address": w.address, "worker_id": w.worker_id.hex()}

    async def _rpc_get_actor(self, payload, bufs):
        actor_id = ActorID.from_hex(payload["actor_id"])
        a = self.actors.get(actor_id)
        if a is None:
            raise rpc.RpcError(f"no such actor {actor_id}")
        return {"state": a.state,
                "address": a.worker.address if a.worker else None,
                "death_cause": a.death_cause,
                "name": a.name,
                "has_concurrency_groups": bool(
                    (a.creation_spec_meta or {}).get(
                        "concurrency_groups"))}

    async def _rpc_get_named_actor(self, payload, bufs):
        name = payload["name"]
        actor_id = self.named_actors.get(name)
        if actor_id is None:
            raise rpc.RpcError(f"no actor named '{name}'")
        a = self.actors[actor_id]
        return {"actor_id": actor_id.hex(), "state": a.state,
                "address": a.worker.address if a.worker else None}

    async def _rpc_list_actors(self, payload, bufs):
        out = []
        for a in self.actors.values():
            out.append({"actor_id": a.actor_id.hex(), "name": a.name,
                        "state": a.state,
                        "resources": a.resources,
                        "restarts": a.restarts_used,
                        "node_id": a.worker.node if a.worker else None,
                        "death_cause": a.death_cause})
        return out

    async def _rpc_kill_actor(self, payload, bufs):
        actor_id = ActorID.from_hex(payload["actor_id"])
        a = self.actors.get(actor_id)
        if a is None or a.state == "DEAD":
            return {}
        self._kill_actor_now(a, "killed via kill_actor",
                             no_restart=payload.get("no_restart", True))
        return {}

    def _kill_actor_now(self, a: ActorInfo, cause: str,
                        no_restart: bool = True):
        a.max_restarts = 0 if no_restart else a.max_restarts
        w = a.worker
        self._mark_actor_dead(a, cause)
        if w is not None:
            self._release_charged(w.charge)
            w.charge = None
            self._kill_worker(w)
        self._pump_leases()

    async def _rpc_actor_handle_change(self, payload, bufs):
        """Per-process handle counts: +1 when a process gains its first
        handle to an actor, -1 when it loses its last. On zero the actor
        is garbage-collected after a short grace period (an in-flight
        handle transfer sends its inc within the window). Detached/named
        actors opt out (reference: gcs_actor_manager.cc handle-out-of-
        scope death, simplified to head-aggregated counting)."""
        a = self.actors.get(ActorID.from_hex(payload["actor_id"]))
        if a is None or a.state == "DEAD":
            return {}
        a.handle_refs += payload["delta"]
        if a.handle_refs > 0 and a.pending_gc is not None:
            a.pending_gc.cancel()
            a.pending_gc = None
        if (a.handle_refs <= 0 and payload["delta"] < 0
                and not a.detached and a.pending_gc is None):
            a.pending_gc = self._loop.create_task(self._actor_gc_after(a))
        return {}

    async def _actor_gc_after(self, a: ActorInfo):
        await asyncio.sleep(
            getattr(self.config, "actor_gc_grace_s", 1.0))
        a.pending_gc = None
        if a.state != "DEAD" and a.handle_refs <= 0 and not a.detached:
            self._kill_actor_now(a, "all actor handles went out of scope")

    # ------------------------------------------------------------- KV
    async def _rpc_kv_put(self, payload, bufs):
        ns = payload.get("ns", "default")
        overwrite = payload.get("overwrite", True)
        k = payload["key"]
        store = self.kv[ns]
        if not overwrite and k in store:
            return {"added": False}
        store[k] = bufs[0] if bufs else payload.get("value", b"")
        self.wal.append({"op": "kv_put", "ns": ns, "key": k,
                         "value": bytes(store[k])})
        return {"added": True}

    async def _rpc_kv_get(self, payload, bufs):
        ns = payload.get("ns", "default")
        v = self.kv[ns].get(payload["key"])
        if v is None:
            return {"found": False}
        return ({"found": True}, [bytes(v)])

    async def _rpc_kv_del(self, payload, bufs):
        ns = payload.get("ns", "default")
        existed = self.kv[ns].pop(payload["key"], None) is not None
        if existed:
            self.wal.append({"op": "kv_del", "ns": ns,
                             "key": payload["key"]})
        return {"deleted": existed}

    async def _rpc_kv_keys(self, payload, bufs):
        ns = payload.get("ns", "default")
        prefix = payload.get("prefix", "")
        return [k for k in self.kv[ns] if k.startswith(prefix)]

    # ------------------------------------------------------------- PGs
    def _place_bundles(self, bundles: List[Bundle], strategy: str
                       ) -> Optional[List[str]]:
        """Assign each bundle a node per the PG strategy, atomically
        (reference: ``bundle_scheduling_policy.h:82-106``). Returns node ids
        or None if infeasible right now."""
        nodes = self._alive_nodes()
        # Work on a scratch copy of availability so the reservation is
        # all-or-nothing (the head is the single resource owner, so this IS
        # the 2-phase commit: prepare on the copy, commit below).
        scratch = {n.node_id: dict(n.available) for n in nodes}

        def fits(nid, req):
            av = scratch[nid]
            return all(av.get(k, 0.0) + 1e-9 >= v for k, v in req.items())

        def take(nid, req):
            av = scratch[nid]
            for k, v in req.items():
                av[k] = av.get(k, 0.0) - v

        assignment: List[Optional[str]] = [None] * len(bundles)
        if strategy in ("PACK", "STRICT_PACK"):
            # Try to fit everything on one node (least-utilized first so
            # PACK actually packs).
            total = self._sum_bundles(bundles)
            for n in sorted(nodes, key=lambda n: n.utilization()):
                if fits(n.node_id, total):
                    return [n.node_id] * len(bundles)
            if strategy == "STRICT_PACK":
                return None
            # PACK fallback: greedy first-fit across nodes.
            for i, b in enumerate(bundles):
                placed = False
                for n in nodes:
                    if fits(n.node_id, b.resources):
                        take(n.node_id, b.resources)
                        assignment[i] = n.node_id
                        placed = True
                        break
                if not placed:
                    return None
            return assignment
        if strategy in ("SPREAD", "STRICT_SPREAD"):
            order = sorted(nodes, key=lambda n: n.utilization())
            used: Set[str] = set()
            for i, b in enumerate(bundles):
                # distinct nodes first; SPREAD may reuse when exhausted
                cands = [n for n in order if n.node_id not in used
                         and fits(n.node_id, b.resources)]
                if not cands and strategy == "SPREAD":
                    cands = [n for n in order if fits(n.node_id, b.resources)]
                if not cands:
                    return None
                n = cands[0]
                take(n.node_id, b.resources)
                used.add(n.node_id)
                assignment[i] = n.node_id
            return assignment
        raise rpc.RpcError(f"unknown placement strategy {strategy!r}")

    @staticmethod
    def _sum_bundles(bundles: List[Bundle]) -> Dict[str, float]:
        total: Dict[str, float] = defaultdict(float)
        for b in bundles:
            for k, v in b.resources.items():
                total[k] += v
        return dict(total)

    async def _rpc_create_placement_group(self, payload, bufs):
        pg_id = PlacementGroupID.from_hex(payload["pg_id"])
        bundles = [Bundle(i, dict(b)) for i, b in enumerate(payload["bundles"])]
        strategy = payload.get("strategy", "PACK")
        pg = PlacementGroupInfo(pg_id=pg_id, bundles=bundles, strategy=strategy,
                                state="PENDING", name=payload.get("name", ""))
        self.pgs[pg_id] = pg
        self.wal.append({"op": "pg", "rec": self._pg_record(pg)})
        deadline = time.time() + payload.get(
            "timeout", self.config.worker_lease_timeout_s)
        while True:
            if pg.state == "REMOVED":
                # remove_placement_group raced the pending create: the
                # caller's removal wins; committing would leak bundles.
                raise rpc.RpcError("placement group removed during creation")
            assignment = self._place_bundles(bundles, strategy)
            if assignment is not None:
                break
            if time.time() > deadline or self._shutting_down:
                # Keep the entry, terminally REMOVED: async creators'
                # ready() polls must see a fast failure here — the
                # unknown-id → PENDING fallback in pg_state only covers
                # the create-RPC-in-flight race.
                pg.state = "REMOVED"
                pg.removed_at = time.time()
                self.wal.append({"op": "pg_remove", "pg_id": pg_id.hex()})
                raise rpc.RpcError(
                    f"placement group infeasible: strategy {strategy}, "
                    f"bundles {[b.resources for b in bundles]}, "
                    f"nodes {[(n.node_id[:8], n.available) for n in self._alive_nodes()]}")
            await asyncio.sleep(0.02)
        # Commit the reservation.
        for b, nid in zip(bundles, assignment):
            self._node_acquire(self.nodes[nid], b.resources)
        pg.bundle_nodes = list(assignment)
        pg.remaining = [dict(b.resources) for b in bundles]
        pg.state = "CREATED"
        return {"state": "CREATED",
                "bundle_nodes": list(assignment)}

    async def _rpc_remove_placement_group(self, payload, bufs):
        pg_id = PlacementGroupID.from_hex(payload["pg_id"])
        pg = self.pgs.get(pg_id)
        if pg is None or pg.state == "REMOVED":
            return {}
        if pg.state in ("CREATED", "RESCHEDULING"):
            for b, nid in zip(pg.bundles, pg.bundle_nodes):
                node = self.nodes.get(nid) if nid else None
                if node is not None:
                    self._node_release(node, b.resources)
        pg.state = "REMOVED"
        pg.removed_at = time.time()
        self.wal.append({"op": "pg_remove", "pg_id": pg_id.hex()})
        self._pump_leases()
        return {}

    async def _rpc_pg_state(self, payload, bufs):
        pg_id = PlacementGroupID.from_hex(payload["pg_id"])
        pg = self.pgs.get(pg_id)
        if pg is None:
            # Creation is async (the driver fires create_placement_group
            # on a background thread and returns the handle at once): an
            # unknown id is usually a ready() poll winning the race
            # against the create RPC — but only briefly, since create
            # registers the entry as its first act. Answer PENDING
            # within a short grace window; past it the id is genuinely
            # dead (lost create RPC, pruned tombstone, head restart) and
            # must fail fast, not spin out the caller's whole timeout.
            now = time.time()
            first = self._pg_unknown_since.setdefault(pg_id, now)
            if now - first < 10.0:
                return {"state": "PENDING", "bundle_nodes": []}
            self._pg_unknown_since.pop(pg_id, None)
            return {"state": "REMOVED", "bundle_nodes": []}
        self._pg_unknown_since.pop(pg_id, None)
        return {"state": pg.state, "bundle_nodes": pg.bundle_nodes}

    # ------------------------------------------------------------- cluster
    async def _rpc_cluster_resources(self, payload, bufs):
        total: Dict[str, float] = defaultdict(float)
        for n in self._alive_nodes():
            for k, v in n.total.items():
                total[k] += v
        return dict(total)

    async def _rpc_available_resources(self, payload, bufs):
        return self._available_summary()

    async def _rpc_list_nodes(self, payload, bufs):
        return [{"node_id": n.node_id, "hostname": n.hostname,
                 "is_head": n.is_head, "state": n.state,
                 "total": dict(n.total), "available": dict(n.available),
                 "labels": dict(n.labels), "agent_url": n.agent_url}
                for n in self.nodes.values()]

    async def _rpc_node_stats(self, payload, bufs):
        """Per-node stats, proxied through the head (reference: the
        dashboard head aggregating every agent's node_stats). The
        head's own node is served locally; remote nodes answer over
        their daemon RPC connection."""
        node_hex = payload.get("node_id") or self.local_node.node_id
        node = self.nodes.get(node_hex)
        if node is None:
            raise rpc.RpcError(f"no such node {node_hex[:12]}")
        if node.node_id == self.local_node.node_id:
            from .node_agent import collect_node_stats

            pids = {w.worker_id.hex(): w.pid
                    for w in self.workers.values()
                    if w.node == node_hex and w.proc is not None}
            stats = collect_node_stats(pids)
            stats["node_id"] = node_hex
            return stats
        if node.conn is None:
            raise rpc.RpcError(f"node {node_hex[:12]} has no daemon "
                               "connection")
        return await node.conn.call_simple("agent_stats", {},
                                           timeout=15.0)

    async def _rpc_get_head_tcp_address(self, payload, bufs):
        return {"address": list(self.tcp_address)}

    async def _rpc_worker_died(self, payload, bufs):
        """Pushed by a node daemon when one of its workers exits."""
        worker_id = WorkerID.from_hex(payload["worker_id"])
        w = self.workers.get(worker_id)
        if w is not None:
            await self._on_worker_death(
                w, payload.get("cause", "worker process exited"))
        return {}

    async def _rpc_report_task_events(self, payload, bufs):
        self.task_events.extend(payload)
        return {}

    async def _rpc_report_spans(self, payload, bufs):
        # New wire shape: {"spans": [...], "dropped": n}; a bare list is
        # the legacy shape from pre-upgrade workers.
        if isinstance(payload, dict):
            self.spans_dropped_total += int(payload.get("dropped", 0))
            payload = payload.get("spans", [])
        if self.spans.maxlen:
            # The bounded deque evicts silently on extend; those drops
            # must show in the same honest count as process-side ones.
            self.spans_dropped_total += max(
                0, len(self.spans) + len(payload) - self.spans.maxlen)
        self.spans.extend(payload)
        return {}

    async def _rpc_get_spans(self, payload, bufs):
        limit = payload.get("limit", 1000)
        spans = list(self.spans)[-limit:]
        if payload.get("with_meta"):
            return {"spans": spans,
                    "dropped_total": self.spans_dropped_total}
        return spans

    # ------------------------------------------------- object directory
    async def _rpc_object_loc_add(self, payload, bufs):
        addr = payload["address"]
        key = repr(addr)
        locs = self.object_locations.setdefault(payload["object_id"], {})
        locs[key] = {"address": addr,
                     "domain": payload.get("shm_domain"),
                     "frame_sizes": payload.get("frame_sizes")}
        # The copy exists: release any pull claim for this domain so a
        # future re-pull (after this copy is freed) isn't stalled behind
        # a stale claim.
        self._pull_claims.pop(
            (payload["object_id"], payload.get("shm_domain")), None)
        return {}

    async def _rpc_object_loc_get(self, payload, bufs):
        locs = self.object_locations.get(payload["object_id"], {})
        return {"locations": list(locs.values())}

    async def _rpc_object_pull_claim(self, payload, bufs):
        """Grant one puller per (object, shm domain): peers wait for the
        claimer's copy and attach it locally instead of each moving the
        same bytes across domains (reference: pull dedup in
        ``pull_manager.h`` + plasma create/seal)."""
        key = (payload["object_id"], payload.get("shm_domain"))
        now = time.time()
        cur = self._pull_claims.get(key)
        if (cur is None or payload.get("force")
                or cur[0] == repr(payload["address"])
                or now - cur[1] > 300.0):
            self._pull_claims[key] = (repr(payload["address"]), now)
            return {"granted": True}
        return {"granted": False}

    async def _rpc_object_loc_del(self, payload, bufs):
        if payload.get("address") is not None:
            locs = self.object_locations.get(payload["object_id"])
            if locs:
                locs.pop(repr(payload["address"]), None)
                if not locs:
                    self.object_locations.pop(payload["object_id"], None)
        else:
            self.object_locations.pop(payload["object_id"], None)
        return {}

    async def _rpc_get_task_events(self, payload, bufs):
        limit = payload.get("limit", 10000)
        return list(self.task_events)[-limit:]

    async def _rpc_worker_log(self, payload, bufs):
        """Tail a worker's log wherever it lives: head-local logs read
        from the head's session dir, remote ones fetched through the
        owning node daemon (reference: ``dashboard/modules/log/`` routes
        log reads through per-node agents)."""
        from .node import tail_worker_log

        wid = payload.get("worker_id", "")
        req = {"worker_id": wid, "bytes": payload.get("bytes", 65536)}
        if wid:  # empty = list the head's log dir, never a node's
            for info in self.workers.values():
                if info.worker_id.hex().startswith(wid):
                    # Log files are named by the FULL id's first 12 hex
                    # chars; a shorter matched prefix must be resolved.
                    req["worker_id"] = info.worker_id.hex()
                    node = self.nodes.get(info.node)
                    if node is not None and not node.is_head \
                            and node.conn is not None:
                        return await node.conn.call_simple("tail_log", req,
                                                           timeout=15.0)
                    break
        # Head-local worker (alive or dead — its file is in the head's
        # session dir), else a DEAD remote worker: the head no longer
        # tracks it, but the node daemon that ran it still has the file,
        # so ask each live node until one finds it.
        try:
            return tail_worker_log(self.session_dir, req)
        except rpc.RpcError:
            if wid:
                for node in self._alive_nodes():
                    if node.is_head or node.conn is None:
                        continue
                    try:
                        return await node.conn.call_simple("tail_log", req,
                                                           timeout=15.0)
                    except Exception:  # noqa: BLE001 - not on this node
                        continue
            raise

    # -------------------------------------------------------- observability
    async def _rpc_report_metrics(self, payload, bufs):
        """Workers/drivers push their metric registry snapshots.

        A driver in the head's own process shares the head's
        process-global registry, which metrics_text merges directly —
        storing its snapshot too would double-count every counter."""
        if payload.get("pid") == os.getpid():
            return {}
        self.metrics_snapshots[payload["component"]] = payload["snapshot"]
        return {}

    async def _rpc_metrics_text(self, payload, bufs):
        return {"text": self.metrics_text()}

    async def _rpc_metrics_merged(self, payload, bufs):
        """Cluster-merged metric snapshot in wire form — the structured
        twin of metrics_text, for consumers that compute on buckets
        (serve.status()'s latency block)."""
        from . import metrics as m

        snaps = [m.global_registry().snapshot()]
        snaps.extend(self.metrics_snapshots.values())
        return m.merged_to_wire(m.merge_snapshots(snaps))

    async def _rpc_state(self, payload, bufs):
        return self.state_listing(payload.get("kind", "summary"))

    async def _rpc_dashboard_url(self, payload, bufs):
        return {"url": self.dashboard.url if self.dashboard else None}

    async def _rpc_chrome_trace(self, payload, bufs):
        return self.chrome_trace()

    # ------------------------------------------------------------- jobs
    async def _rpc_submit_job(self, payload, bufs):
        """Spawn a driver subprocess for an entrypoint shell command
        (reference: ``dashboard/modules/job/job_manager.py`` submit_job).
        The job attaches to this head via RT_ADDRESS."""
        import uuid as _uuid

        job_id = payload.get("submission_id") or \
            f"raysubmit_{_uuid.uuid4().hex[:12]}"
        if job_id in self.jobs and self.jobs[job_id]["status"] in (
                "PENDING", "RUNNING"):
            raise rpc.RpcError(f"job {job_id!r} already running")
        wire_env = payload.get("runtime_env") or {}
        env = dict(self._spawn_env)
        env["RT_ADDRESS"] = self.sock_path
        env["RT_JOB_ID"] = job_id
        env.update(wire_env.get("env_vars") or {})
        wd_key = wire_env.get("working_dir_key")
        blob = None
        if wd_key:
            blob = self.kv["default"].get(wd_key)
            if blob is None:
                raise rpc.RpcError(
                    f"job working_dir package {wd_key!r} missing")
        log_path = os.path.join(self.session_dir, "logs",
                                f"job-{job_id}.log")

        def _spawn():
            # Blocking work (zip extraction, file opens, fork) stays off
            # the head's event loop.
            cwd = os.getcwd()
            if wd_key:
                from . import runtime_env as renv

                scratch = os.path.join(self.session_dir, "runtime_envs")
                os.makedirs(scratch, exist_ok=True)
                cwd = renv._extract(wd_key, lambda k: blob, scratch)
                env["PYTHONPATH"] = (
                    cwd + os.pathsep + env.get("PYTHONPATH", ""))
            with open(log_path, "ab") as log:
                # Popen inherits the fd; the parent must not keep it.
                return subprocess.Popen(
                    ["/bin/bash", "-c", payload["entrypoint"]],
                    stdout=log, stderr=subprocess.STDOUT, env=env, cwd=cwd)

        proc = await self._loop.run_in_executor(None, _spawn)
        self.jobs[job_id] = {
            "job_id": job_id, "entrypoint": payload["entrypoint"],
            "status": "RUNNING", "proc": proc, "log_path": log_path,
            "started_at": time.time(), "finished_at": None,
            "returncode": None,
        }
        self.wal.append({"op": "job",
                         "rec": self._job_public(self.jobs[job_id])})
        return {"job_id": job_id}

    def _poll_jobs(self):
        for job in self.jobs.values():
            proc = job.get("proc")
            if proc is not None and job["status"] == "RUNNING" and \
                    proc.poll() is not None:
                job["returncode"] = proc.returncode
                job["status"] = ("SUCCEEDED" if proc.returncode == 0
                                 else "FAILED")
                job["finished_at"] = time.time()
                self.wal.append({"op": "job",
                                 "rec": self._job_public(job)})

    def _job_public(self, job: dict) -> dict:
        return {k: v for k, v in job.items() if k != "proc"}

    async def _rpc_job_status(self, payload, bufs):
        self._poll_jobs()
        job = self.jobs.get(payload["job_id"])
        if job is None:
            raise rpc.RpcError(f"no job {payload['job_id']!r}")
        return self._job_public(job)

    async def _rpc_list_jobs(self, payload, bufs):
        self._poll_jobs()
        return [self._job_public(j) for j in self.jobs.values()]

    async def _rpc_stop_job(self, payload, bufs):
        job = self.jobs.get(payload["job_id"])
        if job is None:
            raise rpc.RpcError(f"no job {payload['job_id']!r}")
        proc = job.get("proc")
        if proc is not None and proc.poll() is None:
            proc.terminate()
            job["status"] = "STOPPED"
            job["finished_at"] = time.time()
        return self._job_public(job)

    async def _rpc_job_logs(self, payload, bufs):
        job = self.jobs.get(payload["job_id"])
        if job is None:
            raise rpc.RpcError(f"no job {payload['job_id']!r}")
        try:
            with open(job["log_path"], "rb") as f:
                data = f.read()[-payload.get("tail_bytes", 1 << 20):]
        except OSError:
            data = b""
        return {"logs": data.decode("utf-8", "replace")}

    # -------------------------------------------------------- persistence
    def snapshot_state(self) -> dict:
        """Durable control-plane state (reference: GCS tables behind
        Redis, ``store_client/redis_store_client.h``): KV, named actors +
        actor metadata, placement-group specs, job records, job counter.
        Live worker processes are NOT part of it — like a GCS restart,
        compute is re-created, metadata survives.

        MUST run on the event-loop thread (it iterates live dicts);
        pickling/writing the result may be offloaded."""
        actors = [self._actor_record(a) for a in list(self.actors.values())]
        pgs = [self._pg_record(pg) for pg in list(self.pgs.values())
               if pg.state != "REMOVED"]
        return {
            "kv": {ns: dict(store) for ns, store in list(self.kv.items())},
            "actors": actors,
            "pgs": pgs,
            "jobs": [self._job_public(j) for j in list(self.jobs.values())],
            "job_counter": self.job_counter,
            # A restarted head re-binds the same TCP port so node
            # daemons/workers/drivers reconnect to the address they know.
            "tcp_port": self._tcp_server._port if self._tcp_server
            else None,
            # First WAL generation NOT covered by this snapshot
            # (persist rolls the WAL immediately before capturing).
            "wal_gen": self.wal.gen,
            "timestamp": time.time(),
        }

    @staticmethod
    def _actor_record(a: ActorInfo) -> dict:
        """Durable form of an actor — shared by snapshot and WAL."""
        return {
            "actor_id": a.actor_id.hex(), "name": a.name, "state": a.state,
            "resources": dict(a.resources), "max_restarts": a.max_restarts,
            "spec_meta": a.creation_spec_meta, "strategy": a.strategy,
            "detached": a.detached, "death_cause": a.death_cause,
        }

    @staticmethod
    def _pg_record(pg: PlacementGroupInfo) -> dict:
        return {
            "pg_id": pg.pg_id.hex(), "strategy": pg.strategy,
            "name": pg.name,
            "bundles": [dict(b.resources) for b in pg.bundles],
        }

    def _write_snapshot(self, data: dict) -> str:
        """Blocking half (pickle + atomic write) — executor-safe."""
        import cloudpickle

        path = os.path.join(self.session_dir, "head_state.pkl")
        with open(path + ".tmp", "wb") as f:
            f.write(cloudpickle.dumps(data))
        os.replace(path + ".tmp", path)
        return path

    def _snapshot_for_persist(self) -> dict:
        """Roll the WAL, then capture — both on the event loop, so the
        snapshot covers exactly the generations below the new one."""
        self.wal.roll()
        return self.snapshot_state()

    async def persist_state(self, offload: bool = True) -> str:
        """Serialized snapshot+WAL-cleanup cycle (reaper, RPC, and stop
        all funnel here — see ``_persist_lock``)."""
        async with self._persist_lock:
            data = self._snapshot_for_persist()
            if offload:
                path = await self._loop.run_in_executor(
                    None, self._write_snapshot, data)
            else:
                path = self._write_snapshot(data)
            self.wal.drop_below(data["wal_gen"])
            return path

    def restore_state(self, path: str) -> None:
        """Adopt a previous head's durable state. Actors whose processes
        died with the old head are recorded DEAD (their names stay
        resolvable for diagnosis until re-created); PGs re-enter PENDING
        and re-reserve once nodes attach."""
        import cloudpickle

        with open(path, "rb") as f:
            st = cloudpickle.loads(f.read())
        for ns, store in st["kv"].items():
            self.kv[ns].update(store)
        self._restored_tcp_port = st.get("tcp_port")
        for rec in st["actors"]:
            self._restore_actor_record(rec)
        for rec in st["pgs"]:
            self._restore_pg_record(rec)
        for job in st["jobs"]:
            self._restore_job_record(job)
        self.job_counter = max(self.job_counter, st.get("job_counter", 0))
        self._replay_wal(st.get("wal_gen", 0))

    def _restore_actor_record(self, rec: dict):
        actor_id = ActorID.from_hex(rec["actor_id"])
        was_live = rec["state"] not in ("DEAD",)
        a = ActorInfo(
            actor_id=actor_id, name=rec["name"],
            # Live actors' processes may have survived the head
            # crash (node-daemon workers): hold them RESTARTING for
            # the reconnect grace window; workers that reattach with
            # ``hosting_actors`` flip them back to ALIVE, the rest
            # go through the normal failure/restart path (reference:
            # ``gcs_failover_worker_reconnect_timeout``,
            # ``ray_config_def.h:60``).
            state="RESTARTING" if was_live else "DEAD",
            worker=None, resources=rec["resources"],
            max_restarts=rec["max_restarts"],
            creation_spec_meta=rec["spec_meta"],
            strategy=rec["strategy"], detached=rec["detached"],
            death_cause=(rec["death_cause"] if not was_live
                         else ""),
            registered_at=time.time(),
        )
        self.actors[actor_id] = a
        # Live actors (re)claim their name; dead ones keep it resolvable
        # for diagnosis only if nobody else holds it.
        if a.name and (was_live or a.name not in self.named_actors):
            self.named_actors[a.name] = actor_id

    def _restore_pg_record(self, rec: dict):
        pg_id = PlacementGroupID.from_hex(rec["pg_id"])
        bundles = [Bundle(i, dict(r))
                   for i, r in enumerate(rec["bundles"])]
        self.pgs[pg_id] = PlacementGroupInfo(
            pg_id=pg_id, bundles=bundles, strategy=rec["strategy"],
            state="PENDING", name=rec["name"],
            remaining=[dict(b.resources) for b in bundles],
            bundle_nodes=[None] * len(bundles))

    def _restore_job_record(self, job: dict):
        job = dict(job)
        if job["status"] in ("PENDING", "RUNNING"):
            job["status"] = "FAILED"
            job["finished_at"] = job.get("finished_at") or time.time()
        self.jobs[job["job_id"]] = job

    def _replay_wal(self, first_gen: int) -> int:
        """Apply mutations logged after the snapshot being restored.
        Records replay in append order over the snapshot state; the
        appliers are upserts, so a record both snapshotted AND logged
        (snapshot raced the write) converges to the same state."""
        n = 0
        for rec in self.wal.replay_from(first_gen):
            n += 1
            op = rec.get("op")
            if op == "kv_put":
                self.kv[rec["ns"]][rec["key"]] = rec["value"]
            elif op == "kv_del":
                self.kv[rec["ns"]].pop(rec["key"], None)
            elif op == "actor":
                self._restore_actor_record(rec["rec"])
            elif op == "actor_dead":
                a = self.actors.get(ActorID.from_hex(rec["actor_id"]))
                if a is not None:
                    a.state = "DEAD"
                    a.death_cause = rec.get("cause", "")
                    if a.name:
                        self.named_actors.pop(a.name, None)
            elif op == "pg":
                self._restore_pg_record(rec["rec"])
            elif op == "pg_remove":
                self.pgs.pop(
                    PlacementGroupID.from_hex(rec["pg_id"]), None)
            elif op == "job":
                self._restore_job_record(rec["rec"])
            elif op == "job_counter":
                self.job_counter = max(self.job_counter, rec["value"])
        return n

    async def _rpc_persist_state(self, payload, bufs):
        return {"path": await self.persist_state()}

    async def _rpc_autoscaler_state(self, payload, bufs):
        """Demand signals for the autoscaler loop (reference: v2 instance
        manager reads cluster resource state from the GCS)."""
        unplaced = 0
        shapes: list = []
        for pg in self.pgs.values():
            if pg.state in ("PENDING", "RESCHEDULING"):
                for i, n in enumerate(pg.bundle_nodes):
                    if n is None:
                        unplaced += 1
                        shapes.append(dict(pg.bundles[i].resources))
        for req, pg_meta, _strategy, _fut in list(self._pending_leases):
            # Bundle-targeted leases draw on capacity their PG already
            # accounts for (above if unplaced, reserved if placed) —
            # counting them again would double the demand signal.
            if pg_meta:
                continue
            shapes.append(dict(req))
        return {
            "pending_lease_requests": len(self._pending_leases),
            "unplaced_pg_bundles": unplaced,
            # Resource dict per unmet demand unit, so gang-aware
            # providers (TPU slices) can pick a node type.
            "pending_resource_shapes": shapes,
            "node_utilization": {
                n.node_id: n.utilization()
                for n in self._alive_nodes() if not n.is_head},
        }

    def metrics_text(self) -> str:
        """Cluster-merged prometheus exposition."""
        from . import metrics as m

        core = m.core_metrics()
        core["actors_alive"].set(
            sum(1 for a in self.actors.values() if a.state == "ALIVE"))
        core["workers_alive"].set(len(self.workers))
        snaps = [m.global_registry().snapshot()]
        snaps.extend(self.metrics_snapshots.values())
        return m.render_prometheus(m.merge_snapshots(snaps))

    def state_listing(self, kind: str):
        """State API listings (reference: ``util/state/api.py`` list_*)."""
        if kind == "nodes":
            return [{"node_id": n.node_id, "hostname": n.hostname,
                     "is_head": n.is_head, "state": n.state,
                     "total": dict(n.total),
                     "available": dict(n.available),
                     "agent_url": n.agent_url}
                    for n in self.nodes.values()]
        if kind == "workers":
            return [{"worker_id": w.worker_id.hex(), "pid": w.pid,
                     "node_id": w.node, "assignment": str(w.assignment)}
                    for w in self.workers.values()]
        if kind == "actors":
            return [{"actor_id": a.actor_id.hex(), "name": a.name,
                     "state": a.state, "resources": dict(a.resources),
                     "death_cause": a.death_cause}
                    for a in self.actors.values()]
        if kind == "placement_groups":
            # REMOVED entries are tombstones for stale ready() polls,
            # not live state — they stay out of listings.
            return [{"pg_id": pg.pg_id.hex(), "state": pg.state,
                     "strategy": pg.strategy,
                     "bundles": [dict(b.resources) for b in pg.bundles],
                     "bundle_nodes": list(pg.bundle_nodes)}
                    for pg in self.pgs.values()
                    if pg.state != "REMOVED"]
        if kind == "tasks":
            return list(self.task_events)[-1000:]
        if kind == "oom_kills":
            return list(self.oom_kills)
        if kind == "objects":
            return {"snapshots": {
                k: {n: d for n, d in snap.items()
                    if n.startswith("object_store")}
                for k, snap in self.metrics_snapshots.items()}}
        if kind == "summary":
            return {
                "nodes": len(self.nodes),
                "workers": len(self.workers),
                "actors_alive": sum(1 for a in self.actors.values()
                                    if a.state == "ALIVE"),
                "placement_groups": sum(1 for p in self.pgs.values()
                                        if p.state != "REMOVED"),
                "task_events": len(self.task_events),
                "resources_total": dict(self._cluster_totals()),
                "resources_available": self._available_summary(),
            }
        raise rpc.RpcError(f"unknown state kind {kind!r}")

    def _cluster_totals(self) -> Dict[str, float]:
        total: Dict[str, float] = defaultdict(float)
        for n in self._alive_nodes():
            for k, v in n.total.items():
                total[k] += v
        return total

    def chrome_trace(self) -> list:
        """Task events → chrome://tracing 'X' events (reference:
        ``timeline()`` chrome-trace export in the dashboard)."""
        out = []
        for ev in list(self.task_events):
            out.append({
                "name": ev.get("name") or ev.get("task_id", "")[:8],
                "cat": "task", "ph": "X",
                "ts": int(ev["start"] * 1e6),
                "dur": int((ev["end"] - ev["start"]) * 1e6),
                "pid": "ray_tpu",
                "tid": ev.get("worker_id", "?")[:12],
            })
        # Tracing spans render on per-trace rows so one request's
        # submit → execute chain reads left-to-right on one line.
        for sp in list(self.spans):
            out.append({
                "name": sp["name"], "cat": f"span:{sp['kind']}", "ph": "X",
                "ts": int(sp["start"] * 1e6),
                "dur": max(1, int((sp["end"] - sp["start"]) * 1e6)),
                "pid": "trace",
                "tid": sp["trace_id"][:12],
                "args": {"span_id": sp["span_id"],
                         "parent_id": sp.get("parent_id"),
                         "status": sp.get("status", "ok"),
                         **({"attrs": sp["attrs"]} if sp.get("attrs")
                            else {})},
            })
        return out

    async def _rpc_ping(self, payload, bufs):
        return {"ok": True, "time": time.time()}

    async def _rpc_new_job_id(self, payload, bufs):
        self.job_counter += 1
        # Durable before reply: a restarted head must never hand out a
        # job index that collides with one it already granted.
        self.wal.append({"op": "job_counter", "value": self.job_counter})
        return {"job_index": self.job_counter}

    async def _rpc_prestart_workers(self, payload, bufs):
        n = payload.get("n", 1)
        created = []
        for _ in range(n):
            w = await self._spawn_worker(self.local_node)
            self._return_worker(w)
            created.append(w.worker_id.hex())
        return created
