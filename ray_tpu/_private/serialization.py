"""Serialization: pickle-5 framing with out-of-band zero-copy buffers.

Capability parity with the reference's msgpack + pickle5 scheme
(reference: ``python/ray/_private/serialization.py:210-226``) designed fresh:
a small header frame (metadata) followed by a pickle stream whose large
buffers (numpy / jax host arrays) are carried out-of-band so they can be
written straight into shared memory or sent as scatter/gather iovecs without
copies. jax.Array device buffers are brought to host as numpy via dlpack-free
``np.asarray`` (device->host DMA) and restored as numpy; consumers feeding
TPUs call ``jax.device_put`` themselves under their own sharding.
"""
from __future__ import annotations

import pickle
import struct
from typing import Any, Callable, Dict, List, Tuple

import cloudpickle

_MAGIC = b"RTS1"


class SerializationContext:
    """Per-process serializer with a custom-type registry."""

    def __init__(self):
        self._custom: Dict[type, Tuple[Callable, Callable]] = {}

    def register_serializer(self, typ: type, *, serializer, deserializer):
        self._custom[typ] = (serializer, deserializer)

    def _reduce_custom(self, obj):
        for typ, (ser, de) in self._custom.items():
            if isinstance(obj, typ):
                return de, (ser(obj),)
        return NotImplemented

    def serialize(self, obj: Any) -> List[bytes]:
        """Returns a list of frames: [header, pickle_bytes, buf0, buf1, ...]."""
        buffers: List[pickle.PickleBuffer] = []

        class _Pickler(cloudpickle.CloudPickler):
            def reducer_override(this, o):  # noqa: N805
                r = self._reduce_custom(o)
                if r is not NotImplemented:
                    return r
                return super().reducer_override(o)

        import io

        f = io.BytesIO()
        p = _Pickler(f, protocol=5, buffer_callback=buffers.append)
        p.dump(obj)
        body = f.getvalue()
        raws = [b.raw() for b in buffers]
        header = _MAGIC + struct.pack("<I", len(raws))
        return [header, body] + raws

    def deserialize(self, frames: List[bytes]) -> Any:
        header = bytes(frames[0])
        if header[:4] != _MAGIC:
            raise ValueError("bad serialization magic")
        (nbuf,) = struct.unpack("<I", header[4:8])
        body = frames[1]
        bufs = frames[2 : 2 + nbuf]
        return pickle.loads(body, buffers=bufs)


_ctx = SerializationContext()


def get_context() -> SerializationContext:
    return _ctx


def _native():
    from ray_tpu import _native as native_pkg

    return native_pkg.load()


def pack_frames(frames: List[bytes]) -> bytes:
    """Concatenate frames with a length-prefixed index for single-blob
    storage. Hot path: the native codec does it in one pass/one copy."""
    nat = _native()
    if nat is not None:
        return nat.pack_frames(list(frames))
    head = struct.pack("<I", len(frames)) + b"".join(
        struct.pack("<Q", len(f)) for f in frames
    )
    return head + b"".join(bytes(f) for f in frames)


def pack_frames_into(dst, offset: int, frames: List[bytes]) -> int:
    """Scatter frames straight into a writable buffer (shm segment),
    skipping the intermediate blob. Returns bytes written."""
    nat = _native()
    if nat is not None:
        return nat.write_into(dst, offset, list(frames))
    blob = pack_frames(frames)
    # Publish-after-write (matches the native codec): body first, the
    # 4-byte frame count last, so a reader attached to a shared segment
    # mid-write sees count=0 (not ready) instead of a torn structure.
    # Pure Python cannot issue a release fence, so this ordering is only
    # guaranteed on TSO hardware (x86); the native codec carries the
    # proper release/acquire pair for weakly-ordered CPUs.
    mv = memoryview(blob)
    dst[offset + 4:offset + len(blob)] = mv[4:]
    dst[offset:offset + 4] = mv[:4]
    return len(blob)


def packed_size(frames: List[bytes]) -> int:
    return 4 + 8 * len(frames) + sum(len(f) for f in frames)


def unpack_frames(blob) -> List[memoryview]:
    mv = memoryview(blob)
    nat = _native()
    if nat is not None:
        return [mv[off:off + size]
                for off, size in nat.frame_offsets(mv)]
    # Same error contract as the native frame_offsets: ValueError on a
    # short header/table or a frame overrunning the blob (never
    # struct.error, never silently truncated frames).
    if len(mv) < 4:
        raise ValueError("blob too short for header")
    (n,) = struct.unpack("<I", mv[:4])
    if len(mv) < 4 + 8 * n:
        raise ValueError("blob too short for size table")
    sizes = struct.unpack(f"<{n}Q", mv[4 : 4 + 8 * n])
    out = []
    off = 4 + 8 * n
    for s in sizes:
        if off + s > len(mv):
            raise ValueError("frame overruns blob")
        out.append(mv[off : off + s])
        off += s
    return out
