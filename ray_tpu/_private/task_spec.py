"""Task/actor specifications exchanged between driver, head, and workers.

Capability parity with the reference's ``TaskSpecification``
(reference: ``src/ray/common/task/task_spec.h``) and its scheduling-strategy
oneof (reference: ``src/ray/protobuf/common.proto:111-122``): default,
spread, node-affinity, and placement-group strategies are all expressible.
"""
from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from .ids import ActorID, JobID, NodeID, ObjectID, PlacementGroupID, TaskID


class TaskType(enum.Enum):
    NORMAL = 0
    ACTOR_CREATION = 1
    ACTOR_TASK = 2


@dataclass
class SchedulingStrategy:
    """Default hybrid policy unless a specific target is set."""

    # DEFAULT | SPREAD | NODE_AFFINITY | NODE_LABEL | PLACEMENT_GROUP
    kind: str = "DEFAULT"
    node_id: Optional[NodeID] = None
    soft: bool = False
    placement_group_id: Optional[PlacementGroupID] = None
    bundle_index: int = -1
    capture_child_tasks: bool = False
    # NODE_LABEL: nodes must carry every `hard_labels` pair; among those,
    # `soft_labels` matches are preferred (reference:
    # ``node_label_scheduling_policy.h`` + common.proto NodeLabel oneof).
    hard_labels: Optional[Dict[str, str]] = None
    soft_labels: Optional[Dict[str, str]] = None


@dataclass
class TaskSpec:
    task_id: TaskID
    job_id: JobID
    task_type: TaskType
    # Function payload: ("kv", function_key) once exported, or ("inline", bytes).
    function_ref: Tuple[str, Any]
    # Serialized call args: list of ("inline", frames) | ("ref", ObjectRef meta).
    args: List[Tuple[str, Any]] = field(default_factory=list)
    kwargs_keys: List[str] = field(default_factory=list)  # trailing args are kwargs
    num_returns: int = 1
    resources: Dict[str, float] = field(default_factory=dict)
    max_retries: int = 0
    retry_count: int = 0
    recovery_count: int = 0  # lineage re-executions consumed (owner-side)
    scheduling_strategy: SchedulingStrategy = field(default_factory=SchedulingStrategy)
    # Actor fields
    actor_id: Optional[ActorID] = None
    method_name: str = ""
    seq_no: int = -1  # per-handle ordering for actor tasks
    # Named concurrency group this call runs in (None = method default
    # or the actor's default executor). Reference:
    # ``concurrency_group_manager.h``.
    concurrency_group: Optional[str] = None
    max_restarts: int = 0
    max_concurrency: int = 1
    name: str = ""
    runtime_env: Optional[Dict[str, Any]] = None
    owner_address: Any = None  # socket address of the submitting process
    # Streaming generator support
    is_generator: bool = False
    # Propagated tracing context ({"trace_id","span_id"}) when the
    # submitter has tracing enabled (ray_tpu/util/tracing.py).
    trace_ctx: Optional[Dict[str, str]] = None

    def return_object_ids(self) -> List[ObjectID]:
        # Cached: submission builds the caller-facing refs and reply
        # ingestion walks the same list — one construction, not two.
        ids = getattr(self, "_return_ids", None)
        if ids is None:
            ids = [ObjectID.for_task_return(self.task_id, i)
                   for i in range(self.num_returns)]
            object.__setattr__(self, "_return_ids", ids)
        return ids
