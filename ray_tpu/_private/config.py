"""Central config/flag registry.

Mirrors the *capability* of the reference's single macro table of
``RAY_CONFIG(type, name, default)`` flags (reference:
``src/ray/common/ray_config_def.h:22``): one declarative table, every flag
overridable per-process via ``RT_<NAME>`` environment variables, plus a
cluster-level ``system_config`` dict passed through ``init()``.
"""
from __future__ import annotations

import os
from typing import Any, Callable, Dict


def _parse_bool(v: str) -> bool:
    return v.lower() in ("1", "true", "yes", "on")


_PARSERS: Dict[type, Callable[[str], Any]] = {
    bool: _parse_bool,
    int: int,
    float: float,
    str: str,
}

# name -> (type, default, doc)
_FLAG_TABLE: Dict[str, tuple] = {}


def _flag(name: str, typ: type, default: Any, doc: str = ""):
    _FLAG_TABLE[name] = (typ, default, doc)


# --- Core runtime -----------------------------------------------------------
_flag("max_inline_object_size", int, 100 * 1024,
      "Objects <= this many bytes live in the owner's in-process memory "
      "store; larger objects go to the node shared-memory store.")
_flag("object_store_memory", int, 2 * 1024**3,
      "Bytes of shared memory reserved for the node object store.")
_flag("worker_lease_timeout_s", float, 30.0,
      "How long a task waits for a worker lease before erroring.")
_flag("lease_idle_ttl_s", float, 2.0,
      "Idle leased workers return to the shared pool after this long.")
_flag("dashboard_port", int, 0,
      "Dashboard HTTP port (0 = ephemeral, -1 = disabled).")
_flag("actor_gc_grace_s", float, 1.0,
      "Delay before killing an actor whose handle count hit zero.")
_flag("borrow_release_grace_s", float, 2.0,
      "Delay before a finished submission's arg borrows are released "
      "(covers in-flight borrower ref_incs on other connections).")
_flag("task_max_retries", int, 3, "Default retry count for failed tasks.")
_flag("actor_max_restarts", int, 0, "Default actor restart count.")
_flag("num_workers_soft_limit", int, 0,
      "0 = one worker per logical CPU resource.")
_flag("health_check_period_s", float, 1.0,
      "Node health-check ping period (head -> node daemons).")
_flag("health_check_failure_threshold", int, 5,
      "Consecutive failed pings before a node is marked dead.")
_flag("scheduler_spread_threshold", float, 0.5,
      "Hybrid policy: prefer local node below this utilization, else spread.")
_flag("scheduler_top_k_fraction", float, 0.2,
      "Hybrid policy: random choice among the best k=max(1, frac*n) nodes.")
_flag("pubsub_poll_timeout_s", float, 60.0, "Long-poll timeout for pubsub.")
_flag("metrics_report_period_s", float, 5.0, "Metrics export period.")
_flag("rpc_connect_timeout_s", float, 10.0, "Socket connect timeout.")
_flag("shm_chunk_size", int, 8 * 1024 * 1024,
      "Chunk size for spilled / transferred object streaming.")
_flag("spill_directory", str, "", "Directory for object spilling ('' = tmp).")
_flag("enable_timeline", bool, True, "Record task timeline events.")
_flag("lineage_enabled", bool, True,
      "Keep task specs for lineage reconstruction of lost objects.")
_flag("memory_usage_threshold", float, 0.95,
      "Node memory usage fraction above which workers are OOM-killed.")
_flag("memory_monitor_refresh_ms", int, 250,
      "Memory monitor sampling period (0 disables the monitor).")
_flag("memory_monitor_min_free_bytes", int, -1,
      "Additionally require this much free memory (-1 = fraction only).")
_flag("memory_monitor_kill_grace_s", float, 2.0,
      "Minimum seconds between OOM kills on one node (lets a kill "
      "actually release memory before the next policy decision).")

# --- TPU --------------------------------------------------------------------
_flag("tpu_chips_per_host", int, 4, "Logical TPU chips advertised per host.")
_flag("tpu_topology", str, "", "Override detected TPU topology string.")
_flag("mesh_default_axis", str, "data", "Default mesh axis for collectives.")


class Config:
    """Process-wide config. Values resolve env var > system_config > default."""

    def __init__(self, system_config: Dict[str, Any] | None = None):
        self._overrides: Dict[str, Any] = dict(system_config or {})
        for k in self._overrides:
            if k not in _FLAG_TABLE:
                raise ValueError(f"Unknown system_config flag: {k}")

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        try:
            typ, default, _doc = _FLAG_TABLE[name]
        except KeyError:
            raise AttributeError(f"Unknown config flag: {name}") from None
        env = os.environ.get("RT_" + name.upper())
        if env is not None:
            return _PARSERS[typ](env)
        if name in self._overrides:
            return self._overrides[name]
        return default

    def to_dict(self) -> Dict[str, Any]:
        return {name: getattr(self, name) for name in _FLAG_TABLE}


_global_config = Config()


def global_config() -> Config:
    return _global_config


def set_global_config(cfg: Config) -> None:
    global _global_config
    _global_config = cfg
