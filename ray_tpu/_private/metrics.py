"""Metrics plane: typed instruments + Prometheus text exposition.

Capability parity with the reference's stats pipeline (reference:
``src/ray/stats/metric.h:103`` Count/Gauge/Histogram/Sum over
opencensus → prometheus exporter on each node), re-designed for this
runtime: a process-local registry of lock-protected instruments; every
worker ships snapshots to the head with its task events, and the head
merges them per-component and serves the classic ``/metrics`` text format
(dashboard-lite, ``head.py``).

Conventions follow prometheus: ``_total`` suffix on counters, seconds for
durations, labels as a frozen kv tuple.
"""
from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, Iterable, List, Optional, Tuple

LabelPairs = Tuple[Tuple[str, str], ...]


def _labels(kv: Optional[Dict[str, str]]) -> LabelPairs:
    return tuple(sorted((kv or {}).items()))


class _Instrument:
    kind = ""

    def __init__(self, name: str, description: str = "",
                 registry: "MetricsRegistry" = None):
        self.name = name
        self.description = description
        self._lock = threading.Lock()
        (registry or global_registry()).register(self)


class Counter(_Instrument):
    kind = "counter"

    def __init__(self, name, description="", registry=None):
        super().__init__(name, description, registry)
        self._values: Dict[LabelPairs, float] = {}

    def inc(self, value: float = 1.0, labels: Optional[Dict] = None):
        key = _labels(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value

    def collect(self) -> List[Tuple[LabelPairs, float]]:
        with self._lock:
            return list(self._values.items())


class Gauge(_Instrument):
    kind = "gauge"

    def __init__(self, name, description="", registry=None):
        super().__init__(name, description, registry)
        self._values: Dict[LabelPairs, float] = {}

    def set(self, value: float, labels: Optional[Dict] = None):
        with self._lock:
            self._values[_labels(labels)] = float(value)

    def collect(self):
        with self._lock:
            return list(self._values.items())


class Histogram(_Instrument):
    kind = "histogram"
    DEFAULT_BOUNDS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 10, 60)

    def __init__(self, name, description="", bounds: Iterable[float] = (),
                 registry=None):
        super().__init__(name, description, registry)
        self.bounds = tuple(bounds) or self.DEFAULT_BOUNDS
        # labels -> [bucket counts..., +inf count, sum, n]
        self._values: Dict[LabelPairs, list] = {}

    @contextlib.contextmanager
    def timer(self, labels: Optional[Dict] = None):
        """Context manager observing the block's wall time in seconds."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(time.perf_counter() - t0, labels)

    def observe(self, value: float, labels: Optional[Dict] = None):
        key = _labels(labels)
        with self._lock:
            ent = self._values.get(key)
            if ent is None:
                ent = [0] * (len(self.bounds) + 1) + [0.0, 0]
                self._values[key] = ent
            for i, b in enumerate(self.bounds):
                if value <= b:
                    ent[i] += 1
                    break
            else:
                ent[len(self.bounds)] += 1
            ent[-2] += value
            ent[-1] += 1

    def collect(self):
        with self._lock:
            return [(k, list(v)) for k, v in self._values.items()]


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._instruments: Dict[str, _Instrument] = {}

    def register(self, inst: _Instrument):
        with self._lock:
            existing = self._instruments.get(inst.name)
            if existing is not None and existing.kind != inst.kind:
                raise ValueError(
                    f"metric {inst.name!r} already registered as "
                    f"{existing.kind}")
            self._instruments[inst.name] = inst

    def get(self, name: str) -> Optional[_Instrument]:
        with self._lock:
            return self._instruments.get(name)

    def snapshot(self) -> dict:
        """Wire-format snapshot: shipped from workers to the head."""
        out = {}
        with self._lock:
            instruments = list(self._instruments.values())
        for inst in instruments:
            out[inst.name] = {
                "kind": inst.kind, "description": inst.description,
                "bounds": list(getattr(inst, "bounds", ())),
                "values": [(list(k), v) for k, v in inst.collect()],
            }
        return out


_global: Optional[MetricsRegistry] = None
_global_lock = threading.Lock()


def global_registry() -> MetricsRegistry:
    global _global
    with _global_lock:
        if _global is None:
            _global = MetricsRegistry()
        return _global


def merge_snapshots(snaps: List[dict]) -> dict:
    """Head-side merge of per-process snapshots (sum counters/histograms,
    last-writer-wins gauges)."""
    merged: dict = {}
    for snap in snaps:
        for name, data in snap.items():
            ent = merged.setdefault(name, {
                "kind": data["kind"], "description": data["description"],
                "bounds": data.get("bounds", []), "values": {}})
            for key_list, v in data["values"]:
                key = tuple(tuple(p) for p in key_list)
                if data["kind"] == "counter":
                    ent["values"][key] = ent["values"].get(key, 0.0) + v
                elif data["kind"] == "gauge":
                    ent["values"][key] = v
                else:  # histogram: element-wise sum
                    cur = ent["values"].get(key)
                    ent["values"][key] = (
                        [a + b for a, b in zip(cur, v)] if cur else list(v))
    return merged


def render_prometheus(merged: dict, prefix: str = "ray_tpu") -> str:
    """Merged snapshot → prometheus text exposition format."""
    lines: List[str] = []

    def fmt_labels(key: LabelPairs, extra: str = "") -> str:
        parts = [f'{k}="{v}"' for k, v in key]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""

    for name in sorted(merged):
        ent = merged[name]
        full = f"{prefix}_{name}"
        if ent["description"]:
            lines.append(f"# HELP {full} {ent['description']}")
        lines.append(f"# TYPE {full} {ent['kind']}")
        for key, v in sorted(ent["values"].items()):
            if ent["kind"] in ("counter", "gauge"):
                lines.append(f"{full}{fmt_labels(key)} {v}")
            else:
                bounds = ent["bounds"]
                cum = 0
                for i, b in enumerate(bounds):
                    cum += v[i]
                    # No backslash inside the f-string expression:
                    # pre-3.12 interpreters reject it at compile time.
                    le = f'le="{b}"'
                    lines.append(
                        f"{full}_bucket{fmt_labels(key, le)} {cum}")
                cum += v[len(bounds)]
                le_inf = 'le="+Inf"'
                lines.append(
                    f"{full}_bucket{fmt_labels(key, le_inf)} {cum}")
                lines.append(f"{full}_sum{fmt_labels(key)} {v[-2]}")
                lines.append(f"{full}_count{fmt_labels(key)} {v[-1]}")
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------- core set
# Instantiated lazily so importing this module stays cheap.
_core: dict = {}


def core_metrics() -> dict:
    if not _core:
        _core.update(
            tasks_finished=Counter(
                "tasks_finished_total", "Tasks executed on this worker"),
            task_duration=Histogram(
                "task_duration_seconds", "Task execution wall time"),
            objects_stored=Gauge(
                "object_store_objects", "Objects in the memory store"),
            shm_bytes=Gauge(
                "object_store_shm_bytes", "Bytes in shared-memory store"),
            actors_alive=Gauge("actors_alive", "Live actors (head view)"),
            workers_alive=Gauge("workers_alive", "Live workers (head view)"),
            leases_granted=Counter(
                "leases_granted_total", "Worker leases granted by the head"),
            objects_recovered=Counter(
                "objects_recovered_total",
                "Lost objects rebuilt via lineage re-execution"),
            oom_workers_killed=Counter(
                "oom_workers_killed_total",
                "Workers killed by the memory monitor under host "
                "memory pressure"),
        )
    return _core


# ------------------------------------------------------------- serve set
# Request-lifecycle counters for the serve data plane (shed / expired /
# retried / overload re-picks). Incremented in whichever process observes
# the event — proxy, router (caller), replica, batcher — and merged at
# the head like every other instrument. Label conventions:
# ``deployment`` names the deployment; ``where`` distinguishes the layer
# that dropped the request (router | proxy | replica | batcher).
_serve: dict = {}
_serve_lock = threading.Lock()


def serve_metrics() -> dict:
    with _serve_lock:
        if _serve:
            return _serve
        _serve.update(
            requests_shed=Counter(
                "serve_requests_shed_total",
                "Requests shed under overload (backpressure / 503)"),
            requests_expired=Counter(
                "serve_requests_expired_total",
                "Requests dropped because their deadline passed before "
                "execution"),
            retries=Counter(
                "serve_request_retries_total",
                "Budgeted request retries after replica failure"),
            overload_repicks=Counter(
                "serve_overload_repicks_total",
                "Replica overload pushbacks answered by re-picking "
                "another replica"),
        )
        return _serve


def now() -> float:
    return time.time()
