"""Metrics plane: typed instruments + Prometheus text exposition.

Capability parity with the reference's stats pipeline (reference:
``src/ray/stats/metric.h:103`` Count/Gauge/Histogram/Sum over
opencensus → prometheus exporter on each node), re-designed for this
runtime: a process-local registry of lock-protected instruments; every
worker ships snapshots to the head with its task events, and the head
merges them per-component and serves the classic ``/metrics`` text format
(dashboard-lite, ``head.py``).

Conventions follow prometheus: ``_total`` suffix on counters, seconds for
durations, labels as a frozen kv tuple.
"""
from __future__ import annotations

import contextlib
import os
import threading
import time
import warnings
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

LabelPairs = Tuple[Tuple[str, str], ...]


def _load_shared_name_lint():
    """The metric naming lint is SHARED with the static analyzer:
    ``tools/rtlint/metrics_names.py`` is the single implementation, and
    rtlint rule RT106 applies it to every Counter/Gauge/Histogram
    construction site while :meth:`MetricsRegistry.register` applies it
    at runtime — one function, two call sites, no drift.

    The module is loaded BY FILE PATH (``tools/`` sits next to the
    ``ray_tpu`` package in this repo): importing the ``tools.rtlint``
    package here would execute its ``__init__`` and drag the whole
    analyzer into every ray_tpu process — metrics_names.py is
    deliberately dependency-free so this load stays a single stdlib-only
    exec. The package import is only the fallback (installed layouts
    that relocated the file). If neither works, the lint degrades to a
    no-op with a warning rather than breaking ``ray_tpu`` at import."""
    try:
        import importlib.util

        root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        path = os.path.join(root, "tools", "rtlint", "metrics_names.py")
        spec = importlib.util.spec_from_file_location(
            "_rt_shared_metrics_names", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod.lint_metric_name
    except Exception:  # noqa: BLE001 - fall through to package import
        pass
    try:
        from tools.rtlint.metrics_names import lint_metric_name
        return lint_metric_name
    except Exception:  # noqa: BLE001 - packaged without tools/: degrade
        warnings.warn(
            "tools/rtlint/metrics_names.py not found; metric naming "
            "lint disabled (run rtlint from the source tree instead)")
        return lambda name, kind: []


#: Shared prometheus naming lint (see :func:`_load_shared_name_lint`).
lint_metric_name = _load_shared_name_lint()


def _labels(kv: Optional[Dict[str, str]]) -> LabelPairs:
    return tuple(sorted((kv or {}).items()))


class _Instrument:
    kind = ""

    def __init__(self, name: str, description: str = "",
                 registry: "MetricsRegistry" = None):
        self.name = name
        self.description = description
        self._lock = threading.Lock()
        (registry or global_registry()).register(self)


class Counter(_Instrument):
    kind = "counter"

    def __init__(self, name, description="", registry=None):
        super().__init__(name, description, registry)
        self._values: Dict[LabelPairs, float] = {}

    def inc(self, value: float = 1.0, labels: Optional[Dict] = None):
        key = _labels(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + value

    def collect(self) -> List[Tuple[LabelPairs, float]]:
        with self._lock:
            return list(self._values.items())


class Gauge(_Instrument):
    kind = "gauge"

    def __init__(self, name, description="", registry=None):
        super().__init__(name, description, registry)
        self._values: Dict[LabelPairs, float] = {}

    def set(self, value: float, labels: Optional[Dict] = None):
        with self._lock:
            self._values[_labels(labels)] = float(value)

    def collect(self):
        with self._lock:
            return list(self._values.items())


class Histogram(_Instrument):
    kind = "histogram"
    DEFAULT_BOUNDS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 10, 60)

    def __init__(self, name, description="", bounds: Iterable[float] = (),
                 registry=None):
        super().__init__(name, description, registry)
        self.bounds = tuple(bounds) or self.DEFAULT_BOUNDS
        # labels -> [bucket counts..., +inf count, sum, n]
        self._values: Dict[LabelPairs, list] = {}

    @contextlib.contextmanager
    def timer(self, labels: Optional[Dict] = None):
        """Context manager observing the block's wall time in seconds."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(time.perf_counter() - t0, labels)

    def observe(self, value: float, labels: Optional[Dict] = None):
        key = _labels(labels)
        with self._lock:
            ent = self._values.get(key)
            if ent is None:
                ent = [0] * (len(self.bounds) + 1) + [0.0, 0]
                self._values[key] = ent
            for i, b in enumerate(self.bounds):
                if value <= b:
                    ent[i] += 1
                    break
            else:
                ent[len(self.bounds)] += 1
            ent[-2] += value
            ent[-1] += 1

    def collect(self):
        with self._lock:
            return [(k, list(v)) for k, v in self._values.items()]


class EMA:
    """Exponential moving average with TIME-CONSTANT semantics for
    irregularly-sampled gauge signals (the autoscaler's queue-depth /
    occupancy inputs are too noisy to act on raw — ISSUE 17).

    Each update folds the sample in with ``alpha = 1 - exp(-dt / tau)``
    where ``dt`` is the time since the previous sample: after ``tau``
    seconds of steady samples the average has closed ~63.2% of the gap
    to the new level, after ``3 * tau`` ~95% — independent of the
    sampling rate, unlike a fixed-alpha EMA (the property the unit
    tests pin). The first sample initializes the average outright; a
    non-positive ``dt`` (clock skew, duplicate timestamp) is treated as
    ``alpha = 0`` (hold). Not thread-safe — owned by one control loop.
    """

    def __init__(self, tau_s: float):
        if tau_s <= 0:
            raise ValueError("tau_s must be > 0")
        self.tau_s = float(tau_s)
        self.value: Optional[float] = None
        self.last_t: Optional[float] = None

    def update(self, sample: float, t: float) -> float:
        import math

        if self.value is None:
            self.value = float(sample)
            self.last_t = float(t)
            return self.value
        dt = float(t) - self.last_t
        if dt > 0:
            alpha = 1.0 - math.exp(-dt / self.tau_s)
            self.value += alpha * (float(sample) - self.value)
            self.last_t = float(t)
        return self.value

    def reset(self):
        self.value = None
        self.last_t = None


class MetricsRegistry:
    def __init__(self, strict: Optional[bool] = None):
        self._lock = threading.Lock()
        self._instruments: Dict[str, _Instrument] = {}
        # Naming lint mode: warn by default, raise in strict mode
        # (tests set strict=True or RT_METRICS_STRICT=1 so convention
        # drift fails fast instead of shipping unscrapeable names).
        if strict is None:
            strict = os.environ.get("RT_METRICS_STRICT", "").lower() in (
                "1", "true", "yes", "on")
        self.strict = strict
        self._linted: set = set()

    def register(self, inst: _Instrument):
        problems = lint_metric_name(inst.name, inst.kind)
        if problems and self.strict:
            raise ValueError("; ".join(problems))
        with self._lock:
            existing = self._instruments.get(inst.name)
            if existing is not None and existing.kind != inst.kind:
                raise ValueError(
                    f"metric {inst.name!r} already registered as "
                    f"{existing.kind}")
            first_sight = inst.name not in self._linted
            self._linted.add(inst.name)
            self._instruments[inst.name] = inst
        if problems and first_sight:
            for p in problems:
                warnings.warn(p, stacklevel=3)

    def get(self, name: str) -> Optional[_Instrument]:
        with self._lock:
            return self._instruments.get(name)

    def snapshot(self) -> dict:
        """Wire-format snapshot: shipped from workers to the head."""
        out = {}
        with self._lock:
            instruments = list(self._instruments.values())
        for inst in instruments:
            out[inst.name] = {
                "kind": inst.kind, "description": inst.description,
                "bounds": list(getattr(inst, "bounds", ())),
                "values": [(list(k), v) for k, v in inst.collect()],
            }
        return out


_global: Optional[MetricsRegistry] = None
_global_lock = threading.Lock()


def global_registry() -> MetricsRegistry:
    global _global
    with _global_lock:
        if _global is None:
            _global = MetricsRegistry()
        return _global


def merge_snapshots(snaps: List[dict]) -> dict:
    """Head-side merge of per-process snapshots (sum counters/histograms,
    last-writer-wins gauges).

    Two processes reporting DIFFERENT ``bounds`` for the same histogram
    name (a rolling deploy changed the buckets, or two libraries collide
    on a name) cannot be element-wise summed — the old code's ``zip``
    silently truncated the longer list, corrupting every count. Such
    snapshots now merge into separate sub-series kept under the entry's
    ``bounds_conflict`` list (one per distinct bounds tuple) and render
    with a ``bounds_conflict`` label so no sample is lost or miscounted."""
    merged: dict = {}
    for snap in snaps:
        for name, data in snap.items():
            ent = merged.setdefault(name, {
                "kind": data["kind"], "description": data["description"],
                "bounds": data.get("bounds", []), "values": {}})
            values = ent["values"]
            if data["kind"] == "histogram" and \
                    list(data.get("bounds", [])) != list(ent["bounds"]):
                sub = None
                for c in ent.setdefault("bounds_conflict", []):
                    if c["bounds"] == list(data.get("bounds", [])):
                        sub = c
                        break
                if sub is None:
                    sub = {"bounds": list(data.get("bounds", [])),
                           "values": {}}
                    ent["bounds_conflict"].append(sub)
                values = sub["values"]
            for key_list, v in data["values"]:
                key = tuple(tuple(p) for p in key_list)
                if data["kind"] == "counter":
                    values[key] = values.get(key, 0.0) + v
                elif data["kind"] == "gauge":
                    values[key] = v
                else:  # histogram: element-wise sum (bounds match here)
                    cur = values.get(key)
                    values[key] = (
                        [a + b for a, b in zip(cur, v)] if cur else list(v))
    return merged


def escape_label_value(v) -> str:
    """Prometheus exposition escaping for a label value: backslash,
    double-quote, and line-feed must be escaped or the line is invalid."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(s: str) -> str:
    """HELP text escaping (backslash and line-feed per the spec)."""
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def render_prometheus(merged: dict, prefix: str = "ray_tpu") -> str:
    """Merged snapshot → prometheus text exposition format."""
    lines: List[str] = []

    def fmt_labels(key: LabelPairs, extra: str = "") -> str:
        parts = [f'{k}="{escape_label_value(v)}"' for k, v in key]
        if extra:
            parts.append(extra)
        return "{" + ",".join(parts) + "}" if parts else ""

    def render_hist(full, key, bounds, v, extra_pair=None):
        base_key = key if extra_pair is None else key + (extra_pair,)
        cum = 0
        for i, b in enumerate(bounds):
            cum += v[i]
            # No backslash inside the f-string expression:
            # pre-3.12 interpreters reject it at compile time.
            le = f'le="{b}"'
            lines.append(f"{full}_bucket{fmt_labels(base_key, le)} {cum}")
        cum += v[len(bounds)]
        le_inf = 'le="+Inf"'
        lines.append(f"{full}_bucket{fmt_labels(base_key, le_inf)} {cum}")
        lines.append(f"{full}_sum{fmt_labels(base_key)} {v[-2]}")
        lines.append(f"{full}_count{fmt_labels(base_key)} {v[-1]}")

    for name in sorted(merged):
        ent = merged[name]
        full = f"{prefix}_{name}"
        if ent["description"]:
            lines.append(
                f"# HELP {full} {_escape_help(ent['description'])}")
        lines.append(f"# TYPE {full} {ent['kind']}")
        for key, v in sorted(ent["values"].items()):
            if ent["kind"] in ("counter", "gauge"):
                lines.append(f"{full}{fmt_labels(key)} {v}")
            else:
                render_hist(full, key, ent["bounds"], v)
        # Series whose processes reported different bucket bounds render
        # separately, marked by a bounds_conflict label (summing them
        # would corrupt every count).
        for i, sub in enumerate(ent.get("bounds_conflict", [])):
            pair = ("bounds_conflict", str(i + 1))
            for key, v in sorted(sub["values"].items()):
                render_hist(full, key, sub["bounds"], v, extra_pair=pair)
    return "\n".join(lines) + "\n"


# ---------------------------------------------------------------- core set
# Instantiated lazily so importing this module stays cheap.
_core: dict = {}


def core_metrics() -> dict:
    if not _core:
        _core.update(
            tasks_finished=Counter(
                "tasks_finished_total", "Tasks executed on this worker"),
            task_duration=Histogram(
                "task_duration_seconds", "Task execution wall time"),
            objects_stored=Gauge(
                "object_store_objects", "Objects in the memory store"),
            shm_bytes=Gauge(
                "object_store_shm_bytes", "Bytes in shared-memory store"),
            actors_alive=Gauge("actors_alive", "Live actors (head view)"),
            workers_alive=Gauge("workers_alive", "Live workers (head view)"),
            leases_granted=Counter(
                "leases_granted_total", "Worker leases granted by the head"),
            objects_recovered=Counter(
                "objects_recovered_total",
                "Lost objects rebuilt via lineage re-execution"),
            oom_workers_killed=Counter(
                "oom_workers_killed_total",
                "Workers killed by the memory monitor under host "
                "memory pressure"),
        )
    return _core


# ------------------------------------------------------------- serve set
# Request-lifecycle counters for the serve data plane (shed / expired /
# retried / overload re-picks). Incremented in whichever process observes
# the event — proxy, router (caller), replica, batcher — and merged at
# the head like every other instrument. Label conventions:
# ``deployment`` names the deployment; ``where`` distinguishes the layer
# that dropped the request (router | proxy | replica | batcher).
_serve: dict = {}
_serve_lock = threading.Lock()


#: Sub-second-biased bounds for per-token latency (TPOT): decode chunks
#: land tokens every fraction of a millisecond to tens of ms.
_TOKEN_BOUNDS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                 0.025, 0.05, 0.1, 0.25, 0.5, 1.0)
_BATCH_SIZE_BOUNDS = (1, 2, 4, 8, 16, 32, 64, 128, 256)
_RATIO_BOUNDS = (0.1, 0.25, 0.5, 0.75, 0.9, 1.0)


def serve_metrics() -> dict:
    with _serve_lock:
        if _serve:
            return _serve
        _serve.update(
            requests_shed=Counter(
                "serve_requests_shed_total",
                "Requests shed under overload (backpressure / 503)"),
            events_dropped=Counter(
                "rt_events_dropped_total",
                "Flight-recorder events dropped by per-kind rate caps "
                "(labelled by kind; the ring survived a storm)"),
            requests_expired=Counter(
                "serve_requests_expired_total",
                "Requests dropped because their deadline passed before "
                "execution"),
            retries=Counter(
                "serve_request_retries_total",
                "Budgeted request retries after replica failure"),
            overload_repicks=Counter(
                "serve_overload_repicks_total",
                "Replica overload pushbacks answered by re-picking "
                "another replica"),
            # ---- latency histograms (ISSUE 4 tentpole). Each stage is
            # observed by the layer that owns it: e2e/TTFT/TPOT by the
            # caller-side router (covers handle AND proxy traffic —
            # the proxy calls through a handle), queue waits by the
            # layer doing the queueing, batch shape by the batcher.
            e2e_latency=Histogram(
                "serve_request_e2e_seconds",
                "End-to-end request latency observed at the caller "
                "(submission to result, or to stream exhaustion)"),
            ttft=Histogram(
                "serve_ttft_seconds",
                "Time from stream submission to the first item "
                "(time-to-first-token)"),
            tpot=Histogram(
                "serve_tpot_seconds",
                "Per-token inter-chunk latency of streamed responses "
                "(time-per-output-token)", bounds=_TOKEN_BOUNDS),
            queue_wait=Histogram(
                "serve_queue_wait_seconds",
                "Time a request waited before dispatch, by layer "
                "(where=router: admission wait; where=replica: "
                "submission-to-admission transit)"),
            batch_wait=Histogram(
                "serve_batch_wait_seconds",
                "Time a request waited in the @serve.batch queue before "
                "its batch flushed"),
            batch_size=Histogram(
                "serve_batch_size",
                "Observed (pre-padding) batch sizes at flush",
                bounds=_BATCH_SIZE_BOUNDS),
            batch_fill_ratio=Histogram(
                "serve_batch_fill_ratio",
                "Observed batch size / max_batch_size at flush",
                bounds=_RATIO_BOUNDS),
            # ---- continuous-batching engine (ISSUE 5). Observed on the
            # engine driver thread, once per fused dispatch / admission.
            engine_slot_occupancy=Histogram(
                "serve_engine_slot_occupancy",
                "Active-slot fraction of the continuous-batching decode "
                "engine, observed per fused dispatch",
                bounds=_RATIO_BOUNDS),
            engine_admission_wait=Histogram(
                "serve_engine_admission_wait_seconds",
                "Time a request waited in the engine admission queue "
                "before its slot prefill"),
            engine_dispatches=Counter(
                "serve_engine_dispatches_total",
                "Fused decode dispatches issued by the slot engine"),
            engine_tokens=Counter(
                "serve_engine_tokens_total",
                "Tokens emitted to engine stream lanes"),
            engine_queue_depth=Gauge(
                "serve_engine_queue_depth",
                "Requests accepted by the engine but not yet admitted "
                "to a slot (admission backlog), set once per driver "
                "loop — the offline batch-inference throttle signal"),
            # ---- speculative decoding (ISSUE 9). Observed on the
            # engine driver thread, once per draft->verify round.
            engine_spec_proposed=Counter(
                "serve_engine_spec_proposed_total",
                "Draft tokens proposed to the verify step "
                "(draft_k per active slot per round)"),
            engine_spec_accepted=Counter(
                "serve_engine_spec_accepted_total",
                "Draft tokens the target accepted at verification"),
            engine_spec_accept_len=Histogram(
                "serve_engine_spec_accept_len",
                "Per-slot accepted draft length per verify round "
                "(0..draft_k; committed tokens are this + 1)",
                bounds=(0, 1, 2, 3, 4, 6, 8, 12, 16)),
            # ---- paged KV pool (ISSUE 6). Set/incremented on the
            # engine driver thread as the allocator hands pages out.
            engine_pages_free=Gauge(
                "serve_engine_pages_free",
                "KV pages on the paged engine's free list"),
            engine_kv_bytes_per_token=Gauge(
                "serve_engine_kv_bytes_per_token",
                "HBM bytes one KV-cache position costs under the "
                "engine's configured kv_dtype (int8 pages carry codes "
                "plus amortized per-page scales)"),
            engine_attn_kernel_dispatches=Counter(
                "serve_engine_attn_kernel_dispatches_total",
                "Fused decode dispatches that ran the paged-attention "
                "kernel path (attn_kernel=pallas) instead of the XLA "
                "gather reference"),
            engine_pages_used=Gauge(
                "serve_engine_pages_used",
                "KV pages held by live lanes or the prefix cache"),
            engine_prefix_hits=Counter(
                "serve_engine_prefix_hits_total",
                "Admissions that mapped a cached prompt prefix instead "
                "of prefilling it"),
            engine_cow_copies=Counter(
                "serve_engine_cow_copies_total",
                "Copy-on-write page forks (cached prefix ended "
                "mid-page)"),
            # ---- crash-safe streaming (ISSUE 7). Resumes are observed
            # caller-side (the router re-routes a mid-stream failure
            # with a replay token); driver restarts on the engine's
            # supervisor path; drains by the layer executing them
            # (replica and controller).
            stream_resumes=Counter(
                "serve_stream_resumes_total",
                "Mid-stream failovers: streams re-routed to another "
                "replica with a deterministic replay token after a "
                "replica/driver failure"),
            engine_driver_restarts=Counter(
                "serve_engine_driver_restarts_total",
                "Engine driver threads restarted by the supervisor "
                "after a death or wedge (first occurrence; a second "
                "escalates to replica replacement)"),
            replica_drains=Counter(
                "serve_replica_drains_total",
                "Graceful replica drains (admissions stopped, running "
                "lanes finished or failed retryably) before teardown"),
            drain_duration=Histogram(
                "serve_drain_duration_seconds",
                "Wall time of graceful replica drains"),
            # ---- disaggregated prefill/decode (ISSUE 14). Export is
            # observed by the prefill engine, import latency by the
            # decode engine (wall-clock across processes, like the
            # deadlines it rides with), lease reclaims by the prefill
            # engine's driver-loop sweep, and fallbacks by whichever
            # layer degraded to a local prefill (where=router |
            # engine).
            kv_handoff=Histogram(
                "serve_kv_handoff_seconds",
                "Prefill->decode KV handoff latency: export stamp to "
                "successful import on the decode engine"),
            kv_ship_bytes=Counter(
                "serve_kv_ship_bytes_total",
                "KV bytes exported into handoff ship buffers"),
            handoff_leases_reclaimed=Counter(
                "serve_handoff_leases_reclaimed_total",
                "Handoff leases that expired unclaimed (the decode "
                "side died or fell back); their shipped pages were "
                "swept"),
            prefill_fallbacks=Counter(
                "serve_prefill_fallbacks_total",
                "Disaggregated requests that degraded to a local "
                "prefill (where=router: no prefill replica answered; "
                "where=engine: shipped payload unavailable or failed "
                "byte verification)"),
            # ---- SLO-driven autoscaler (ISSUE 17). Observed by the
            # controller's reconcile loop, once per applied decision /
            # per held tick.
            autoscale_decisions=Counter(
                "serve_autoscale_decisions_total",
                "Autoscaler decisions applied, by direction (up | "
                "down); labels carry deployment and role group"),
            autoscale_held=Counter(
                "serve_autoscale_held_total",
                "Autoscaler ticks that degraded to a conservative hold, "
                "by reason (stale_signal | missing_signal | cold_start "
                "| cooldown | stabilizing | idle_wait)"),
        )
        return _serve


def merged_to_wire(merged: dict) -> dict:
    """Merged snapshot → RPC-safe form (tuple label keys become lists,
    mirroring ``MetricsRegistry.snapshot``'s wire format)."""
    out = {}
    for name, ent in merged.items():
        w = {"kind": ent["kind"], "description": ent["description"],
             "bounds": list(ent["bounds"]),
             "values": [(list(list(p) for p in k), v)
                        for k, v in ent["values"].items()]}
        if ent.get("bounds_conflict"):
            w["bounds_conflict"] = [
                {"bounds": list(sub["bounds"]),
                 "values": [(list(list(p) for p in k), v)
                            for k, v in sub["values"].items()]}
                for sub in ent["bounds_conflict"]]
        out[name] = w
    return out


def quantile_from_buckets(bounds: Sequence[float], counts: Sequence[float],
                          q: float) -> Optional[float]:
    """Quantile estimate from cumulative-free bucket counts (the wire
    layout: one count per bound plus the +Inf overflow). Linear
    interpolation inside the winning bucket, like PromQL's
    ``histogram_quantile``; the +Inf bucket clamps to the last bound."""
    total = sum(counts)
    if total <= 0:
        return None
    target = q * total
    cum = 0.0
    lower = 0.0
    for i, b in enumerate(bounds):
        prev = cum
        cum += counts[i]
        if cum >= target:
            frac = (target - prev) / max(counts[i], 1e-12)
            return lower + (b - lower) * min(max(frac, 0.0), 1.0)
        lower = b
    return float(bounds[-1]) if bounds else None


def histogram_summary(wire: dict, metric: str,
                      label_filter: Optional[Dict[str, str]] = None,
                      qs: Sequence[float] = (0.5, 0.95, 0.99)
                      ) -> Optional[dict]:
    """p50/p95/p99 (+count/sum) for one histogram in a wire-format merged
    snapshot, summing every label set matching ``label_filter``. Returns
    None when the metric is absent or has no observations."""
    ent = wire.get(metric)
    if ent is None or ent.get("kind") != "histogram":
        return None
    want = set((label_filter or {}).items())
    bounds = ent.get("bounds", [])
    agg: Optional[List[float]] = None
    for key_list, v in ent.get("values", []):
        if not want <= {(p[0], p[1]) for p in key_list}:
            continue
        agg = [a + b for a, b in zip(agg, v)] if agg else list(v)
    if agg is None or agg[-1] <= 0:
        return None
    buckets = agg[:len(bounds) + 1]
    out = {f"p{int(q * 100)}_s": quantile_from_buckets(bounds, buckets, q)
           for q in qs}
    out["count"] = agg[-1]
    out["mean_s"] = agg[-2] / agg[-1]
    # Differing-bounds sub-series cannot join one quantile computation;
    # surface what the quantiles above do NOT cover instead of silently
    # dropping those observations from the summary.
    excluded = 0
    for sub in ent.get("bounds_conflict", []):
        for key_list, v in sub.get("values", []):
            if want <= {(p[0], p[1]) for p in key_list}:
                excluded += v[-1]
    if excluded:
        out["excluded_bounds_conflict_count"] = excluded
    return out


def now() -> float:
    return time.time()
