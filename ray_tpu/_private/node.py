"""Node daemon: per-host agent that attaches to the head over TCP.

Capability parity with the reference's raylet node manager
(reference: ``src/ray/raylet/node_manager.cc:1780`` — local worker pool,
resource reporting, worker liveness) re-designed for this runtime's
head-centric resource accounting: the daemon only *spawns and reaps*
worker processes on its host; all scheduling decisions stay at the head.

Workers spawned here listen on TCP (so any node can pull objects from
them) and register directly with the head, tagged with this node's id.
"""
from __future__ import annotations

import asyncio
import os
import socket
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional, Tuple

from . import reaper, rpc
from .ids import NodeID, WorkerID
from .utils import spawn_env_with_pkg_root


def tail_worker_log(session_dir: str, payload: dict) -> dict:
    """Serve the tail of a worker's log file from this host (reference:
    the per-node dashboard log agent, ``dashboard/modules/log/`` — logs
    stay on the node that produced them and are fetched on demand).

    ``payload``: ``worker_id`` (hex, >=12 chars; omit to list log files)
    and ``bytes`` (tail size, default 64KiB).
    """
    logs_dir = os.path.join(session_dir, "logs")
    wid = payload.get("worker_id", "")
    if not wid:
        try:
            return {"files": sorted(os.listdir(logs_dir))}
        except OSError:
            return {"files": []}
    if not all(c in "0123456789abcdefABCDEF" for c in wid):
        # worker ids are hex; anything else is a path-traversal probe
        # (the agent HTTP endpoint feeds user-supplied strings here)
        raise rpc.RpcError(f"invalid worker id {wid[:32]!r}")
    nbytes = int(payload.get("bytes", 65536))
    path = os.path.join(logs_dir, f"worker-{wid[:12]}.log")
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            size = f.tell()
            f.seek(max(0, size - nbytes))
            data = f.read()
    except OSError as e:
        raise rpc.RpcError(f"log unavailable for worker {wid[:12]}: {e}")
    return {"data": data.decode("utf-8", "replace"), "size": size}


class NodeService:
    def __init__(self, head_address: Tuple[str, int], session_dir: str,
                 resources: Dict[str, float],
                 shm_domain: Optional[str] = None,
                 private_domain: bool = False,
                 labels: Optional[Dict[str, str]] = None,
                 node_ip: Optional[str] = None):
        self.head_address = head_address
        self.session_dir = session_dir
        self.resources = dict(resources)
        self.node_id = NodeID.from_random()
        # shm_domain: workers on the same domain exchange large objects via
        # host shared memory; across domains they ship bytes over TCP. Tests
        # set a synthetic domain per node to exercise the cross-node path on
        # one machine.
        from .utils import session_shm_domain

        # Session-scoped default, same recipe as CoreWorker: a daemon
        # without an explicit domain gets one derived from ITS OWN
        # session dir — never the bare hostname, which two sessions on
        # one machine would collide on.
        self.shm_domain = shm_domain or session_shm_domain(session_dir)
        # Only a domain EXPLICITLY declared private may be swept at
        # stop: an inferred guard (hostname comparison) would clobber
        # nodes deliberately sharing a custom domain on one host.
        self.private_domain = private_domain
        self.labels = dict(labels or {})
        # The IP other nodes dial to reach workers on this host. Must be
        # routable cluster-wide on a real multi-host deployment.
        self.node_ip = node_ip or os.environ.get("RT_NODE_IP") or \
            _detect_node_ip(head_address)
        self._conn: Optional[rpc.Connection] = None
        from .config import Config

        self.config = Config()  # replaced by the head's at registration
        self._agent = None  # NodeAgentServer, started in start()
        self._agent_adv_host = self.node_ip
        self._procs: Dict[str, subprocess.Popen] = {}  # worker hex -> proc
        self._reap_task: Optional[asyncio.Task] = None
        self._stopping = False
        self._spawn_env = spawn_env_with_pkg_root(
            {"RT_NODE_IP": self.node_ip})

    async def start(self):
        os.makedirs(os.path.join(self.session_dir, "logs"), exist_ok=True)
        # Per-node dashboard agent (reference ``dashboard/agent.py:28``):
        # node-local stats/logs over HTTP, also proxied by the head.
        # Default bind is LOOPBACK: the agent serves worker logs and
        # process stats unauthenticated, and the head-proxy path
        # (/api/node, node RPC) already gives cluster-wide access — so
        # nothing on the cluster network gets a direct unauthenticated
        # door by default. Set RT_AGENT_BIND to the node IP (or a
        # wildcard) to expose it deliberately; "off" disables.
        bind = os.environ.get("RT_AGENT_BIND", "127.0.0.1")
        if bind and bind.lower() not in ("off", "disabled", "none"):
            from .node_agent import NodeAgentServer

            self._agent = NodeAgentServer(
                stats_fn=self._agent_stats,
                workers_fn=lambda: [{"worker_id": h[:12], "pid": p.pid}
                                    for h, p in self._procs.items()],
                log_fn=lambda q: tail_worker_log(self.session_dir, q),
                host=bind)
            await self._agent.start()
            # Advertise the address the agent actually LISTENS on
            # (wildcard → the routable node IP). A loopback bind
            # advertises NOTHING cluster-wide — a 127.0.0.1 URL would
            # resolve to the VIEWER's machine; the head-proxy path
            # (/api/node over the node RPC) serves those consumers.
            if bind in ("0.0.0.0", "::"):
                self._agent_adv_host = self.node_ip
            elif bind.startswith("127.") or bind in ("localhost", "::1"):
                self._agent_adv_host = None
            else:
                self._agent_adv_host = bind
        self._conn = await rpc.connect(self.head_address, self._handle)
        resp = await self._conn.call_simple("register_node", {
            "node_id": self.node_id.hex(),
            "hostname": self.shm_domain,
            "host": socket.gethostname(),
            "resources": self.resources,
            "labels": self.labels,
            "agent_url": (
                f"http://{self._agent_adv_host}:{self._agent.port}"
                if self._agent and self._agent_adv_host else None),
        }, timeout=30.0)
        self._adopt_head_config(resp)
        self._reap_task = asyncio.get_running_loop().create_task(
            self._reap_loop())
        return self

    def _agent_stats(self) -> dict:
        from .node_agent import collect_node_stats

        stats = collect_node_stats(
            {h: p.pid for h, p in self._procs.items()})
        stats["node_id"] = self.node_id.hex()
        return stats

    async def stop(self):
        self._stopping = True
        if self._agent:
            await self._agent.stop()
        if self._reap_task:
            self._reap_task.cancel()
        for proc in self._procs.values():
            try:
                proc.terminate()
            except Exception:
                pass
        if self._conn:
            await self._conn.close()
        if self.private_domain:
            # Nothing outside this node can own segments of a private
            # domain — sweep what SIGKILLed workers left. Wait for the
            # just-terminated workers first: a worker mid-put could
            # otherwise create a segment after the sweep listed
            # /dev/shm.
            deadline = time.time() + 2.0
            for proc in self._procs.values():
                while proc.poll() is None and time.time() < deadline:
                    await asyncio.sleep(0.05)
                if proc.poll() is None:
                    try:
                        proc.kill()
                    except Exception:  # noqa: BLE001
                        pass
            from .object_store import sweep_domain_segments

            sweep_domain_segments(self.shm_domain)

    async def run_forever(self):
        """Block until the head is gone for good. A dropped head
        connection starts a reconnect loop (a restarted head re-binds
        the same address and adopts us again); the daemon only exits —
        taking its workers with it — once the grace window expires
        (reference: raylet reconnect after GCS failover)."""
        while True:
            closed = asyncio.get_running_loop().create_future()
            prev = self._conn.on_close

            def _on_close(prev=prev, closed=closed):
                if prev:
                    prev()
                if not closed.done():
                    closed.set_result(None)

            self._conn.on_close = _on_close
            await closed
            if self._stopping:
                return
            if not await self._reconnect_head():
                return

    async def _reconnect_head(self) -> bool:
        grace = float(os.environ.get("RT_HEAD_RECONNECT_TIMEOUT_S", "60"))
        deadline = time.time() + grace
        while not self._stopping and time.time() < deadline:
            try:
                conn = await rpc.connect(self.head_address, self._handle)
                resp = await conn.call_simple("register_node", {
                    "node_id": self.node_id.hex(),
                    "hostname": self.shm_domain,
                    "host": socket.gethostname(),
                    "resources": self.resources,
                    "labels": self.labels,
                    "agent_url": (
                        f"http://{self._agent_adv_host}:"
                        f"{self._agent.port}"
                        if self._agent and self._agent_adv_host
                        else None),
                }, timeout=30.0)
                self._adopt_head_config(resp)
                self._conn = conn
                return True
            except Exception:  # noqa: BLE001 - head still down
                await asyncio.sleep(0.5)
        return False

    def _adopt_head_config(self, register_resp: dict):
        """Resolve flags as local env > HEAD's cluster config > default,
        so ``system_config`` passed to init()/Cluster governs remote
        daemons too (reference: raylet receives the GCS's
        system-config blob at registration)."""
        from .config import Config

        try:
            self.config = Config(register_resp.get("config") or {})
        except (ValueError, TypeError):  # version-skewed head: defaults
            self.config = Config()

    # ------------------------------------------------------------- handler
    async def _handle(self, method: str, payload: Any, bufs: List[bytes],
                      conn: rpc.Connection):
        if method == "spawn_worker":
            return await self._spawn_worker(payload["worker_id"])
        if method == "kill_worker":
            return self._kill_worker(payload["worker_id"],
                                     force=payload.get("force", False))
        if method == "ping":
            return {"ok": True, "node_id": self.node_id.hex()}
        if method == "tail_log":
            return tail_worker_log(self.session_dir, payload)
        if method == "agent_stats":
            return self._agent_stats()
        if method == "pubsub":
            return {}
        raise rpc.RpcError(f"node daemon: unknown method {method}")

    async def _spawn_worker(self, worker_hex: str):
        log = open(os.path.join(self.session_dir, "logs",
                                f"worker-{worker_hex[:12]}.log"), "ab")
        host, port = self.head_address
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.worker_main",
             "--session-dir", self.session_dir,
             "--worker-id", worker_hex,
             "--head-tcp", f"{host}:{port}",
             "--node-id", self.node_id.hex(),
             "--shm-domain", self.shm_domain,
             "--tcp"],
            stdout=log, stderr=subprocess.STDOUT,
            env={**self._spawn_env,
                 reaper.EXPECTED_PPID_ENV: str(os.getpid())},
            cwd=os.getcwd(),
        )
        self._procs[worker_hex] = proc
        return {"pid": proc.pid}

    def _kill_worker(self, worker_hex: str, force: bool = False):
        proc = self._procs.pop(worker_hex, None)
        if proc is not None:
            try:
                # force (OOM kills): SIGKILL releases the memory NOW —
                # a SIGTERM handler in a thrashing worker may never run
                proc.kill() if force else proc.terminate()
            except Exception:
                pass
        return {}

    async def _reap_loop(self):
        from .memory_monitor import kill_threshold_bytes, sample_memory

        last_memcheck = 0.0
        while not self._stopping:
            cfg = self.config  # re-read: a reconnect may refresh it
            refresh_s = cfg.memory_monitor_refresh_ms / 1000.0
            await asyncio.sleep(0.2)
            for hex_id, proc in list(self._procs.items()):
                code = proc.poll()
                if code is not None:
                    self._procs.pop(hex_id, None)
                    try:
                        self._conn.push("worker_died", {
                            "worker_id": hex_id,
                            "cause": f"exit code {code}"})
                    except Exception:
                        pass
            # Memory monitor: sample THIS host, report breaches to the
            # head — the kill policy needs assignment info only the
            # head has (reference: MemoryMonitor callback → raylet's
            # WorkerKillingPolicy, ``memory_monitor.h:52``).
            now = time.time()
            if refresh_s > 0 and now - last_memcheck >= refresh_s:
                last_memcheck = now
                try:
                    snap = sample_memory()
                    thr = kill_threshold_bytes(
                        snap, cfg.memory_usage_threshold,
                        cfg.memory_monitor_min_free_bytes)
                    if snap.used_bytes > thr:
                        self._conn.push("memory_pressure", {
                            "node_id": self.node_id.hex(),
                            "used_bytes": snap.used_bytes,
                            "total_bytes": snap.total_bytes,
                            "threshold_bytes": thr,
                        })
                except Exception:  # noqa: BLE001 - monitoring only
                    pass


def _detect_node_ip(head_address: Tuple[str, int]) -> str:
    """The local IP used to reach the head — the address workers advertise
    (reference: ``ray._private.services.get_node_ip_address``)."""
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        s.connect((head_address[0], head_address[1] or 80))
        ip = s.getsockname()[0]
        s.close()
        return ip
    except OSError:
        return "127.0.0.1"
