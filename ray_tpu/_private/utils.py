"""Small shared helpers for process spawning."""
from __future__ import annotations

import os
from typing import Dict, Optional


def spawn_env_with_pkg_root(extra: Optional[Dict[str, str]] = None
                            ) -> Dict[str, str]:
    """Environment for spawned daemon/worker processes: guarantees the
    ray_tpu package root is importable regardless of the parent's cwd."""
    env = dict(os.environ)
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    pp = env.get("PYTHONPATH", "")
    if pkg_root not in pp.split(os.pathsep):
        env["PYTHONPATH"] = pkg_root + (os.pathsep + pp if pp else "")
    if extra:
        env.update(extra)
    return env
