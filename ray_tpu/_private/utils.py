"""Small shared helpers for process spawning."""
from __future__ import annotations

import os
from typing import Dict, Optional


def spawn_env_with_pkg_root(extra: Optional[Dict[str, str]] = None
                            ) -> Dict[str, str]:
    """Environment for spawned daemon/worker processes: guarantees the
    ray_tpu package root is importable regardless of the parent's cwd."""
    env = dict(os.environ)
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    pp = env.get("PYTHONPATH", "")
    if pkg_root not in pp.split(os.pathsep):
        env["PYTHONPATH"] = pkg_root + (os.pathsep + pp if pp else "")
    if extra:
        env.update(extra)
    return env


def session_shm_domain(session_dir: str) -> str:
    """Default shm domain for a session: host-scoped AND session-scoped.

    Every process of one session on one host derives the same value
    (head, head-local workers, UDS-attached drivers), so they exchange
    large objects through shared memory — while two sessions on one
    machine can never collide on segment names, and a head's clean
    shutdown may sweep its own domain's leftovers (SIGKILLed workers
    skip unlink) without touching anyone else's.
    """
    import socket

    return f"{socket.gethostname()}.{os.path.basename(session_dir.rstrip('/'))}"


def process_exited(pid: int) -> bool:
    """True if ``pid`` no longer runs — counting zombies as exited (an
    unreaped child still answers ``kill(pid, 0)``, so signal-0 probing
    lies to anyone who isn't the parent)."""
    try:
        with open(f"/proc/{pid}/stat") as f:
            # field 3 is the state; comm (field 2) may contain spaces
            # and parens, so split on the LAST ')'
            return f.read().rsplit(")", 1)[1].split()[0] == "Z"
    except PermissionError:
        # hidepid mounts deny stat on other users' pids — the process
        # EXISTS (ENOENT is how absence presents), so report alive.
        return False
    except (OSError, IndexError):
        # IndexError: stat read raced final teardown (empty/partial
        # content instead of ESRCH on some kernels) — gone either way.
        if not os.path.isdir("/proc"):
            # No procfs at all (macOS, some containers): fall back to
            # signal-0 probing — blind to zombies, but better than
            # declaring every process exited.
            try:
                os.kill(pid, 0)
                return False
            except ProcessLookupError:
                return True
            except OSError:
                return False  # EPERM: exists, owned by someone else
        return True
