"""Partition-rule based parameter sharding (GSPMD-style).

The reference delegates sharded data parallelism to torch FSDP
(``python/ray/train/train_loop_utils.py:175`` ``parallel_strategy="fsdp"``);
on TPU the same capability is native to XLA: annotate every parameter with a
``NamedSharding`` and the compiler emits the ZeRO-3 gather/reduce-scatter
schedule itself. These helpers map pytree paths → ``PartitionSpec`` via
ordered regex rules (the t5x-style approach, rebuilt fresh).
"""
from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

PartitionRule = Tuple[str, Tuple[Optional[str], ...]]


def path_str(path) -> str:
    """Render a jax tree path as 'a/b/0/c'."""
    parts = []
    for p in path:
        name = getattr(p, "name", None)
        if name is None:
            name = getattr(p, "key", None)
        if name is None:
            name = getattr(p, "idx", None)
        parts.append(str(name))
    return "/".join(parts)


def spec_for(path: str, shape: Sequence[int],
             rules: Sequence[PartitionRule], mesh) -> "Any":
    """First matching rule wins; axes absent from the mesh degrade to None."""
    from jax.sharding import PartitionSpec as P

    names = set(mesh.axis_names)
    for pattern, spec in rules:
        if re.search(pattern, path):
            out = []
            for dim, ax in enumerate(spec):
                if ax is None or dim >= len(shape):
                    out.append(None)
                    continue
                axes = ax if isinstance(ax, tuple) else (ax,)
                axes = tuple(a for a in axes if a in names)
                if not axes:
                    out.append(None)
                    continue
                import math
                size = math.prod(mesh.devices.shape[
                    mesh.axis_names.index(a)] for a in axes)
                if shape[dim] % size != 0:
                    out.append(None)  # indivisible → replicate this dim
                    continue
                out.append(axes if len(axes) > 1 else axes[0])
            while out and out[-1] is None:
                out.pop()
            return P(*out)
    return P()


def tree_shardings(params, mesh, rules: Sequence[PartitionRule]):
    """NamedSharding pytree matching ``params`` under ``rules``."""
    import jax
    from jax.sharding import NamedSharding

    def one(path, leaf):
        p = path_str(path)
        shape = getattr(leaf, "shape", ())
        return NamedSharding(mesh, spec_for(p, shape, rules, mesh))

    return jax.tree_util.tree_map_with_path(one, params)


def replicated(tree, mesh):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    return jax.tree.map(lambda _: NamedSharding(mesh, P()), tree)


def shard_tree(tree, shardings):
    """Device-put every leaf to its sharding (host → mesh scatter)."""
    import jax

    return jax.tree.map(lambda x, s: jax.device_put(x, s), tree, shardings)


# Default rule set for transformer LMs: embeddings/ffn/attention sharded over
# (fsdp, tp); biases/norms replicated. Works for the models/ GPT pytree.
LM_RULES: List[PartitionRule] = [
    (r"embed/kernel", (("fsdp",), "tp")),          # [vocab, d] row-shard
    (r"(wq|wk|wv)/kernel", (("fsdp",), "tp")),     # [d, heads*hd] col-shard
    (r"wo/kernel", ("tp", ("fsdp",))),             # [heads*hd, d]
    (r"router/kernel", (("fsdp",),)),              # [L, d, E] small, L-shard
    (r"w_up/kernel", (("fsdp",), "ep", None, "tp")),   # [L, E, d, f]
    (r"w_down/kernel", (("fsdp",), "ep", "tp")),       # [L, E, f, d]
    (r"(w1|wi|up|gate)/kernel", (("fsdp",), "tp")),
    (r"(w2|wo_ff|down)/kernel", ("tp", ("fsdp",))),
    (r"head/kernel", (("fsdp",), "tp")),
    (r"pos_embed", (None, ("fsdp",))),
    (r"(bias|scale|norm)", (None,)),
    (r".*", ()),                                   # replicate the rest
]

# Pipeline parallel: stacked block layers sharded over pp on the layer
# (leading) dim, everything else replicated (or dp-replicated). Matches
# pipeline_apply's stage ownership.
PP_LM_RULES: List[PartitionRule] = [
    (r"block/", ("pp",)),
    (r".*", ()),
]

# Pure data-parallel: everything replicated.
DP_RULES: List[PartitionRule] = [(r".*", ())]

# Activation/batch sharding rules used by train steps.
BATCH_SPEC = ("dp", "fsdp")  # batch dim sharded over dp×fsdp


def batch_sharding(mesh, extra_seq_axis: Optional[str] = None):
    """NamedSharding for [batch, seq, ...] activations."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    names = set(mesh.axis_names)
    b = tuple(a for a in BATCH_SPEC if a in names)
    s = extra_seq_axis if (extra_seq_axis in names) else None
    spec = P(b if b else None, s)
    return NamedSharding(mesh, spec)
