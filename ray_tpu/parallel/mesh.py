"""Device-mesh construction and multi-host initialization.

TPU-native replacement for the reference's process-group rendezvous
(reference: ``python/ray/train/torch/config.py:65`` builds a torch
``init_process_group``; here the equivalent object is a
``jax.sharding.Mesh`` whose axes name the parallelism dimensions and over
which XLA inserts ICI/DCN collectives).

Axis conventions (any subset may be present, sizes multiply to #devices):

- ``dp``   — data parallel (gradient psum)
- ``fsdp`` — fully-sharded data parallel (params/opt-state sharded, ZeRO-3)
- ``tp``   — tensor parallel (contracting-dim sharding inside matmuls)
- ``sp``   — sequence/context parallel (ring attention / Ulysses)
- ``ep``   — expert parallel (MoE all-to-all)
- ``pp``   — pipeline parallel (collective-permute microbatch schedule)
"""
from __future__ import annotations

import math
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

AXIS_ORDER = ("pp", "dp", "fsdp", "ep", "sp", "tp")
# tp innermost: tensor-parallel collectives are per-matmul (latency bound),
# so they should ride the fastest/nearest ICI links; dp/fsdp gradient
# reductions are per-step and tolerate the outer (slower) axes.


@dataclass
class MeshConfig:
    """Declarative mesh shape; -1 on one axis means "fill remaining"."""

    axes: Dict[str, int] = field(default_factory=dict)
    devices: Optional[Sequence] = None  # default: jax.devices()

    def resolve(self, n_devices: int) -> Dict[str, int]:
        axes = dict(self.axes)
        if not axes:
            return {"dp": n_devices}
        fill = [k for k, v in axes.items() if v == -1]
        if len(fill) > 1:
            raise ValueError(f"only one axis may be -1, got {fill}")
        fixed = math.prod(v for v in axes.values() if v != -1)
        if fill:
            if n_devices % fixed:
                raise ValueError(
                    f"{n_devices} devices not divisible by {fixed}")
            axes[fill[0]] = n_devices // fixed
        elif fixed != n_devices:
            raise ValueError(
                f"mesh axes {axes} use {fixed} devices, have {n_devices}")
        return axes


def create_mesh(axes: Optional[Dict[str, int]] = None, *,
                devices: Optional[Sequence] = None):
    """Build a ``jax.sharding.Mesh`` with named parallelism axes.

    Axes are laid out in ``AXIS_ORDER`` so that ``tp``/``sp`` map to the
    innermost (fastest-wrapping) device dimension — on a TPU slice that is
    the tightest ICI neighborhood, which is where per-matmul collectives
    belong.
    """
    import jax
    import numpy as np

    devs = list(devices if devices is not None else jax.devices())
    shape = MeshConfig(dict(axes or {})).resolve(len(devs))
    names = tuple(sorted(shape, key=lambda a: AXIS_ORDER.index(a)
                         if a in AXIS_ORDER else len(AXIS_ORDER)))
    dims = tuple(shape[n] for n in names)
    arr = np.asarray(devs).reshape(dims)
    return jax.sharding.Mesh(arr, names)


def single_device_mesh(axis: str = "dp"):
    import jax

    return create_mesh({axis: 1}, devices=jax.devices()[:1])


def mesh_shape(mesh) -> Dict[str, int]:
    return dict(zip(mesh.axis_names, mesh.devices.shape))


def initialize_multihost(coordinator_address: Optional[str] = None,
                         num_processes: Optional[int] = None,
                         process_id: Optional[int] = None) -> None:
    """Join this process into a multi-host JAX runtime (DCN control plane).

    TPU-native analogue of the reference's rank-0 rendezvous
    (``train/torch/config.py:112`` ``dist.init_process_group``): after this
    call ``jax.devices()`` spans every host and a single Mesh covers the
    full slice/pod.
    """
    import jax

    kwargs = {}
    if coordinator_address:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)


def local_chip_count() -> int:
    """Best-effort local TPU chip count without initializing the runtime."""
    env = os.environ.get("TPU_VISIBLE_CHIPS") or os.environ.get(
        "TPU_VISIBLE_DEVICES")
    if env:
        return len([c for c in env.split(",") if c.strip()])
    import glob

    return len(glob.glob("/dev/accel*")) or 0
