"""Pipeline parallelism: GPipe microbatch schedule over the ``pp`` mesh axis.

Capability parity with the reference's pipeline-parallel training support
(the reference delegates PP to torch/DeepSpeed through Train's backend,
e.g. ``python/ray/train/torch/config.py``); on TPU the schedule is built
from XLA collectives directly: layers are sharded over ``pp`` (each rank
holds a contiguous stage of the stacked-layer pytree) and activations flow
stage-to-stage with ``lax.ppermute`` inside a ``shard_map`` — the
collective-permute pipeline pattern that maps onto neighboring ICI links.

Schedule (GPipe):
    step t: stage p processes microbatch (t - p); M + P - 1 total steps;
    bubble fraction (P-1)/(M+P-1). Backward is the transposed pipeline
    automatically — the autodiff transpose of ``ppermute`` is the reverse
    ``ppermute``, so one ``jax.grad`` of this forward IS the backward
    schedule.

Composition: ``pp`` composes with ``dp``/``fsdp`` batch axes (batch is
sharded outside, every pp stage sees its dp shard), with ``tp`` inside a
stage (Megatron-style hand collectives in the block body), and with
``sp`` (activations sequence-sharded inside each stage; the block body
runs ring attention over the sp sub-axis — every (pp, sp) device passes
its local sequence chunk to the same-sp-coordinate device of the next
stage, so the ppermute rides neighboring ICI links unchanged).
"""
from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax

from ray_tpu._private.jax_compat import shard_map


def _stage_spec(leaf, pp_axis: str):
    """PartitionSpec sharding only the leading (layer) dim over pp."""
    from jax.sharding import PartitionSpec as P

    return P(pp_axis, *([None] * (leaf.ndim - 1)))


def pipeline_apply(block_fn: Callable, stacked_params: Any, x: jax.Array,
                   *, mesh, pp_axis: str = "pp",
                   num_microbatches: int = 0, tp_axis: str = None,
                   sp_axis: str = None,
                   param_specs: Any = None) -> jax.Array:
    """Run ``x`` through L stacked layers pipelined over the pp axis.

    ``block_fn(act, layer_params) -> act`` is one transformer block;
    ``stacked_params`` is a pytree whose leaves have leading dim L with
    L % pp == 0 (stage s owns layers [s*L/P, (s+1)*L/P)).
    ``x`` is [B, S, d] with the batch dim (optionally) sharded over
    dp/fsdp; it must NOT be sharded over pp.

    Sequence parallelism inside a stage (pp x sp): pass ``sp_axis`` and
    a ``block_fn`` whose attention is a ring/Ulysses collective over
    ``sp_axis`` (see gpt's ``_block_pp_sp``) — activations arrive
    [mb, S/sp, d] per device and stay sequence-sharded end to end.

    Tensor parallelism inside a stage (pp x tp): pass ``tp_axis`` plus
    ``param_specs`` (a pytree of PartitionSpecs sharding each leaf over
    pp AND the tp dims) and a ``block_fn`` that performs its own tp
    collectives (Megatron-style: column-parallel qkv/up, row-parallel
    out/down with a psum over ``tp_axis`` after each row matmul) — the
    whole body runs per-device under shard_map, so GSPMD cannot insert
    them. Activations stay replicated over tp.
    """
    from jax.sharding import PartitionSpec as P

    names = set(mesh.axis_names)
    if pp_axis not in names:
        raise ValueError(f"mesh has no {pp_axis!r} axis: {mesh.axis_names}")
    if "sp" in names and sp_axis is None:
        raise ValueError(
            "mesh has an sp axis: pass sp_axis= with an sp-aware "
            "block_fn (ring attention over sp — see gpt's pp x sp "
            "branch)")
    if "tp" in names and tp_axis is None:
        raise ValueError(
            "mesh has a tp axis: pass tp_axis= and param_specs= with a "
            "tp-aware block_fn (see gpt.forward's pp branch)")
    pp_size = mesh.shape[pp_axis]
    num_mb = num_microbatches or 2 * pp_size

    bt = tuple(a for a in ("dp", "fsdp") if a in names) or None
    x_spec = P(bt, sp_axis, None)
    if param_specs is None:
        param_specs = jax.tree.map(lambda l: _stage_spec(l, pp_axis),
                                   stacked_params)

    def body(params_local, x_local):
        P_ = pp_size  # static: mesh shape is known at trace time
        p = lax.axis_index(pp_axis)
        B_loc, S, d = x_local.shape
        if B_loc % num_mb:
            raise ValueError(
                f"per-shard batch {B_loc} not divisible by "
                f"num_microbatches={num_mb}")
        mb = B_loc // num_mb
        x_mbs = x_local.reshape(num_mb, mb, S, d)

        def stage(act):
            def scan_body(carry, layer_params):
                return block_fn(carry, layer_params), None

            out, _ = lax.scan(scan_body, act, params_local)
            return out

        fwd_perm = [(i, (i + 1) % P_) for i in range(P_)]

        def step(carry, t):
            prev_out, outbuf = carry
            recv = lax.ppermute(prev_out, pp_axis, fwd_perm)
            feed = lax.dynamic_index_in_dim(
                x_mbs, jnp.clip(t, 0, num_mb - 1), keepdims=False)
            act_in = jnp.where(p == 0, feed, recv)
            out = stage(act_in)
            # Stage P-1 finishes microbatch (t - (P-1)) at step t; other
            # ranks write garbage slots that the masked psum below zeroes.
            out_idx = jnp.clip(t - (P_ - 1), 0, num_mb - 1)
            valid = (t >= P_ - 1) & (t - (P_ - 1) < num_mb)
            cur = lax.dynamic_index_in_dim(outbuf, out_idx, keepdims=False)
            outbuf = lax.dynamic_update_index_in_dim(
                outbuf, jnp.where(valid, out, cur), out_idx, 0)
            return (out, outbuf), None

        act0 = jnp.zeros((mb, S, d), x_local.dtype)
        outbuf0 = jnp.zeros((num_mb, mb, S, d), x_local.dtype)
        (_, outbuf), _ = lax.scan(step, (act0, outbuf0),
                                  jnp.arange(num_mb + P_ - 1))
        # Only the last stage's buffer is real; masked psum broadcasts it
        # to every pp rank (exact: all other contributions are 0).
        outbuf = lax.psum(
            jnp.where(p == P_ - 1, outbuf, jnp.zeros_like(outbuf)), pp_axis)
        return outbuf.reshape(B_loc, S, d)

    return shard_map(
        body, mesh=mesh, in_specs=(param_specs, x_spec),
        out_specs=x_spec, check_vma=False)(stacked_params, x)
