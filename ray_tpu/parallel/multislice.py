"""Multi-slice (DCN) data parallelism: ICI mesh inside a slice, a
store-backed collective group across slices.

Capability parity with the reference's multi-node communication backend
(NCCL/MPI process groups spanning hosts): on TPU pods, traffic inside a
slice rides ICI via XLA collectives; traffic BETWEEN slices crosses the
data-center network. This module composes the two the standard way
(jax-ml scaling-book "multi-slice" recipe): the per-slice train step
psums gradients over the ICI mesh, then one host-side allreduce per
step crosses slices over the DCN transport (here: the cluster KV store
group — the same role NCCL-over-TCP plays for the reference).

``run_multislice_dryrun`` proves the composition end-to-end on CPU: it
spawns one process per virtual slice (each with its own
``--xla_force_host_platform_device_count`` device set), trains the nano
GPT one step per slice, allreduces gradients across slices, and checks
every slice applied the identical update.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time
from typing import Any


def dcn_allreduce_tree(tree: Any, group) -> Any:
    """Average a pytree of host arrays across slices via the DCN group.

    One flattened fp32 vector per step — a single DCN collective, not
    one per leaf (DCN latency dominates; bandwidth is fine)."""
    import jax
    import numpy as np

    leaves, treedef = jax.tree.flatten(tree)
    flat = np.concatenate([np.asarray(l, np.float32).ravel()
                           for l in leaves]) if leaves else np.zeros(0)
    # The tree allreduce may hand back a zero-copy READ-ONLY shm view
    # (object-store fast path) — divide out-of-place.
    summed = np.asarray(group.allreduce(flat, "sum"),
                        np.float32) / group.world_size
    out, off = [], 0
    for l in leaves:
        n = int(np.prod(np.shape(l))) or 1
        out.append(summed[off:off + n].reshape(np.shape(l))
                   .astype(np.asarray(l).dtype))
        off += n
    return jax.tree.unflatten(treedef, out)


def slice_main(argv=None) -> int:
    """One virtual slice: intra-slice dp mesh + cross-slice DCN group."""
    parser = argparse.ArgumentParser()
    parser.add_argument("--head", required=True)
    parser.add_argument("--slice-id", type=int, required=True)
    parser.add_argument("--n-slices", type=int, required=True)
    parser.add_argument("--devices", type=int, default=4)
    parser.add_argument("--out", required=True)
    args = parser.parse_args(argv)

    from ray_tpu.testing import force_host_devices

    force_host_devices(args.devices)

    import jax
    import jax.numpy as jnp
    import numpy as np

    import ray_tpu as rt
    from ray_tpu import collective
    from ray_tpu.models import gpt
    from ray_tpu.parallel import create_mesh

    rt.init(address=args.head)
    group = collective.init_collective_group(
        args.n_slices, args.slice_id, backend="store",
        group_name="dcn_dp")

    # Intra-slice: plain dp over the slice's ICI mesh.
    mesh = create_mesh({"dp": args.devices})
    cfg = gpt.CONFIGS["nano"]
    params = gpt.init_params(jax.random.PRNGKey(0), cfg)  # same seed/slice

    def loss_fn(params, tokens):
        logits = gpt.forward(params, tokens[:, :-1], cfg, mesh)
        logp = jax.nn.log_softmax(logits, axis=-1)
        tgt = tokens[:, 1:]
        ll = jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
        return -jnp.mean(ll)

    from jax.sharding import NamedSharding, PartitionSpec as P

    batch_sh = NamedSharding(mesh, P("dp", None))
    grad_fn = jax.jit(jax.value_and_grad(loss_fn))

    # Each slice sees DIFFERENT data (global batch = concat of slices).
    rng = np.random.default_rng(1000 + args.slice_id)
    tokens = jax.device_put(
        rng.integers(0, cfg.vocab_size, (8, 33)).astype(np.int32),
        batch_sh)

    loss, grads = grad_fn(params, tokens)
    host_grads = jax.device_get(grads)          # ICI psum already applied
    avg = dcn_allreduce_tree(host_grads, group)  # DCN crossing

    lr = 0.1
    new_params = jax.tree.map(
        lambda p, g: (np.asarray(p, np.float32)
                      - lr * np.asarray(g, np.float32)), params, avg)
    # Identical update on every slice == the checksum agrees.
    checksum = float(sum(float(np.sum(l))
                         for l in jax.tree.leaves(new_params)))
    sums = np.asarray(group.allgather(
        np.asarray([checksum], np.float64))).ravel()
    ok = all(abs(float(s) - checksum) < 1e-3 * max(1.0, abs(checksum))
             for s in sums)
    with open(args.out, "w") as f:
        json.dump({"slice": args.slice_id, "loss": float(loss),
                   "checksum": checksum, "agree": bool(ok)}, f)
    rt.shutdown()
    return 0 if ok else 1


def run_multislice_dryrun(n_slices: int = 2, devices_per_slice: int = 4,
                          timeout_s: float = 600.0) -> dict:
    """Spawn one process per virtual slice against an embedded cluster;
    returns the per-slice reports (raises if any slice fails)."""
    import ray_tpu as rt

    if rt.is_initialized():
        rt.shutdown()
    rt.init(num_cpus=max(2, n_slices), num_tpus=0)
    from ray_tpu.core.worker import CoreWorker

    head_sock = CoreWorker._current.head_sock
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    outs, procs = [], []
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    try:
        for s in range(n_slices):
            out = tempfile.mktemp(prefix=f"rt_slice{s}_")
            outs.append(out)
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "ray_tpu.parallel.multislice",
                 "--head", head_sock, "--slice-id", str(s),
                 "--n-slices", str(n_slices),
                 "--devices", str(devices_per_slice), "--out", out],
                env=env))
        deadline = time.time() + timeout_s
        for p in procs:
            p.wait(timeout=max(1.0, deadline - time.time()))
        reports = []
        for s, (p, out) in enumerate(zip(procs, outs)):
            if p.returncode != 0:
                raise RuntimeError(f"slice {s} failed (rc={p.returncode})")
            with open(out) as f:
                reports.append(json.load(f))
        assert all(r["agree"] for r in reports), reports
        return {"slices": reports}
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for out in outs:
            try:
                os.unlink(out)
            except OSError:
                pass
        rt.shutdown()


if __name__ == "__main__":
    raise SystemExit(slice_main())
