"""Device meshes, sharding rules, and parallelism plans (TPU-native core).

Replaces the reference's process-group plumbing (NCCL/gloo rendezvous,
torch DDP/FSDP wrapping — ``python/ray/train/torch/config.py``,
``train_loop_utils.py``) with jax Mesh + NamedSharding: the compiler, not
the framework, owns the collective schedule.
"""
from .mesh import (  # noqa: F401
    AXIS_ORDER,
    MeshConfig,
    create_mesh,
    initialize_multihost,
    local_chip_count,
    mesh_shape,
    single_device_mesh,
)
from .sharding import (  # noqa: F401
    DP_RULES,
    LM_RULES,
    batch_sharding,
    replicated,
    shard_tree,
    spec_for,
    tree_shardings,
)
