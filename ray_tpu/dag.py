"""Compiled DAGs: pre-wired actor pipelines over mutable channels.

Capability parity with the reference's compiled graphs (reference:
``python/ray/dag/compiled_dag_node.py:372`` — ``bind`` builds a DAG of
actor method calls, ``experimental_compile`` allocates channels and
pins a long-running execution loop on each actor so per-call RPC and
object-store traffic disappear from the steady state).

Here: ``actor.method.bind(upstream)`` builds MethodNodes off an
``InputNode``; ``compile()`` creates one shm Channel per edge and starts
a drive loop on each actor (a special ``__rt_drive__`` actor task the
worker runtime interprets: read input channel → call method → write
output channel). ``execute(x)`` writes the input channel and reads the
terminal channel — one shm write and one shm read per call.

Current scope: linear chains of single-reader edges (the common
inference-pipeline shape); fan-out/fan-in composition can extend the
edge allocation without changing the channel protocol.
"""
from __future__ import annotations

from typing import Any, List, Optional

from .experimental.channel import Channel, ChannelClosed  # noqa: F401


class InputNode:
    """Placeholder for the value passed to ``execute()``."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class MethodNode:
    def __init__(self, handle, method_name: str, upstream):
        self.handle = handle
        self.method_name = method_name
        self.upstream = upstream

    def bind_chain(self) -> List["MethodNode"]:
        chain: List[MethodNode] = []
        node: Any = self
        while isinstance(node, MethodNode):
            chain.append(node)
            node = node.upstream
        if not isinstance(node, InputNode):
            raise ValueError("compiled DAG chain must end at an InputNode")
        return list(reversed(chain))

    def experimental_compile(self, *, capacity_bytes: int = 1 << 20,
                             timeout: float = 30.0) -> "CompiledDAG":
        return CompiledDAG(self.bind_chain(), capacity_bytes, timeout)


def bind(actor_method, upstream) -> MethodNode:
    """``bind(actor.method, upstream_node)`` — functional form."""
    return MethodNode(actor_method._handle, actor_method._name, upstream)


class CompiledDAG:
    def __init__(self, chain: List[MethodNode], capacity_bytes: int,
                 timeout: float):
        import ray_tpu as rt

        self._rt = rt
        self._timeout = timeout
        # one channel per edge: input → a1 → a2 → ... → output
        self._channels = [Channel(capacity_bytes, num_readers=1)
                          for _ in range(len(chain) + 1)]
        from .api import ActorMethod

        self._drive_refs = []
        for i, node in enumerate(chain):
            method = ActorMethod(node.handle, "__rt_drive__")
            self._drive_refs.append(method.remote(
                node.method_name, self._channels[i],
                self._channels[i + 1]))
        self._closed = False

    def execute(self, value: Any) -> Any:
        if self._closed:
            raise ChannelClosed("compiled DAG torn down")
        self._channels[0].write(value, timeout=self._timeout)
        out = self._channels[-1].read(0, timeout=self._timeout)
        from .exceptions import TaskError

        if isinstance(out, TaskError):
            raise out  # same raise-on-get convention as rt.get
        return out

    def teardown(self):
        if self._closed:
            return
        self._closed = True
        for ch in self._channels:
            ch.close()
        # drive loops observe the closed flag and return
        try:
            self._rt.get(self._drive_refs, timeout=10)
        except Exception:
            pass
        for ch in self._channels:
            ch.destroy()

    def __del__(self):
        try:
            self.teardown()
        except Exception:
            pass
