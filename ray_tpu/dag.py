"""Compiled DAGs: pre-wired actor pipelines over mutable channels.

Capability parity with the reference's compiled graphs (reference:
``python/ray/dag/compiled_dag_node.py:372`` — ``bind`` builds a DAG of
actor method calls, ``experimental_compile`` allocates channels and
pins a long-running execution loop on each actor so per-call RPC and
object-store traffic disappear from the steady state).

Here: ``actor.method.bind(*upstreams)`` builds MethodNodes off an
``InputNode``; ``compile()`` allocates one channel per producer edge and
starts a drive loop on each actor (a special ``__rt_drive__`` actor task
the worker runtime interprets: read one value from each input channel →
call the method → write the output channel). ``execute(x)`` writes the
input channel and reads the terminal channel(s).

Topology support: linear chains, fan-out (one producer, many
consumers — a multi-reader channel), fan-in / multi-arg nodes
(``bind(a, b)`` joins one item from each upstream per call), and
``MultiOutputNode`` for multiple terminals. Edges whose endpoints sit
in different shm domains (different hosts) automatically use the
TCP-pushed channel instead of the shm slot (reference:
``node_manager.proto:430-432``).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from .experimental.channel import (Channel, ChannelClosed,  # noqa: F401
                                   TcpChannel)


class InputNode:
    """Placeholder for the value passed to ``execute()``."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


class MethodNode:
    def __init__(self, handle, method_name: str, *upstreams):
        self.handle = handle
        self.method_name = method_name
        self.upstreams: Tuple[Any, ...] = upstreams
        if not upstreams:
            raise ValueError("a MethodNode needs at least one upstream")

    # Back-compat alias: old code reads .upstream on linear chains.
    @property
    def upstream(self):
        return self.upstreams[0]

    def experimental_compile(self, *, capacity_bytes: int = 1 << 20,
                             timeout: float = 30.0) -> "CompiledDAG":
        return CompiledDAG([self], capacity_bytes, timeout)


class MultiOutputNode:
    """Explicit multi-terminal wrapper: ``execute`` returns one value
    per listed node (reference: ``ray.dag.MultiOutputNode``)."""

    def __init__(self, nodes: List[MethodNode]):
        self.nodes = list(nodes)

    def experimental_compile(self, *, capacity_bytes: int = 1 << 20,
                             timeout: float = 30.0) -> "CompiledDAG":
        return CompiledDAG(self.nodes, capacity_bytes, timeout)


def bind(actor_method, *upstreams) -> MethodNode:
    """``bind(actor.method, up1, up2, ...)`` — functional form."""
    return MethodNode(actor_method._handle, actor_method._name, *upstreams)


class CompiledDAG:
    def __init__(self, terminals: List[MethodNode], capacity_bytes: int,
                 timeout: float):
        import ray_tpu as rt
        from ray_tpu.core.worker import CoreWorker

        self._rt = rt
        self._timeout = timeout
        core = CoreWorker.current()

        # ---- topology: topological order via post-order DFS ----------
        nodes: List[MethodNode] = []
        seen: Dict[int, bool] = {}

        def visit(n):
            if isinstance(n, InputNode) or id(n) in seen:
                return
            seen[id(n)] = True
            for u in n.upstreams:
                visit(u)
            nodes.append(n)

        for t in terminals:
            visit(t)

        # consumers[producer] = [(consumer_node | "driver", arg_pos)]
        consumers: Dict[int, List[tuple]] = {}
        producers: Dict[int, Any] = {}  # id -> node (or InputNode)
        self._input_node: Optional[InputNode] = None
        for n in nodes:
            for pos, u in enumerate(n.upstreams):
                if isinstance(u, InputNode):
                    self._input_node = u
                producers[id(u)] = u
                consumers.setdefault(id(u), []).append((n, pos))
        for t in terminals:
            producers[id(t)] = t
            consumers.setdefault(id(t), []).append(("driver", 0))
        if self._input_node is None:
            raise ValueError("compiled DAG must consume an InputNode")

        # ---- placement: shm domain per endpoint ----------------------
        addresses: Dict[int, Any] = {}
        for n in nodes:
            core.wait_actor_ready(n.handle._actor_id, timeout=timeout)
            addresses[id(n)] = core.actor_address(n.handle._actor_id,
                                                  timeout=timeout)
        # One cluster-state fetch for the whole compile (after every
        # actor is placed, so assignments are visible), not per node.
        try:
            cluster_workers = core.head_call("state", {"kind": "workers"})
            node_domains = {
                ni["node_id"]: ni["hostname"]
                for ni in core.head_call("state", {"kind": "nodes"})}
        except Exception:  # noqa: BLE001 - assume co-located
            cluster_workers, node_domains = [], {}

        def actor_domain(handle) -> Optional[str]:
            hexa = handle._actor_id.hex()
            for w in cluster_workers:
                if hexa[:12] in str(w.get("assignment", "")):
                    return node_domains.get(w["node_id"])
            return None

        domains: Dict[int, Optional[str]] = {}
        for n in nodes:
            domains[id(n)] = actor_domain(n.handle)
        driver_domain = core.shm_domain

        def endpoint_domain(e):
            if e == "driver" or isinstance(e, InputNode):
                return driver_domain
            return domains.get(id(e)) or driver_domain

        def endpoint_address(e):
            if e == "driver" or isinstance(e, InputNode):
                return core.address
            return addresses[id(e)]

        # ---- channels: one per producer ------------------------------
        self._channels: Dict[int, Any] = {}
        self._reader_idx: Dict[Tuple[int, int], int] = {}
        for pid, producer in producers.items():
            cons = consumers.get(pid, [])
            if not cons:
                continue
            wd = endpoint_domain(producer)
            cross = any(endpoint_domain(c) != wd
                        and endpoint_domain(c) is not None
                        for c, _ in cons)
            if cross:
                ch = TcpChannel([endpoint_address(c) for c, _ in cons])
            else:
                ch = Channel(capacity_bytes, num_readers=len(cons))
            self._channels[pid] = ch
            for ridx, (c, pos) in enumerate(cons):
                cid = -1 if c == "driver" else id(c)
                self._reader_idx[(pid, cid, pos)] = ridx

        # ---- drive loops ---------------------------------------------
        from .api import ActorMethod

        self._drive_refs = []
        for n in nodes:
            in_chs = [self._channels[id(u)] for u in n.upstreams]
            ridxs = [self._reader_idx[(id(u), id(n), pos)]
                     for pos, u in enumerate(n.upstreams)]
            method = ActorMethod(n.handle, "__rt_drive__")
            self._drive_refs.append(method.remote(
                n.method_name, in_chs, ridxs, self._channels[id(n)]))

        self._terminals = terminals
        self._multi = len(terminals) > 1
        self._closed = False

    def _terminal_read(self, t):
        ch = self._channels[id(t)]
        ridx = self._reader_idx[(id(t), -1, 0)]
        out = ch.read(ridx, timeout=self._timeout)
        from .exceptions import TaskError

        if isinstance(out, TaskError):
            raise out  # same raise-on-get convention as rt.get
        return out

    def execute(self, value: Any) -> Any:
        if self._closed:
            raise ChannelClosed("compiled DAG torn down")
        self._channels[id(self._input_node)].write(
            value, timeout=self._timeout)
        outs = [self._terminal_read(t) for t in self._terminals]
        return outs if self._multi else outs[0]

    def teardown(self):
        if self._closed:
            return
        self._closed = True
        for ch in self._channels.values():
            ch.close()
        # drive loops observe the closed flag and return
        try:
            self._rt.get(self._drive_refs, timeout=10)
        except Exception:
            pass
        for ch in self._channels.values():
            ch.destroy()

    def __del__(self):
        try:
            self.teardown()
        except Exception:
            pass
