"""DQN: replay + target network + double-Q loss on a jitted learner.

Capability parity with the reference's DQN/Rainbow family entry point
(reference: ``rllib/algorithms/dqn/dqn.py`` — ``training_step``: sample →
store → replay-sample → TD update → target sync → priority update), with
the torch loss replaced by a jitted double-DQN step and prioritized
replay from :mod:`.replay_buffer`.
"""
from __future__ import annotations

from typing import Any, Dict

import numpy as np

from .algorithm import Algorithm, AlgorithmConfig
from .learner import LearnerGroup
from .replay_buffer import PrioritizedReplayBuffer, ReplayBuffer
from .rl_module import DiscreteMLPModule, module_forward


class EpsilonGreedyModule(DiscreteMLPModule):
    """Q-network module: exploration is epsilon-greedy over argmax-Q.

    The "value" head doubles as nothing here — Q-values come from the
    logits head; GAE columns produced by the env runner are ignored by
    the DQN learner.
    """

    def __init__(self, spec, seed: int = 0):
        if spec.conv:
            from .conv_module import init_conv_params

            self.spec = spec
            self.params = init_conv_params(spec, seed)
        else:
            super().__init__(spec, seed)
        self.epsilon = 1.0

    def forward_inference(self, obs: np.ndarray):
        q, _ = module_forward(self.spec, self.params, obs, np)
        return q.argmax(-1)

    def forward_values(self, obs: np.ndarray) -> np.ndarray:
        _, value = module_forward(self.spec, self.params, obs, np)
        return value

    def forward_exploration(self, obs: np.ndarray,
                            rng: np.random.Generator):
        q, value = module_forward(self.spec, self.params, obs, np)
        greedy = q.argmax(-1)
        explore = rng.random(len(greedy)) < self.epsilon
        random_a = rng.integers(0, q.shape[-1], len(greedy))
        actions = np.where(explore, random_a, greedy)
        # logp is meaningless for value-based exploration; fill zeros.
        return actions, np.zeros(len(actions), np.float32), value

    def set_weights(self, params):
        # Epsilon rides along with weight broadcasts (the algorithm owns
        # the schedule; runners just apply it).
        if isinstance(params, dict) and "__epsilon__" in params:
            params = dict(params)
            self.epsilon = float(params.pop("__epsilon__"))
        super().set_weights(params)


class DQNLearner:
    """Jitted double-DQN TD step with a periodically synced target net."""

    def __init__(self, module_spec, *, lr: float = 1e-3,
                 gamma: float = 0.99, grad_clip: float = 10.0,
                 seed: int = 0, mesh=None):
        import jax
        import optax

        self.spec = module_spec
        self.gamma = gamma
        self.optimizer = optax.chain(
            optax.clip_by_global_norm(grad_clip), optax.adam(lr))
        module = module_spec.build(seed)
        self.params = module.params
        self.target_params = jax.tree.map(np.copy, self.params)
        self.opt_state = self.optimizer.init(self.params)
        self._step = self._build_step()

    def _build_step(self):
        import jax
        import jax.numpy as jnp
        import optax

        spec, gamma, optimizer = self.spec, self.gamma, self.optimizer

        def loss_fn(params, target_params, batch):
            q, _ = module_forward(spec, params, batch["obs"], jnp)
            q_taken = jnp.take_along_axis(
                q, batch["actions"][:, None], axis=-1)[:, 0]
            # double DQN: online net picks a', target net evaluates it
            q_next_online, _ = module_forward(spec, params,
                                              batch["next_obs"], jnp)
            a_prime = q_next_online.argmax(-1)
            q_next_target, _ = module_forward(spec, target_params,
                                              batch["next_obs"], jnp)
            q_prime = jnp.take_along_axis(
                q_next_target, a_prime[:, None], axis=-1)[:, 0]
            target = batch["rewards"] + gamma * q_prime * \
                (1.0 - batch["dones"])
            td = q_taken - jax.lax.stop_gradient(target)
            weights = batch.get("weights")
            w = weights if weights is not None else jnp.ones_like(td)
            loss = jnp.mean(w * jnp.square(td))
            return loss, {"td_errors": td, "qf_loss": loss,
                          "q_mean": q_taken.mean()}

        def step(params, target_params, opt_state, batch):
            (_, aux), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params, target_params, batch)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, aux

        return jax.jit(step)

    def update(self, batch: Dict[str, np.ndarray]) -> Dict[str, Any]:
        feed = {
            "obs": batch["obs"].astype(np.float32),
            "actions": batch["actions"].astype(np.int64),
            "rewards": batch["rewards"].astype(np.float32),
            "next_obs": batch["next_obs"].astype(np.float32),
            "dones": batch["dones"].astype(np.float32),
        }
        if "weights" in batch:
            feed["weights"] = batch["weights"].astype(np.float32)
        self.params, self.opt_state, aux = self._step(
            self.params, self.target_params, self.opt_state, feed)
        td = np.asarray(aux.pop("td_errors"))
        out = {k: float(v) for k, v in aux.items()}
        out["td_errors"] = td
        return out

    def sync_target(self):
        import jax

        self.target_params = jax.tree.map(np.asarray, self.params)

    def get_weights(self):
        import jax

        return jax.tree.map(np.asarray, self.params)

    def set_weights(self, weights):
        self.params = weights

    def get_state(self):
        import jax

        return {"params": jax.tree.map(np.asarray, self.params),
                "target": jax.tree.map(np.asarray, self.target_params),
                "opt_state": jax.tree.map(np.asarray, self.opt_state)}

    def set_state(self, state):
        self.params = state["params"]
        self.target_params = state["target"]
        self.opt_state = state["opt_state"]

    def update_full(self, batch, **kw):
        return self.update(batch)


class DQNConfig(AlgorithmConfig):
    def __init__(self):
        super().__init__()
        self.algo_class = DQN
        self.lr = 1e-3
        self.train_batch_size = 32
        self.replay_capacity = 50_000
        self.num_steps_sampled_before_learning = 1000
        self.target_update_freq = 500      # learner updates between syncs
        self.updates_per_iteration = 64
        self.prioritized_replay = True
        self.replay_alpha = 0.6
        self.replay_beta = 0.4
        self.epsilon_initial = 1.0
        self.epsilon_final = 0.05
        self.epsilon_decay_steps = 10_000
        self.rollout_fragment_length = 64


class DQN(Algorithm):
    def __init__(self, config: DQNConfig):
        self._replay = None
        super().__init__(config)

    def _make_module_spec(self, config):
        spec = config.module_spec()
        spec.module_cls = EpsilonGreedyModule
        return spec

    def _build_learner_group(self):
        cfg = self.config
        spec = self.module_spec
        if cfg.prioritized_replay:
            self._replay = PrioritizedReplayBuffer(
                cfg.replay_capacity, alpha=cfg.replay_alpha,
                beta=cfg.replay_beta, seed=cfg.seed)
        else:
            self._replay = ReplayBuffer(cfg.replay_capacity, seed=cfg.seed)
        self._learner = DQNLearner(
            spec, lr=cfg.lr, gamma=cfg.gamma, grad_clip=cfg.grad_clip,
            seed=cfg.seed, mesh=cfg.mesh)
        self._updates = 0

        class _SoloGroup(LearnerGroup):
            def __init__(inner):  # noqa: N805 - tiny adapter
                inner.local = self._learner
                inner.remote = []

        return _SoloGroup()

    def _epsilon(self) -> float:
        cfg = self.config
        frac = min(1.0, self._timesteps /
                   max(1, cfg.epsilon_decay_steps))
        return cfg.epsilon_initial + frac * (
            cfg.epsilon_final - cfg.epsilon_initial)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        # 1. sample and store
        for batch in self.env_runner_group.sample():
            n = len(batch)
            self._timesteps += n
            self._replay.add({
                "obs": batch["obs"], "actions": batch["actions"],
                "rewards": batch["rewards"],
                "next_obs": batch["next_obs"],
                "dones": (batch["dones"].astype(np.float32)),
            })
        metrics: Dict[str, Any] = {}
        # 2. replay updates once warm
        if len(self._replay) >= cfg.num_steps_sampled_before_learning:
            for _ in range(cfg.updates_per_iteration):
                sample = self._replay.sample(cfg.train_batch_size)
                out = self._learner.update(sample)
                td = out.pop("td_errors")
                if hasattr(self._replay, "update_priorities"):
                    self._replay.update_priorities(sample["_indices"], td)
                metrics = out
                self._updates += 1
                if self._updates % cfg.target_update_freq == 0:
                    self._learner.sync_target()
        # 3. broadcast weights + fresh epsilon to runners
        w = dict(self._learner.get_weights())
        w["__epsilon__"] = self._epsilon()
        self.env_runner_group.sync_weights(w)
        metrics["epsilon"] = self._epsilon()
        metrics["replay_size"] = len(self._replay)
        metrics["num_updates"] = self._updates
        return metrics
