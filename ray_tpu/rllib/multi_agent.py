"""Multi-agent RL: dict-keyed envs, per-agent episode streams, a
module container, and multi-agent PPO.

Reference surface: ``rllib/env/multi_agent_env.py`` (dict obs/action
API with the mandatory ``"__all__"`` termination key),
``rllib/env/multi_agent_env_runner.py:44`` (episode-wise sampling with
agent→module mapping), ``rllib/core/rl_module/multi_rl_module.py``
(dict-of-modules container), ``rllib/env/multi_agent_episode.py``
(per-agent trajectories with delayed-reward accumulation for
turn-based envs).

Re-designed for this framework's TPU split rather than translated:
rollouts stay numpy-only on CPU actors while each module's learner is
the existing jitted PPOLearner — multi-agent training is N independent
jit programs over per-module batches, so XLA sees the same fused
single-module step it already compiles, and modules with different
architectures never force padding or ragged batching onto the MXU.
Trajectories are kept as per-(env, agent, module) STREAMS: contiguous
transition runs that GAE scans per-stream, which replaces the
reference's MultiAgentEpisode global-time bookkeeping with flat arrays.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import ray_tpu as rt

from .algorithm import Algorithm
from .env_runner import SampleBatch
from .learner import LearnerGroup, PPOLearner, compute_gae
from .rl_module import RLModuleSpec

# ---------------------------------------------------------------- env API


class MultiAgentEnv:
    """Base class for dict-keyed multi-agent environments.

    Contract (reference ``multi_agent_env.py``):
      - ``reset(seed) -> (obs_dict, info_dict)`` — obs for every agent
        that must act first.
      - ``step(action_dict) -> (obs, rewards, terminateds, truncateds,
        infos)`` — all dicts keyed by agent id. Only agents present in
        ``obs`` act next step (turn-based envs return a subset).
        Rewards may name agents that did NOT act this step (delayed
        credit); they accrue to that agent's open transition.
        ``terminateds["__all__"]`` is REQUIRED and ends the episode for
        everyone; per-agent keys retire individual agents early.
    """

    possible_agents: List[str] = []
    observation_spaces: Dict[str, Any] = {}
    action_spaces: Dict[str, Any] = {}

    def reset(self, *, seed: Optional[int] = None,
              options: Optional[dict] = None):
        raise NotImplementedError

    def step(self, action_dict: Dict[str, Any]):
        raise NotImplementedError

    def close(self):
        pass


def spec_from_spaces(obs_space, act_space,
                     hidden: Tuple[int, ...] = (64, 64)) -> RLModuleSpec:
    """Build an RLModuleSpec from gymnasium-style spaces (the per-agent
    half of ``AlgorithmConfig.module_spec``)."""
    obs_dim = int(np.prod(obs_space.shape))
    if hasattr(act_space, "n"):
        return RLModuleSpec(obs_dim=obs_dim, num_actions=int(act_space.n),
                            hidden=hidden)
    return RLModuleSpec(
        obs_dim=obs_dim, num_actions=int(np.prod(act_space.shape)),
        hidden=hidden, continuous=True,
        action_low=np.asarray(act_space.low, np.float32),
        action_high=np.asarray(act_space.high, np.float32))


# ------------------------------------------------------------- container


class MultiRLModule:
    """Dict of ``module_id → RLModule`` (reference
    ``multi_rl_module.py``): one acting-side container whose weights
    move as a dict pytree."""

    def __init__(self, specs: Dict[str, RLModuleSpec], seed: int = 0):
        self.specs = specs
        self.modules = {mid: spec.build(seed + i)
                        for i, (mid, spec) in enumerate(sorted(
                            specs.items()))}

    def __getitem__(self, mid: str):
        return self.modules[mid]

    def keys(self):
        return self.modules.keys()

    def get_weights(self) -> Dict[str, Any]:
        return {mid: m.get_weights() for mid, m in self.modules.items()}

    def set_weights(self, weights: Dict[str, Any]):
        for mid, w in weights.items():
            if mid in self.modules:
                self.modules[mid].set_weights(w)


# ------------------------------------------------------------ env runner


class _Pending:
    """An OPEN transition: the agent acted, its next obs hasn't arrived."""

    __slots__ = ("obs", "action", "logp", "value", "reward")

    def __init__(self, obs, action, logp, value):
        self.obs = obs
        self.action = action
        self.logp = logp
        self.value = value
        self.reward = 0.0


class MultiAgentEnvRunner:
    """Steps ``num_envs`` copies of a MultiAgentEnv, accumulating
    per-(env, agent, module) transition streams.

    Stream semantics: a stream is a CONTIGUOUS run of one agent's
    transitions under one module in one env copy, spanning episodes
    (episode boundaries are flagged done/truncated inside the stream —
    ``compute_gae`` cuts there). ``sample()`` drains all closed
    transitions; transitions still waiting for their next observation
    stay open across fragments so every emitted row has a true
    successor state.
    """

    def __init__(self, env_creator: Callable,
                 module_specs: Dict[str, RLModuleSpec],
                 policy_mapping_fn: Optional[Callable] = None,
                 num_envs: int = 1, rollout_fragment_length: int = 200,
                 seed: int = 0):
        self.envs = [env_creator() for _ in range(num_envs)]
        self.marl_module = MultiRLModule(module_specs, seed)
        self.mapping = policy_mapping_fn or (lambda aid, env_idx: str(aid))
        self.T = rollout_fragment_length
        self.rng = np.random.default_rng(seed)
        self._ready: List[Dict[str, np.ndarray]] = [dict() for _ in self.envs]
        self._map: List[Dict[str, str]] = [dict() for _ in self.envs]
        self._pending: Dict[Tuple[int, str], _Pending] = {}
        # (env, agent, module) -> list of closed transition dicts
        self._streams: Dict[Tuple[int, str, str], List[dict]] = {}
        # (env, agent, module) -> index of a closed transition whose
        # next_value is the value we compute when the agent next acts
        self._needs_next: Dict[Tuple[int, str, str], int] = {}
        self.episode_returns = [0.0] * num_envs
        self.completed_returns: List[float] = []
        self._module_ep_returns: Dict[str, List[float]] = {
            mid: [] for mid in module_specs}
        self._module_running: List[Dict[str, float]] = [
            {mid: 0.0 for mid in module_specs} for _ in self.envs]
        for i, env in enumerate(self.envs):
            obs, _ = env.reset(seed=seed + i)
            self._begin_episode(i, obs)

    # ------------------------------------------------------- episode mgmt
    def _begin_episode(self, i: int, obs_dict):
        self._ready[i] = {a: np.asarray(o, np.float32)
                          for a, o in obs_dict.items()}
        self._map[i] = {}
        for mid in self._module_running[i]:
            self._module_running[i][mid] = 0.0

    def _module_of(self, i: int, agent: str) -> str:
        m = self._map[i]
        if agent not in m:
            m[agent] = self.mapping(agent, i)
        return m[agent]

    def _close(self, i: int, agent: str, *, done: bool, trunc: bool,
               next_value: Optional[float]):
        """Move the open transition to its stream. ``next_value=None``
        defers the successor value to the agent's next action (or the
        fragment drain)."""
        p = self._pending.pop((i, agent), None)
        if p is None:
            return
        mid = self._module_of(i, agent)
        key = (i, agent, mid)
        stream = self._streams.setdefault(key, [])
        stream.append({
            "obs": p.obs, "action": p.action, "reward": p.reward,
            "done": done, "trunc": trunc, "logp": p.logp,
            "value": p.value,
            "next_value": 0.0 if done else next_value,
        })
        if not done and next_value is None:
            self._needs_next[key] = len(stream) - 1

    def _finish_episode_tail(self, i: int, term: dict, trunc: dict,
                             final_obs: dict):
        """``__all__`` fired: close every open transition of env ``i``
        and truncate dangling next-value waits (the episode is over —
        nothing after it may leak into GAE)."""
        all_term = bool(term.get("__all__", False))
        for (ei, agent) in [k for k in self._pending if k[0] == i]:
            a_term = bool(term.get(agent, all_term))
            # The episode IS over for everyone: any non-terminated agent
            # is truncated at this point regardless of what its
            # per-agent flag says — an un-cut final transition would
            # let GAE leak into the NEXT episode sharing this stream.
            a_trunc = not a_term
            nv = None
            if not a_term:
                # bootstrap the truncated tail with V(arrival obs);
                # fall back to the action obs if the env omitted it
                mid = self._module_of(i, agent)
                arrival = final_obs.get(agent)
                obs = (np.asarray(arrival, np.float32)
                       if arrival is not None
                       else self._pending[(ei, agent)].obs)
                nv = float(self.marl_module[mid].forward_values(
                    obs[None])[0])
            self._close(i, agent, done=a_term, trunc=a_trunc,
                        next_value=nv)
        # No _needs_next entry for env ``i`` can exist here: entries
        # are created only when a new obs arrives (which also makes the
        # agent ready), every ready agent acts on the next _act() call
        # (popping its entry), and the ``__all__`` branch runs before
        # this step's obs loop could create new ones.
        self.completed_returns.append(self.episode_returns[i])
        self.episode_returns[i] = 0.0
        for mid, ret in self._module_running[i].items():
            self._module_ep_returns[mid].append(ret)

    # ------------------------------------------------------------ stepping
    def _act(self):
        """One policy pass for every ready agent across all envs,
        grouped per module so each module sees one stacked batch."""
        groups: Dict[str, List[Tuple[int, str, np.ndarray]]] = {}
        for i in range(len(self.envs)):
            for agent, obs in self._ready[i].items():
                groups.setdefault(self._module_of(i, agent), []).append(
                    (i, agent, obs))
        actions: List[Dict[str, Any]] = [dict() for _ in self.envs]
        for mid, rows in groups.items():
            obs_batch = np.stack([r[2] for r in rows])
            acts, logp, values = self.marl_module[mid].forward_exploration(
                obs_batch, self.rng)
            for j, (i, agent, obs) in enumerate(rows):
                key = (i, agent, mid)
                if key in self._needs_next:
                    # V(s') for the previous closed transition is the
                    # value just computed at this (its successor) obs
                    self._streams[key][self._needs_next.pop(key)][
                        "next_value"] = float(values[j])
                self._pending[(i, agent)] = _Pending(
                    obs, acts[j], float(logp[j]), float(values[j]))
                actions[i][agent] = acts[j]
        for i in range(len(self.envs)):
            self._ready[i] = {}  # acting consumes the obs
        return actions

    def _step_envs(self, actions: List[Dict[str, Any]]):
        for i, env in enumerate(self.envs):
            # Step even with an empty action dict: an env may have no
            # ready agent this step (idle frames in turn-based games)
            # and only advances — eventually re-emitting obs — when
            # stepped; skipping it would freeze the episode forever.
            acts = {a: (int(v) if np.ndim(v) == 0 else v)
                    for a, v in actions[i].items()}
            obs, rew, term, trunc, _ = env.step(acts)
            for agent, r in rew.items():
                p = self._pending.get((i, agent))
                if p is not None:
                    p.reward += float(r)
                self.episode_returns[i] += float(r)
                mid = self._module_of(i, agent)
                self._module_running[i][mid] += float(r)
            if term.get("__all__", False) or trunc.get("__all__", False):
                self._finish_episode_tail(i, term, trunc, obs)
                new_obs, _ = env.reset()
                self._begin_episode(i, new_obs)
                continue
            # individual exits (agent died, env continues for the rest)
            for agent in set(list(term) + list(trunc)) - {"__all__"}:
                if term.get(agent, False) or trunc.get(agent, False):
                    p = self._pending.get((i, agent))
                    if p is None:
                        continue  # already retired (envs may re-report
                        # flags for dead agents); nothing to close
                    a_term = bool(term.get(agent, False))
                    nv = None
                    if not a_term:
                        mid = self._module_of(i, agent)
                        arrival = obs.get(agent)
                        src = (np.asarray(arrival, np.float32)
                               if arrival is not None else p.obs)
                        nv = float(self.marl_module[mid].forward_values(
                            src[None])[0])
                    self._close(i, agent, done=a_term,
                                trunc=not a_term, next_value=nv)
            for agent, o in obs.items():
                a_term = bool(term.get(agent, False))
                a_trunc = bool(trunc.get(agent, False))
                if a_term or a_trunc:
                    continue  # closed above; agent is out
                # new obs arrived: close the open transition (its
                # successor value comes at the agent's next action)
                self._close(i, agent, done=False, trunc=False,
                            next_value=None)
                self._ready[i][agent] = np.asarray(o, np.float32)

    # -------------------------------------------------------------- drain
    def sample(self) -> Dict[str, SampleBatch]:
        for _ in range(self.T):
            self._step_envs(self._act())
        # fill dangling next-values with a bootstrap at the held obs
        fill: Dict[str, List[Tuple[Tuple, int, np.ndarray]]] = {}
        for key, idx in self._needs_next.items():
            i, agent, mid = key
            held = self._ready[i].get(agent)
            obs = held if held is not None else self._streams[key][idx]["obs"]
            fill.setdefault(mid, []).append((key, idx, obs))
        for mid, rows in fill.items():
            vals = self.marl_module[mid].forward_values(
                np.stack([r[2] for r in rows]))
            for (key, idx, _), v in zip(rows, vals):
                self._streams[key][idx]["next_value"] = float(v)
        self._needs_next.clear()

        out: Dict[str, SampleBatch] = {}
        per_mod: Dict[str, List[List[dict]]] = {}
        for key in sorted(self._streams):
            stream = self._streams[key]
            if stream:
                per_mod.setdefault(key[2], []).append(stream)
        self._streams = {}
        for mid, streams in per_mod.items():
            cols = {k: [] for k in ("obs", "actions", "rewards", "dones",
                                    "truncateds", "logp", "values",
                                    "next_values")}
            lens = []
            for stream in streams:
                lens.append(len(stream))
                for tr in stream:
                    cols["obs"].append(tr["obs"])
                    cols["actions"].append(tr["action"])
                    cols["rewards"].append(tr["reward"])
                    cols["dones"].append(tr["done"])
                    cols["truncateds"].append(tr["trunc"])
                    cols["logp"].append(tr["logp"])
                    cols["values"].append(tr["value"])
                    cols["next_values"].append(tr["next_value"])
            out[mid] = SampleBatch(
                obs=np.stack(cols["obs"]).astype(np.float32),
                actions=np.asarray(cols["actions"]),
                rewards=np.asarray(cols["rewards"], np.float32),
                dones=np.asarray(cols["dones"], bool),
                truncateds=np.asarray(cols["truncateds"], bool),
                logp=np.asarray(cols["logp"], np.float32),
                values=np.asarray(cols["values"], np.float32),
                next_values=np.asarray(cols["next_values"], np.float32),
                _streams=np.asarray(lens, np.int64),
            )
        return out

    # ------------------------------------------------------------ weights
    def set_weights(self, weights: Dict[str, Any]):
        self.marl_module.set_weights(weights)

    def get_metrics(self) -> Dict[str, Any]:
        recent = self.completed_returns[-100:]
        out = {
            "num_episodes": len(self.completed_returns),
            "episode_return_mean": float(np.mean(recent)) if recent else 0.0,
            "episode_return_max": float(np.max(recent)) if recent else 0.0,
        }
        for mid, rets in self._module_ep_returns.items():
            r = rets[-100:]
            out[f"module/{mid}/episode_return_mean"] = (
                float(np.mean(r)) if r else 0.0)
        return out


class MultiAgentEnvRunnerGroup:
    """Local or remote multi-agent runners (mirrors EnvRunnerGroup)."""

    def __init__(self, env_creator, module_specs: Dict[str, RLModuleSpec],
                 policy_mapping_fn=None, num_env_runners: int = 0,
                 num_envs_per_runner: int = 1,
                 rollout_fragment_length: int = 200, seed: int = 0):
        self.local: Optional[MultiAgentEnvRunner] = None
        self.remote: List[Any] = []
        if num_env_runners == 0:
            self.local = MultiAgentEnvRunner(
                env_creator, module_specs, policy_mapping_fn,
                num_envs_per_runner, rollout_fragment_length, seed)
        else:
            cls = rt.remote(MultiAgentEnvRunner)
            self.remote = [
                cls.options(num_cpus=1).remote(
                    env_creator, module_specs, policy_mapping_fn,
                    num_envs_per_runner, rollout_fragment_length,
                    seed + 1000 * (i + 1))
                for i in range(num_env_runners)
            ]

    def sync_weights(self, weights: Dict[str, Any]):
        if self.local:
            self.local.set_weights(weights)
        if self.remote:
            wref = rt.put(weights)
            rt.get([r.set_weights.remote(wref) for r in self.remote],
                   timeout=60)

    def sample(self) -> List[Dict[str, SampleBatch]]:
        if self.local:
            return [self.local.sample()]
        return rt.get([r.sample.remote() for r in self.remote], timeout=300)

    def get_metrics(self) -> Dict[str, Any]:
        if self.local:
            return self.local.get_metrics()
        ms = rt.get([r.get_metrics.remote() for r in self.remote],
                    timeout=60)
        total = sum(m["num_episodes"] for m in ms)
        means = [m["episode_return_mean"] for m in ms
                 if m["num_episodes"] > 0]
        out = {
            "num_episodes": total,
            "episode_return_mean": float(np.mean(means)) if means else 0.0,
            "episode_return_max": max((m["episode_return_max"]
                                       for m in ms), default=0.0),
        }
        for k in ms[0]:
            if k.startswith("module/"):
                vs = [m[k] for m in ms if m["num_episodes"] > 0]
                out[k] = float(np.mean(vs)) if vs else 0.0
        return out

    def stop(self):
        for r in self.remote:
            try:
                rt.kill(r)
            except Exception:
                pass


# -------------------------------------------------------------- learners


class MultiLearnerGroup:
    """Per-module LearnerGroups under one state surface, so the base
    Algorithm's checkpoint path works unchanged (reference
    ``learner_group.py`` holding a MultiRLModule; here each module keeps
    its own jitted program — no ragged multi-module batches)."""

    def __init__(self, groups: Dict[str, LearnerGroup],
                 policies_to_train: Optional[List[str]] = None):
        self.groups = groups
        self.policies_to_train = (list(policies_to_train)
                                  if policies_to_train is not None
                                  else sorted(groups))

    def update_module(self, mid: str, batch, **kw) -> Dict[str, float]:
        return self.groups[mid].update(batch, **kw)

    def get_weights(self) -> Dict[str, Any]:
        return {mid: g.get_weights() for mid, g in self.groups.items()}

    def get_state(self) -> Dict[str, Any]:
        return {mid: g.get_state() for mid, g in self.groups.items()}

    def set_state(self, state: Dict[str, Any]):
        for mid, st in state.items():
            if mid in self.groups:
                self.groups[mid].set_state(st)

    def stop(self):
        for g in self.groups.values():
            g.stop()


# ------------------------------------------------------------- algorithm


class MultiAgentPPO(Algorithm):
    """PPO over a MultiRLModule: per-module GAE on per-stream segments,
    then each trainable module's clipped-surrogate update on its own
    jitted learner (reference new-stack multi-agent PPO:
    ``ppo.py`` + ``multi_agent_env_runner.py``). Discrete actions.

    Built from a PPOConfig with ``.multi_agent(...)`` set."""

    def _make_module_spec(self, config) -> Dict[str, RLModuleSpec]:
        policies = config.policies
        mapping = config.policy_mapping_fn or (
            lambda aid, env_idx: str(aid))
        items = (policies.items() if isinstance(policies, dict)
                 else [(pid, None) for pid in policies])
        need_env = any(not isinstance(s, RLModuleSpec) for _, s in items)
        env = config.make_env_creator()() if need_env else None
        specs: Dict[str, RLModuleSpec] = {}
        try:
            for pid, spec in items:
                if not isinstance(spec, RLModuleSpec):
                    agents = [a for a in env.possible_agents
                              if mapping(a, 0) == pid]
                    if not agents:
                        raise ValueError(
                            f"no agent in possible_agents maps to module "
                            f"{pid!r}; pass an explicit RLModuleSpec")
                    a = agents[0]
                    spec = spec_from_spaces(
                        env.observation_spaces[a], env.action_spaces[a],
                        config.hidden)
                if spec.continuous:  # explicit AND inferred specs
                    raise NotImplementedError(
                        f"module {pid!r} has a continuous action space; "
                        f"MultiAgentPPO trains discrete actions only")
                specs[pid] = spec
        finally:
            if env is not None:
                env.close()
        return specs

    def _build_env_runner_group(self):
        config = self.config
        return MultiAgentEnvRunnerGroup(
            config.make_env_creator(), self.module_spec,
            policy_mapping_fn=config.policy_mapping_fn,
            num_env_runners=config.num_env_runners,
            num_envs_per_runner=config.num_envs_per_runner,
            rollout_fragment_length=config.rollout_fragment_length,
            seed=config.seed)

    def _build_learner_group(self) -> MultiLearnerGroup:
        cfg = self.config

        def factory_for(mid):
            spec = self.module_spec[mid]

            def factory():
                return PPOLearner(
                    spec, lr=cfg.lr, clip_param=cfg.clip_param,
                    vf_coeff=cfg.vf_coeff,
                    entropy_coeff=cfg.entropy_coeff,
                    grad_clip=cfg.grad_clip, mesh=cfg.mesh, seed=cfg.seed)

            return factory

        groups = {mid: LearnerGroup(factory_for(mid),
                                    num_learners=cfg.num_learners)
                  for mid in self.module_spec}
        return MultiLearnerGroup(groups, cfg.policies_to_train)

    def training_step(self) -> Dict[str, Any]:
        cfg = self.config
        # 1. sample until train_batch_size TOTAL env steps (summed over
        #    modules — one env step yields one transition per acting
        #    agent, the reference's count_steps_by="env_steps" analog)
        per_module: Dict[str, List[SampleBatch]] = {}
        collected = 0
        while collected < cfg.train_batch_size:
            for batches in self.env_runner_group.sample():
                for mid, b in batches.items():
                    per_module.setdefault(mid, []).append(b)
                    collected += len(b)
        self._timesteps += collected

        # 2. per-module GAE over each contiguous stream segment
        metrics: Dict[str, Any] = {}
        for mid in self.learner_group.policies_to_train:
            frags = per_module.get(mid)
            if not frags:
                continue
            cols = {k: [] for k in ("obs", "actions", "logp_old",
                                    "advantages", "value_targets")}
            for frag in frags:
                lo = 0
                for ln in frag["_streams"]:
                    ln = int(ln)
                    sl = slice(lo, lo + ln)
                    lo += ln
                    adv, vtarg = compute_gae(
                        frag["rewards"][sl], frag["values"][sl],
                        frag["next_values"][sl], frag["dones"][sl],
                        frag["truncateds"][sl], np.array([ln, 1]),
                        gamma=cfg.gamma, lam=cfg.lam)
                    cols["obs"].append(frag["obs"][sl])
                    cols["actions"].append(frag["actions"][sl])
                    cols["logp_old"].append(frag["logp"][sl])
                    cols["advantages"].append(adv)
                    cols["value_targets"].append(vtarg)
            train_batch = {k: np.concatenate(v).astype(
                np.int64 if k == "actions" else np.float32)
                for k, v in cols.items()}
            m = self.learner_group.update_module(
                mid, train_batch, minibatch_size=cfg.minibatch_size,
                num_epochs=cfg.num_epochs, shuffle_seed=self.iteration)
            for k, v in m.items():
                metrics[f"module/{mid}/{k}"] = v

        # 3. broadcast fresh weights
        self.env_runner_group.sync_weights(self.learner_group.get_weights())
        metrics["num_env_steps_trained"] = collected
        return metrics
