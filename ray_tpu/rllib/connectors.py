"""Connectors V2: composable env↔module transform pipelines.

Capability parity with the reference's connector framework
(reference: ``rllib/connectors/connector_v2.py`` + the default
env-to-module pipeline in ``single_agent_env_runner.py``): small pure
transforms chained into a pipeline the env runner applies to raw
observations before module inference. State (e.g. frame stacks) lives in
the connector, keyed by vector-env slot.
"""
from __future__ import annotations

from typing import List, Tuple

import numpy as np


class ConnectorV2:
    """One transform; ``__call__(obs [N, ...], slots) -> obs [N, ...]``.

    ``slots`` names the vector-env slot of each row (stateful connectors
    key their per-episode state on it); None means ``range(N)``.
    """

    def __call__(self, obs: np.ndarray, slots=None) -> np.ndarray:
        raise NotImplementedError

    def reset(self, slot: int) -> None:
        """Episode boundary for one vector-env slot (stateful connectors)."""

    def out_shape(self, in_shape) -> tuple:
        """Probe the post-transform observation shape."""
        probe = np.zeros((1,) + tuple(in_shape), np.float32)
        shape = tuple(self(probe).shape[1:])
        self.reset(0)  # drop any state the probe created in slot 0
        return shape


class FlattenObs(ConnectorV2):
    def __call__(self, obs, slots=None):
        return np.asarray(obs, np.float32).reshape(len(obs), -1)


class NormalizeObs(ConnectorV2):
    """Fixed affine normalization (e.g. uint8 images → [0, 1])."""

    def __init__(self, scale: float = 1.0, offset: float = 0.0):
        self.scale = scale
        self.offset = offset

    def __call__(self, obs, slots=None):
        return (np.asarray(obs, np.float32) - self.offset) * self.scale


class FrameStack(ConnectorV2):
    """Stack the last k frames along the channel axis ([N,H,W,C*k])."""

    def __init__(self, k: int = 4):
        self.k = k
        self._stacks: dict = {}

    def __call__(self, obs, slots=None):
        obs = np.asarray(obs, np.float32)
        slots = range(len(obs)) if slots is None else slots
        out = []
        for i, frame in zip(slots, obs):
            stack = self._stacks.get(i)
            if stack is None:
                stack = [frame] * self.k
            else:
                stack = stack[1:] + [frame]
            self._stacks[i] = stack
            out.append(np.concatenate(stack, axis=-1))
        return np.stack(out)

    def reset(self, slot: int):
        self._stacks.pop(slot, None)


class ConnectorPipeline(ConnectorV2):
    def __init__(self, connectors: List[ConnectorV2]):
        self.connectors = list(connectors)

    def __call__(self, obs, slots=None):
        for c in self.connectors:
            obs = c(obs, slots)
        return obs

    def reset(self, slot: int):
        for c in self.connectors:
            c.reset(slot)
